#!/usr/bin/env bash
# The chip in this image comes and goes (wedged for most of rounds 1-2).
# This watcher probes it on a cadence and, whenever it is alive, burns down
# a queue of hardware jobs exactly once each, logging to tpu_results/.
# Safe to re-run: finished jobs leave a .done stamp and are skipped.
#
# Round-3 change (VERDICT #1): each finished job COMMITS its artifacts
# immediately — a mid-round capture must survive a wedged round-end.

set -u
cd "$(dirname "$0")/.."
mkdir -p tpu_results

probe() {
  timeout 150 python -c "
import jax
d = jax.devices()
assert d and d[0].platform != 'cpu', d
print('alive:', d)
" >/dev/null 2>&1
}

commit_results() {  # $1 = job name; commit ONLY the hardware artifacts
  local name="$1" err=""
  for attempt in 1 2 3; do
    if err=$(git add -A tpu_results BENCH_TPU_CACHE.json 2>&1 \
       && git commit -q -m "tpu: ${name} results captured" \
            -- tpu_results BENCH_TPU_CACHE.json 2>&1); then
      echo "[opportunist] $(date -u +%H:%M:%S) $name committed" >> tpu_results/watcher.log
      return 0
    fi
    sleep 7  # index.lock contention with the builder's own commits
  done
  echo "[opportunist] $(date -u +%H:%M:%S) $name commit FAILED: ${err}" >> tpu_results/watcher.log
  return 1
}

run_job() {  # $1 = name, $2... = command
  local name="$1"; shift
  [ -f "tpu_results/$name.done" ] && return 0
  # bounded retries: transient wedges deserve another shot, but a
  # deterministic failure must not spam a commit per probe cycle forever
  local fails=0
  [ -f "tpu_results/$name.failcount" ] && fails=$(cat "tpu_results/$name.failcount")
  if [ "$fails" -ge "${MAX_JOB_FAILS:-3}" ]; then
    return 1
  fi
  echo "[opportunist] $(date -u +%H:%M:%S) running $name" >> tpu_results/watcher.log
  if timeout "${JOB_TIMEOUT:-3600}" "$@" > "tpu_results/$name.out" 2> "tpu_results/$name.err"; then
    touch "tpu_results/$name.done"
    echo "[opportunist] $(date -u +%H:%M:%S) $name OK" >> tpu_results/watcher.log
    commit_results "$name" || true
  else
    echo "[opportunist] $(date -u +%H:%M:%S) $name FAILED rc=$?" >> tpu_results/watcher.log
    # attribute the failure: if the chip is dead right now, the job almost
    # certainly died of the wedge, not of its own bug — such failures must
    # not burn the bounded retry budget (wedges dominate this image)
    if probe; then
      echo $((fails + 1)) > "tpu_results/$name.failcount"
    else
      echo "[opportunist] $(date -u +%H:%M:%S) $name failure attributed to chip wedge; retry budget not charged" >> tpu_results/watcher.log
    fi
    # raw .err streams are gitignored (can be huge); commit a bounded tail
    # so the failure diagnostics survive a wedged round-end too
    tail -c 100000 "tpu_results/$name.err" > "tpu_results/$name.err.tail" 2>/dev/null
    commit_results "$name-failed" || true
    return 1
  fi
}

all_done() {
  local f
  for j in bench_tinyllama profile_attn bench_llama8b bench_llama8b_int4 tpu_lane; do
    [ -f "tpu_results/$j.done" ] && continue
    f=0; [ -f "tpu_results/$j.failcount" ] && f=$(cat "tpu_results/$j.failcount")
    [ "$f" -ge "${MAX_JOB_FAILS:-3}" ] && continue
    return 1
  done
  return 0
}

while ! all_done; do
  if probe; then
    echo "[opportunist] $(date -u +%H:%M:%S) chip alive" >> tpu_results/watcher.log
    # profile FIRST: it writes + installs the attention-impl verdict
    # (tpu_results/ATTN_PROFILE.json + ~/.cache), so the benches below run
    # with attention_impl="auto" resolved on evidence — the Pallas flip is
    # automatic on the first live window (VERDICT r4 decision procedure)
    run_job profile_attn python scripts/profile_attention.py --config both \
      --out tpu_results/ATTN_PROFILE.json --install || true
    probe || continue
    run_job bench_tinyllama python bench.py || true
    probe || continue
    JOB_TIMEOUT=4800 run_job bench_llama8b env CALFKIT_BENCH_CONFIG=llama8b python bench.py || true
    probe || continue
    JOB_TIMEOUT=4800 run_job bench_llama8b_int4 env CALFKIT_BENCH_CONFIG=llama8b_int4 python bench.py || true
    probe || continue
    run_job tpu_lane env CALFKIT_TESTS_TPU=1 python -m pytest -q || true
  else
    echo "[opportunist] $(date -u +%H:%M:%S) chip wedged" >> tpu_results/watcher.log
  fi
  all_done && break
  sleep "${PROBE_INTERVAL:-300}"
done
# distinguish captured vs gave-up in the terminal record
summary=""
for j in bench_tinyllama profile_attn bench_llama8b bench_llama8b_int4 tpu_lane; do
  if [ -f "tpu_results/$j.done" ]; then summary="$summary $j=done"
  else summary="$summary $j=gave-up"; fi
done
echo "[opportunist] $(date -u +%H:%M:%S) queue finished:$summary" >> tpu_results/watcher.log
