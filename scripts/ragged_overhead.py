"""Ragged unified prefill+decode waves A/B (ISSUE 6 acceptance artifact).

Deterministic fixed-latency device-stub comparison of the BIFURCATED
schedule (admission chunks and decode waves as separate device
invocations — ``ragged_waves=False``) against the RAGGED unified lane
(one fused invocation carrying decode rows AND the inflight wave's next
prefill chunk), holding the workload and the simulated device constant:

- every jit boundary (decode / chunk / fused / finalize) is replaced by
  a host stub; each *invocation* occupies the serialized device for
  ``DEVICE_MS`` (dispatches queue behind each other, like a real
  accelerator stream) and token blocks become host-readable only when
  the device would have finished them (the ``_sync_host`` →
  ``np.asarray`` block, exactly like OVERLAP.json's stub);
- the workload is MIXED prefill+decode by construction: multi-chunk
  prompts arriving faster than they drain, short decode tails — the
  shape where the round-5 TPU bench measured mean_batch_occupancy 0.365
  (two thirds of every decode dispatch idle).

Reported per mode: mean batch occupancy (absorbed prefill rows count as
dispatch participants — the point of the unified wave), total device
invocations and invocations-per-request, host us per invocation, and
prefill tokens absorbed.  Exits non-zero unless the ragged lane's
occupancy is at least ``OCCUPANCY_BAR``x the bifurcated baseline, it
uses strictly fewer invocations per request, and both modes served every
request in full (token-count parity; stream-content parity is pinned by
tests/test_ragged_waves.py against the real model).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from scripts._stub_common import (  # noqa: E402
    stub_prefill_lens,
    stub_retire_block,
)

BS = 8
STEPS = 8
CHUNK = 32
PROMPT_CHUNKS = 4  # 4-chunk prompts: admission dominates the mix
NEW_TOKENS = 16  # two decode dispatches per request
REQUESTS = 32
WAVE = 4  # max_prefill_wave: half the batch prefills while half decodes
DEVICE_MS = 4.0  # per INVOCATION — the fused dispatch pays it once
OCCUPANCY_BAR = 1.5  # ragged occupancy must beat bifurcated by this factor


class _DeviceSim:
    """A serialized fixed-latency device (see scripts/overlap_overhead.py):
    each invocation starts at max(now, previous ready time) and finishes
    ``latency_s`` later."""

    def __init__(self, latency_s: float):
        self.latency_s = latency_s
        self.busy_until: float | None = None
        self.idle_s = 0.0
        self.dispatches = 0

    def launch(self) -> float:
        now = time.perf_counter()
        if self.busy_until is not None:
            self.idle_s += max(0.0, now - self.busy_until)
        start = max(now, self.busy_until or now)
        self.busy_until = start + self.latency_s
        self.dispatches += 1
        return self.busy_until


class _LazyBlock:
    """A token block readable at ``ready_at`` — ``np.asarray`` blocks
    like a real device_get."""

    def __init__(self, arr: np.ndarray, ready_at: float):
        self._arr = arr
        self._ready_at = ready_at

    def __array__(self, dtype=None, copy=None):
        delay = self._ready_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        return self._arr if dtype is None else self._arr.astype(dtype)

    @property
    def T(self):
        return np.asarray(self).T


def _stub_jits(engine: InferenceEngine, sim: _DeviceSim) -> None:
    """Stub every jit boundary the chunked/ragged scheduler crosses.
    Decode math mirrors the device retirement contract via
    scripts/_stub_common; the chunk/finalize stubs mirror the scratch /
    lens-scatter contracts."""

    def decode_outputs(k, v, last, lens, active, done_prev, hard_end, steps):
        ready_at = sim.launch()
        toks = np.ones((steps, BS), np.int32)
        _act, n_valid, done, new_lens = stub_retire_block(
            active, done_prev, lens, hard_end, steps
        )
        return k, v, last, new_lens, _LazyBlock(toks, ready_at), n_valid, done

    def fake_decode(window: int, steps: int | None = None, sampled: bool = False):
        steps = steps or engine.runtime.decode_steps_per_dispatch

        def run(params, k, v, last, lens, active, done_prev, _stop,
                hard_end, *rest):
            return decode_outputs(
                k, v, last, lens, active, done_prev, hard_end, steps
            )

        return run

    def fake_chunk(chunk: int, rows: int):
        def run(params, sk, sv, tokens_chunk, offset):
            sim.launch()  # a bifurcated chunk is its own device invocation
            return sk, sv, np.ones((rows, chunk, 8), np.float32)

        return run

    def fake_ragged(window: int, steps: int, sampled: bool,
                    chunk: int, rows: int):
        def run(params, k, v, last, lens, active, done_prev, _stop,
                hard_end, keys, temp, tk, tp, sk, sv, tokens_chunk, offset):
            # ONE invocation covers decode AND the chunk — the fused lane
            out = decode_outputs(
                k, v, last, lens, active, done_prev, hard_end, steps
            )
            return (*out, sk, sv, np.ones((rows, chunk, 8), np.float32))

        return run

    def fake_finalize(bucket: int, rows: int, sampled: bool):
        def run(k, v, sk, sv, last, lens, slots, true_lens, logits,
                *rest, tables=None, page_rows=None, scatter_ids=None):
            sim.launch()  # the wave landing is one invocation in BOTH modes
            firsts = np.ones((rows,), np.int32)
            lens = stub_prefill_lens(lens, slots, true_lens)
            return k, v, tables, last, lens, *rest[:4], firsts

        return run

    engine._decode_jit = fake_decode
    engine._chunk_jit = fake_chunk
    engine._ragged_jit = fake_ragged
    engine._finalize_jit = fake_finalize


async def measure(ragged: bool) -> dict:
    config = preset("debug", max_seq_len=256)
    runtime = RuntimeConfig(
        max_batch_size=BS, max_seq_len=256, prefill_chunk=CHUNK,
        decode_steps_per_dispatch=STEPS, chunked_prefill=True,
        max_prefill_wave=WAVE, ragged_waves=ragged,
    )
    engine = InferenceEngine(config, runtime)
    sim = _DeviceSim(DEVICE_MS / 1000.0)
    _stub_jits(engine, sim)
    await engine.start()

    prompt_len = CHUNK * PROMPT_CHUNKS - 3  # straddles the last chunk

    async def one(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            [1 + (i % 50), *range(2, prompt_len)], max_new_tokens=NEW_TOKENS
        ):
            n += 1
        return n

    t0 = time.perf_counter()
    counts = await asyncio.gather(*[one(i) for i in range(REQUESTS)])
    wall = time.perf_counter() - t0
    await engine.stop()
    assert all(c == NEW_TOKENS for c in counts), "stub served wrong lengths"

    stats = engine.stats
    host_us = max(0.0, wall - sim.dispatches * DEVICE_MS / 1000.0)
    return {
        "ragged_waves": ragged,
        "mean_batch_occupancy": round(stats.mean_occupancy, 4),
        "decode_dispatches": stats.decode_dispatches,
        "device_invocations": sim.dispatches,
        "invocations_per_request": round(sim.dispatches / REQUESTS, 3),
        "host_us_per_invocation": round(host_us / sim.dispatches * 1e6, 1),
        "prefill_absorbed_tokens": stats.prefill_absorbed_tokens,
        "unified_dispatches": stats.unified_dispatches,
        "tokens_per_dispatch": round(stats.mean_tokens_per_dispatch, 2),
        "tokens": int(stats.decode_tokens),
        "wall_s": round(wall, 3),
    }


async def run() -> dict:
    bifurcated = await measure(ragged=False)
    unified = await measure(ragged=True)
    ratio = unified["mean_batch_occupancy"] / max(
        bifurcated["mean_batch_occupancy"], 1e-9
    )
    ok = (
        ratio >= OCCUPANCY_BAR
        and unified["invocations_per_request"]
        < bifurcated["invocations_per_request"]
        and unified["prefill_absorbed_tokens"] > 0
        and unified["tokens"] == bifurcated["tokens"]
    )
    return {
        "metric": "ragged_unified_waves_ab[fixed-latency device stub, "
        "mixed prefill+decode]",
        "value": round(ratio, 2),
        "unit": "x mean batch occupancy (ragged/bifurcated)",
        "bar": OCCUPANCY_BAR,
        "ok": ok,
        "bifurcated": bifurcated,
        "ragged": unified,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
