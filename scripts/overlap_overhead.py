"""Overlapped-execution A/B microbench (ISSUE 3 acceptance artifact).

Measures the INTER-DISPATCH DEVICE-IDLE BUBBLE with a fixed-latency
device stub, overlap on vs off, holding everything else constant:

- every decode jit is replaced by a host stub whose token block is a
  lazy array that becomes readable ``DEVICE_MS`` after the moment the
  dispatch would have *started* on a serialized device (dispatches queue
  behind each other, like a real accelerator stream);
- launches are instant (JAX async dispatch); the engine's single
  designated sync point (``_sync_host`` → ``np.asarray``) blocks until
  the lazy block's ready time — exactly how a real host blocks on
  ``device_get``;
- the stub records, at every launch, how long the simulated device sat
  idle since its previous dispatch finished.  That idle-per-dispatch is
  THE number double buffering exists to erase: in lockstep mode it is
  the host's whole fan-out + scheduler + admission turnaround; with
  overlap on, dispatch N+1 is enqueued before N's sync, so the device
  goes straight from N to N+1.

Prints one JSON line (written to OVERLAP.json via --out); exits non-zero
unless overlap reclaims the bubble by at least ``RECLAIM_BAR``x and the
wasted-token tax stays within the one-dispatch-late bound
(retired rows x steps_per_dispatch).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from scripts._stub_common import (  # noqa: E402
    stub_prefill_lens,
    stub_retire_block,
)

BS = 16
STEPS = 8
NEW_TOKENS = 64
# simulated device time per decode dispatch — sized so one dispatch
# comfortably covers the host's per-tick bookkeeping (the overlap claim
# is "host hides under device", so the stub device must be at least as
# slow as the host is; a real 8B dispatch is O(10-100 ms))
DEVICE_MS = 8.0
RECLAIM_BAR = 5.0  # overlap must shrink idle/dispatch by at least this


class _DeviceSim:
    """A serialized fixed-latency device: dispatches start at
    max(now, previous ready time) and finish ``latency_s`` later.  Idle
    is accumulated at launch — the span the device spent waiting for the
    host between dispatches."""

    def __init__(self, latency_s: float):
        self.latency_s = latency_s
        self.busy_until: float | None = None
        self.idle_s = 0.0
        self.dispatches = 0

    def launch(self) -> float:
        now = time.perf_counter()
        if self.busy_until is not None:
            self.idle_s += max(0.0, now - self.busy_until)
        start = max(now, self.busy_until or now)
        self.busy_until = start + self.latency_s
        self.dispatches += 1
        return self.busy_until


class _LazyBlock:
    """A token block that becomes host-readable at ``ready_at`` — the
    engine's ``np.asarray`` sync blocks exactly like a real device_get."""

    def __init__(self, arr: np.ndarray, ready_at: float):
        self._arr = arr
        self._ready_at = ready_at

    def __array__(self, dtype=None, copy=None):
        delay = self._ready_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        return self._arr if dtype is None else self._arr.astype(dtype)

    @property
    def T(self):  # the lockstep fan-out transposes the synced block
        return np.asarray(self).T


def _stub_jits(engine: InferenceEngine, sim: _DeviceSim) -> None:
    def fake_decode(window: int, steps: int | None = None, sampled: bool = False):
        steps = steps or engine.runtime.decode_steps_per_dispatch

        def run(params, k, v, last, lens, active, done_prev, _stop,
                hard_end, *rest):
            ready_at = sim.launch()
            toks = np.ones((steps, BS), np.int32)
            _act, n_valid, done, new_lens = stub_retire_block(
                active, done_prev, lens, hard_end, steps
            )
            return (
                k, v, last, new_lens,
                _LazyBlock(toks, ready_at), n_valid, done,
            )

        return run

    def fake_prefill_jit(bucket: int, rows: int, sampled: bool = False):
        def run(params, k, v, last, lens, tokens, slots, true_lens,
                *rest, tables=None, page_rows=None, scatter_ids=None):
            firsts = jnp.ones((rows,), jnp.int32)
            lens = stub_prefill_lens(lens, slots, true_lens)
            return k, v, tables, last, lens, *rest[:4], firsts

        return run

    engine._decode_jit = fake_decode
    engine._prefill_jit = fake_prefill_jit


async def measure(overlap: bool) -> dict:
    config = preset("debug", max_seq_len=256)
    runtime = RuntimeConfig(
        max_batch_size=BS, max_seq_len=256, prefill_chunk=32,
        decode_steps_per_dispatch=STEPS, overlap_dispatch=overlap,
    )
    engine = InferenceEngine(config, runtime)
    sim = _DeviceSim(DEVICE_MS / 1000.0)
    _stub_jits(engine, sim)
    await engine.start()

    async def one(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            [1 + (i % 50), 3, 5], max_new_tokens=NEW_TOKENS
        ):
            n += 1
        return n

    # ONE generation (requests == slots): the measurement targets the
    # steady-state inter-dispatch bubble; a batch turnover drains the
    # whole pipeline and its admission idle is identical in both modes,
    # diluting the A/B signal without informing it
    t0 = time.perf_counter()
    counts = await asyncio.gather(*[one(i) for i in range(BS)])
    wall = time.perf_counter() - t0
    await engine.stop()
    assert all(c == NEW_TOKENS for c in counts), "stub served wrong lengths"

    retired = BS
    idle_us = sim.idle_s / max(1, sim.dispatches - 1) * 1e6
    return {
        "overlap_dispatch": overlap,
        "dispatches": sim.dispatches,
        "device_ms_per_dispatch": DEVICE_MS,
        "idle_us_per_dispatch": round(idle_us, 1),
        "device_idle_s": round(sim.idle_s, 4),
        "wasted_tokens": engine.stats.overlap_wasted_tokens,
        "wasted_bound": retired * STEPS,
        "wall_s": round(wall, 3),
        "tokens": int(engine.stats.decode_tokens),
    }


async def run() -> dict:
    lockstep = await measure(overlap=False)
    overlap = await measure(overlap=True)
    # 1 us floor on the denominator: overlap routinely measures EXACTLY
    # zero idle (every launch found the device busy), and idle/0 would
    # print as a meaningless astronomical ratio
    reclaim = (
        lockstep["idle_us_per_dispatch"]
        / max(overlap["idle_us_per_dispatch"], 1.0)
    )
    ok = (
        reclaim >= RECLAIM_BAR
        and overlap["wasted_tokens"] <= overlap["wasted_bound"]
        and lockstep["wasted_tokens"] == 0
    )
    return {
        "metric": "overlap_dispatch_ab[fixed-latency device stub]",
        "value": round(reclaim, 1),
        "unit": "x idle reclaimed (lockstep/overlap, per dispatch)",
        "bar": RECLAIM_BAR,
        "ok": ok,
        "lockstep": lockstep,
        "overlap": overlap,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
