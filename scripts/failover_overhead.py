"""Failure-recovery A/B microbench (ISSUE 9 acceptance artifact).

Kill-mid-run on the REAL mesh → worker → engine path: an in-memory mesh,
two Workers each hosting a replica of one agent over a REAL debug
inference engine, a fleet-routed Client — then one replica is
HARD-KILLED (FleetTopology's process-death seam: publishes vanish,
consumption freezes, heartbeats stop, no drain) while its runs are
mid-generation.

Two arms, identical workload and kill:

- **failover on** — the client supervises each placement
  (``FailoverPolicy``): the dead placement is detected when the corpse's
  heartbeat lapses ``stale_after``, the orphaned correlation is
  cancel-tombstoned, and the call re-dispatches to the survivor under
  the REMAINING deadline.  Every request completes; the headline number
  is the worst time-to-recover (kill → terminal) against the caller
  deadline.
- **failover off** — the pre-ISSUE-9 behavior: the victim's runs have no
  supervisor, so each burns its ENTIRE caller deadline and dies with
  ClientTimeoutError; only the survivor's share completes.

Prints one JSON line (written to FAILOVER.json via --out); exits
non-zero unless the failover arm completes EVERY request with worst
recovery under half the caller deadline AND the baseline arm loses the
victim's runs.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.client import Client  # noqa: E402
from calfkit_tpu.exceptions import ClientTimeoutError  # noqa: E402
from calfkit_tpu.fleet import FailoverPolicy, FleetRouter  # noqa: E402
from calfkit_tpu.inference import model as M  # noqa: E402
from calfkit_tpu.inference.client import JaxLocalModelClient  # noqa: E402
from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from tests._chaos import FleetTopology  # noqa: E402 - the process-death seam

AGENT = "svc"
OFFERED = 4  # requests in flight when the replica dies
NEW_TOKENS = 24
DEADLINE_S = 8.0  # the caller budget recovery is measured against
HEARTBEAT_S = 0.05
STALE_MULT = 6.0  # stale_after = 0.3s: the detection floor
PACE_S = 0.03  # per-dispatch pacing so the kill lands mid-generation
RECOVERY_BAR_FRACTION = 0.5  # worst recover must be < deadline/2

CFG = preset("debug")
PARAMS = M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engines(n: int):
    engines, models = [], []
    for _ in range(n):
        runtime = RuntimeConfig(
            max_batch_size=4, max_seq_len=128, prefill_chunk=16,
            decode_steps_per_dispatch=4, page_size=16,
        )
        engine = InferenceEngine(CFG, runtime, params=PARAMS)
        engines.append(engine)
        models.append(
            JaxLocalModelClient(
                config=CFG, runtime=runtime, engine=engine,
                max_new_tokens=NEW_TOKENS,
            )
        )
    return engines, models


async def _until(condition, *, seconds: float = 20.0, what: str = "") -> None:
    deadline = time.perf_counter() + seconds
    while not condition():
        if time.perf_counter() > deadline:
            raise RuntimeError(f"never settled: {what}")
        await asyncio.sleep(0.01)


async def measure(failover_on: bool) -> dict:
    engines, models = _engines(2)
    mesh = InMemoryMesh()
    fleet = FleetTopology(
        mesh, models, name=AGENT,
        heartbeat_interval=HEARTBEAT_S, stale_multiplier=STALE_MULT,
    )
    async with fleet:
        # pace BOTH engines so the victim's runs are still decoding when
        # the kill lands (and the arms stay symmetric)
        def pace(point):
            if point == "dispatch":
                time.sleep(PACE_S)

        for engine in engines:
            engine._chaos = pace
        router = FleetRouter(
            mesh, "least-loaded", stale_after=fleet.config.stale_after
        )
        client = Client.connect(
            mesh,
            router=router,
            failover=(
                FailoverPolicy(probe_interval=0.05, max_failovers=2)
                if failover_on else None
            ),
        )
        await router.start()
        await _until(
            lambda: len(router.registry.eligible(AGENT)) == 2,
            what="both replicas eligible",
        )
        victim = fleet.index_of_lowest_key()

        # warm BOTH engines first (one run each, placed round-robin by
        # least-loaded) so the measured window contains serving and
        # recovery, not first-use XLA compilation — a cold survivor
        # would bill multi-second jit builds to the failover path
        warm = [
            asyncio.create_task(
                client.agent(AGENT).execute(
                    f"request {i}: payload", timeout=60.0
                )
            )
            for i in range(2)
        ]
        await asyncio.gather(*warm)

        done_at: dict[int, float] = {}
        outcomes: dict[int, str] = {}

        async def one(i: int):
            try:
                result = await client.agent(AGENT).execute(
                    f"request {i}: payload", timeout=DEADLINE_S
                )
                assert result.output is not None
                outcomes[i] = "ok"
            except ClientTimeoutError:
                outcomes[i] = "timeout"
            except Exception as exc:  # noqa: BLE001 - report, don't crash
                outcomes[i] = f"error:{type(exc).__name__}"
            done_at[i] = time.perf_counter()

        tasks = []
        for i in range(OFFERED):
            tasks.append(asyncio.create_task(one(i)))
            await asyncio.sleep(0.02)
        await _until(
            lambda: engines[victim]._active,
            what="the victim engine never had active work",
        )
        t_kill = time.perf_counter()
        fleet.kill(victim)
        await asyncio.gather(*tasks)

        completed = sum(1 for o in outcomes.values() if o == "ok")
        timeouts = sum(1 for o in outcomes.values() if o == "timeout")
        # requests finishing after the kill either recovered (failover)
        # or burned their deadline (baseline): their kill→terminal time
        # IS the recovery/failure latency
        post_kill_s = [
            round(done_at[i] - t_kill, 3)
            for i in range(OFFERED)
            if done_at[i] > t_kill
        ]
        out = {
            "failover": failover_on,
            "offered": OFFERED,
            "completed": completed,
            "timeouts": timeouts,
            "outcomes": sorted(outcomes.values()),
            "kill_to_terminal_s": sorted(post_kill_s),
            "worst_kill_to_terminal_s": max(post_kill_s) if post_kill_s else 0.0,
            "stale_after_s": fleet.config.stale_after,
            "survivor_failover_arrivals": (
                fleet.agents[1 - victim]._failover_requests
            ),
        }
        await client.close()
    for engine in engines:
        await engine.stop()
    await mesh.stop()
    return out


async def run() -> dict:
    on = await measure(True)
    off = await measure(False)
    worst = on["worst_kill_to_terminal_s"]
    ok = (
        on["completed"] == OFFERED
        and worst < DEADLINE_S * RECOVERY_BAR_FRACTION
        and on["survivor_failover_arrivals"] >= 1
        and off["completed"] < OFFERED
        and off["timeouts"] >= 1
    )
    return {
        "metric": "failover_ab[kill-mid-run, real mesh->worker->engine "
                  "path, 2 replicas, real debug engines, hard-kill via "
                  "the process-death seam]",
        "value": worst,
        "unit": "s worst kill->terminal with failover on (vs the "
                f"{DEADLINE_S}s caller deadline the baseline burns whole)",
        "deadline_s": DEADLINE_S,
        "recovery_bar_s": DEADLINE_S * RECOVERY_BAR_FRACTION,
        "ok": ok,
        "on": on,
        "off": off,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
