"""Shared device-stub math for the overhead microbenches.

The engine retires rows on the DEVICE's verdict (``sampler.retire_mask_slots``
inside the decode/verify jits), so every script that stubs the jit boundary
must mirror that contract or its engine never finishes a request.  One numpy
copy here instead of one per script — a change to the retirement semantics
updates a single reference implementation, and the committed artifacts
(SCHED_OVERHEAD_r*.json, OVERLAP.json, OBS_OVERHEAD.json, SPEC_DECODE.json)
cannot silently keep passing against a contract the engine dropped.

These benches configure no stop tokens, so only the hard-bound half of
``retire_mask_slots`` is mirrored (tests/test_overlap_dispatch.py pins the
full stop-token math against the real jnp implementation).
"""

from __future__ import annotations

import numpy as np


def stub_retire_block(
    active, done_prev, lens, hard_end, steps: int
) -> "tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]":
    """A plain decode dispatch's retirement verdict (no stop tokens):
    → (act, n_valid, done, new_lens), matching the jit contract — rows
    masked by ``done_prev`` freeze, live rows deliver up to the hard
    bound and retire when it falls inside the block."""
    act = np.asarray(active) & ~np.asarray(done_prev)
    lens = np.asarray(lens)
    bound = np.asarray(hard_end) - lens
    n_valid = np.where(act, np.clip(bound, 0, steps), 0).astype(np.int32)
    done = act & (bound <= steps)
    new_lens = np.where(act, lens + steps, lens).astype(np.int32)
    return act, n_valid, done, new_lens


def stub_retire_emitted(
    active, lens, hard_end, emitted
) -> "tuple[np.ndarray, np.ndarray]":
    """A verify (speculative) dispatch's verdict over per-row ragged
    ``emitted`` counts (no stop tokens): → (n_valid, done)."""
    act = np.asarray(active)
    bound = np.maximum(np.asarray(hard_end) - np.asarray(lens), 0)
    emitted = np.asarray(emitted)
    n_valid = np.minimum(emitted, bound).astype(np.int32)
    done = act & (bound <= emitted)
    return n_valid, done


def stub_prefill_lens(lens, slots, true_lens) -> np.ndarray:
    """The prefill jit scatters each wave row's true length into ``lens``;
    the decode stub's bound math reads it, so prefill stubs must mirror
    the scatter."""
    lens = np.asarray(lens).copy()
    lens[np.asarray(slots)] = np.asarray(true_lens)
    return lens
