"""Speculative-decoding host-stub microbench → SPEC_DECODE.json.

SCHED_OVERHEAD-style: the device is removed entirely — the engine's
prefill and VERIFY jits are replaced by shape-faithful host stubs whose
"target model" is a deterministic next-token rule — so what runs (and is
measured) is the REAL product scheduler: the n-gram drafter over real
request histories, wave formation, ragged per-row acceptance accounting,
length bookkeeping, retirement, and the token fan-out.  The stub's greedy
rule makes acceptance MEASURED, not faked: the drafter only scores when
its lookup genuinely predicts the rule's continuation from the history.

Two workloads:

- ``cyclic``: prompts seed a short deterministic cycle (period 8), the
  acceptance-friendly regime ISSUE 1 pins (agentic/tool-call traffic with
  repetitive structure).  Bar: **tokens_per_dispatch > 1.5** — each verify
  dispatch must amortize its would-be weight read over >1.5 tokens.
- ``adversarial``: the rule is position-dependent so history lookup can
  barely ever predict it; speculation must degrade gracefully toward ~1
  token/dispatch, never below (the correction token is unconditional).

Prints one JSON line; ``--out PATH`` writes the committed artifact.
Exits non-zero when a bar is violated.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.inference.config import (  # noqa: E402
    RuntimeConfig,
    SpecConfig,
    preset,
)
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from scripts._stub_common import stub_retire_emitted  # noqa: E402

K_SPEC = 4
NEW_TOKENS = 64
TPD_BAR = 1.5  # tokens per verify dispatch at acceptance-friendly settings


def _cyclic_next(token: int, pos: int) -> int:
    """Period-8 cycle over a tiny alphabet: once one period is in the
    history, n-gram lookup predicts every subsequent token."""
    return 100 + (token - 100 + 1) % 8


def _adversarial_next(token: int, pos: int) -> int:
    """Position-salted rule: the continuation after a repeated tail keeps
    changing, so lookup proposals are almost always rejected."""
    return 100 + (token * 31 + pos * 17 + 7) % 97


def _stub_jits(engine: InferenceEngine, bs: int, rule) -> None:
    """Replace the device path with host stubs running ``rule`` as the
    target model.  Stubs sit at the JIT boundary (same discipline as
    scripts/sched_overhead.py): all real host-side scheduler work still
    runs and is what gets measured."""
    import jax.numpy as jnp

    def fake_prefill_jit(bucket: int, rows: int, sampled: bool = False):
        def run(params, k, v, last, lens, tokens, slots, true_lens,
                slot_keys, temp, top_k, top_p,
                seeds, w_temp, w_top_k, w_top_p,
                tables=None, page_rows=None, scatter_ids=None):
            toks = np.asarray(tokens)
            tl = np.asarray(true_lens)
            sl = np.asarray(slots)
            firsts = np.array(
                [
                    rule(int(toks[r, tl[r] - 1]), int(tl[r]))
                    for r in range(rows)
                ],
                np.int32,
            )
            # the real jit scatters the wave's last/lens rows on device;
            # the verify stub reads them, so the stub must mirror that
            new_last = np.asarray(last).copy()
            new_lens = np.asarray(lens).copy()
            new_last[sl] = firsts
            new_lens[sl] = tl
            return (k, v, tables, jnp.asarray(new_last),
                    jnp.asarray(new_lens), slot_keys, temp, top_k,
                    top_p, jnp.asarray(firsts))

        return run

    def fake_verify_jit(window: int, S: int, sampled: bool = False):
        def run(params, k, v, *rest):
            if engine._paged:
                tables, last, lens, active, drafts, ndraft, _stop, hard_end, *_ = rest
            else:
                last, lens, active, drafts, ndraft, _stop, hard_end, *_ = rest
            last_np = np.asarray(last)
            lens_np = np.asarray(lens)
            act = np.asarray(active)
            dr = np.asarray(drafts)
            nd = np.asarray(ndraft)
            hard = np.asarray(hard_end)
            B = last_np.shape[0]
            out = np.zeros((B, S), np.int32)
            emitted = np.zeros((B,), np.int32)
            new_last = last_np.copy()
            new_lens = lens_np.copy()
            for b in range(B):
                if not act[b]:
                    continue
                cur = int(last_np[b])
                accepted = 0
                for j in range(S - 1):
                    target = rule(cur, int(lens_np[b]) + j)
                    if j < nd[b] and int(dr[b, j]) == target:
                        out[b, j] = target
                        cur = target
                        accepted += 1
                    else:
                        break
                # correction/bonus token at the first non-accepted position
                out[b, accepted] = rule(cur, int(lens_np[b]) + accepted)
                emitted[b] = accepted + 1
                new_last[b] = out[b, accepted]
                new_lens[b] += emitted[b]
            # the device-side retirement contract (no stop tokens in this
            # bench): deliver up to the hard bound, done when the block
            # reaches it — the engine's spec tick retires on THIS verdict
            n_valid, done = stub_retire_emitted(act, lens_np, hard, emitted)
            return (k, v, jnp.asarray(new_last), jnp.asarray(new_lens),
                    jnp.asarray(out), jnp.asarray(emitted),
                    jnp.asarray(n_valid), jnp.asarray(done))

        return run

    engine._prefill_jit = fake_prefill_jit
    engine._verify_jit = fake_verify_jit


async def measure(bs: int, workload: str) -> dict:
    rule = _cyclic_next if workload == "cyclic" else _adversarial_next
    config = preset("debug", max_seq_len=256)
    runtime = RuntimeConfig(
        max_batch_size=bs, max_seq_len=256, prefill_chunk=32,
        decode_steps_per_dispatch=32, kv_layout="paged", page_size=16,
        num_kv_pages=bs * 16 + 1,
        speculative=SpecConfig(k=K_SPEC),
    )
    engine = InferenceEngine(config, runtime)
    _stub_jits(engine, bs, rule)
    await engine.start()

    async def one(i: int) -> int:
        # two full cycle periods in the prompt: the drafter has the
        # pattern from token one
        start = 100 + (i % 8)
        prompt = [start]
        for p in range(17):
            prompt.append(rule(prompt[-1], p))
        n = 0
        async for _ in engine.generate(prompt, max_new_tokens=NEW_TOKENS):
            n += 1
        return n

    requests = 2 * bs
    t0 = time.perf_counter()
    counts = await asyncio.gather(*[one(i) for i in range(requests)])
    wall = time.perf_counter() - t0
    stats = engine.stats
    await engine.stop()
    assert all(c == NEW_TOKENS for c in counts), "stub served wrong lengths"
    return {
        "workload": workload,
        "bs": bs,
        "k": K_SPEC,
        "requests": requests,
        "decode_tokens": stats.decode_tokens,
        "verify_dispatches": stats.decode_dispatches,
        "tokens_per_dispatch": round(stats.tokens_per_dispatch, 3),
        "spec_proposed": stats.spec_proposed,
        "spec_accepted": stats.spec_accepted,
        "acceptance_rate": round(stats.acceptance_rate, 4),
        "host_us_per_token": round(
            wall / max(1, stats.decode_tokens) * 1e6, 2
        ),
        "wall_s": round(wall, 3),
    }


async def run() -> dict:
    runs = [
        await measure(16, "cyclic"),
        await measure(64, "cyclic"),
        await measure(16, "adversarial"),
    ]
    friendly = runs[1]
    adversarial = runs[2]
    ok = (
        friendly["tokens_per_dispatch"] > TPD_BAR
        and adversarial["tokens_per_dispatch"] >= 1.0
    )
    return {
        "metric": f"spec_decode[host-stub ngram k={K_SPEC} paged]",
        "value": friendly["tokens_per_dispatch"],
        "unit": "tok/dispatch",
        "acceptance_rate": friendly["acceptance_rate"],
        "bars": {
            "tokens_per_dispatch_cyclic": TPD_BAR,
            "tokens_per_dispatch_adversarial_floor": 1.0,
        },
        "ok": ok,
        "runs": runs,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
