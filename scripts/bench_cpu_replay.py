"""Round-stamped CPU-replay bench artifact (VERDICT r3 item 3).

Runs the REAL bench (same engine code path, same measurement window) on
CPU in two shapes — the smoke config and the tinyllama-architecture
``tinyllama_cpu`` config — and writes ``BENCH_CPU_r{N}.json`` at the repo
root.  This is the evidence that engine / measurement-window changes
actually moved, committed every round even when the chip is wedged; claims
like "occupancy 1.0 at 4x-bs windows" live here instead of in commit
messages.

Usage: python scripts/bench_cpu_replay.py --round 4
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _git, _last_json_line  # noqa: E402 - shared helpers


def _run_config(config: str, timeout_s: int) -> dict:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        CALFKIT_BENCH_CONFIG=config,
        CALFKIT_BENCH_INNER="1",  # skip the accelerator probe outright
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {"config": config, "error": f"timeout after {timeout_s}s"}
    result = _last_json_line(proc.stdout)
    if result is not None:
        result["config"] = config
        return result
    return {
        "config": config,
        "error": f"no JSON line (rc={proc.returncode}): "
                 f"{(proc.stdout + proc.stderr)[-400:]}",
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--round", type=int, required=True)
    parser.add_argument("--smoke-timeout", type=int, default=900)
    parser.add_argument("--tinyllama-timeout", type=int, default=2400)
    ns = parser.parse_args()

    artifact = {
        "kind": "cpu-replay",
        "round": ns.round,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": _git("rev-parse", "HEAD"),
        "runs": [
            _run_config("smoke", ns.smoke_timeout),
            _run_config("tinyllama_cpu", ns.tinyllama_timeout),
        ],
    }
    out = os.path.join(REPO, f"BENCH_CPU_r{ns.round:02d}.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({
        "artifact": os.path.basename(out),
        "ok": all("error" not in r for r in artifact["runs"]),
        "values": {
            r["config"]: r.get("value") for r in artifact["runs"]
        },
    }))


if __name__ == "__main__":
    main()
