"""Run the Llama-3-8B readiness dryrun and commit the evidence
(VERDICT r4 item 1 fallback: when the chip is wedged, prove the 8B TP=8
config cannot die on first contact).

Writes LLAMA8B_READY.json: {ok, wall_s, n_devices, budget | error}.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    started = time.time()
    proc = subprocess.run(
        [sys.executable, str(ROOT / "__graft_entry__.py"), "llama8b", str(n)],
        capture_output=True, text=True, timeout=1800, cwd=ROOT,
    )
    wall = round(time.time() - started, 1)
    out: dict = {"n_devices": n, "wall_s": wall, "ok": proc.returncode == 0}
    if proc.returncode == 0:
        lines = proc.stdout.strip().splitlines()
        out["stdout_tail"] = lines[-1:]
        # the budget the dryrun ACTUALLY asserted, not a re-derivation
        for line in lines:
            if line.startswith("BUDGET "):
                out["budget"] = json.loads(line.removeprefix("BUDGET "))
                break
    else:
        out["error"] = (proc.stderr or proc.stdout).strip()[-4000:]
    (ROOT / "LLAMA8B_READY.json").write_text(json.dumps(out, indent=1) + "\n")
    print(json.dumps({k: v for k, v in out.items() if k != "error"}))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
