"""Bounded-admission A/B microbench (ISSUE 5 acceptance artifact).

Offers 2x the engine's serving capacity (slots + the `max_pending`
queue bound) with a fixed-latency device stub, bounds ON vs OFF,
holding everything else constant, and measures the two numbers the
overload-protection tentpole promises:

- **queue-wait p99 stays bounded**: with `max_pending` set, a caller
  that is admitted waits AT MOST one queue-bound's worth of generations
  regardless of offered load — the excess is refused instead of queued.
  Without the bound, every extra caller stretches the tail: the same
  offered load roughly multiplies p99 queue-wait by the oversubscription
  factor (the silent queue-wait growth the PR exists to kill).
- **the shed path is O(1) and fast**: a refused submit raises its typed
  ``EngineOverloadedError`` in well under a millisecond, before ANY
  device work — shedding under pressure must itself be cheap.

Prints one JSON line (written to SHED.json via --out); exits non-zero
unless the bounded run's p99 queue-wait stays under the single-backlog
bar, the unbounded run's tail is demonstrably worse, and the shed path
meets the sub-millisecond bar.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.exceptions import EngineOverloadedError  # noqa: E402
from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from scripts._stub_common import (  # noqa: E402
    stub_prefill_lens,
    stub_retire_block,
)

BS = 8  # engine slots
STEPS = 8
NEW_TOKENS = 64
DEVICE_MS = 4.0  # simulated device time per decode dispatch
# capacity = BS active + BS queued (max_pending=BS); offer 2x that
OFFERED = 4 * BS
SHED_BAR_MS = 1.0  # a refusal must cost less than this
# an admitted caller's worst case with the bound: the whole admitted
# backlog (one slot-full generation) ahead of it, plus slack for host
# scheduling.  NOT scaled to offered load — that is the whole point.
GEN_MS = (NEW_TOKENS / STEPS) * DEVICE_MS
BOUNDED_P99_BAR_MS = 2.5 * GEN_MS


class _DeviceSim:
    """Serialized fixed-latency device (see overlap_overhead.py)."""

    def __init__(self, latency_s: float):
        self.latency_s = latency_s
        self.busy_until: float | None = None
        self.dispatches = 0

    def launch(self) -> float:
        now = time.perf_counter()
        start = max(now, self.busy_until or now)
        self.busy_until = start + self.latency_s
        self.dispatches += 1
        return self.busy_until


class _LazyBlock:
    """A token block readable at ``ready_at`` — the engine's sync blocks
    exactly like a real device_get."""

    def __init__(self, arr: np.ndarray, ready_at: float):
        self._arr = arr
        self._ready_at = ready_at

    def __array__(self, dtype=None, copy=None):
        delay = self._ready_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        return self._arr if dtype is None else self._arr.astype(dtype)

    @property
    def T(self):
        return np.asarray(self).T


def _stub_jits(engine: InferenceEngine, sim: _DeviceSim) -> None:
    def fake_decode(window: int, steps: int | None = None, sampled: bool = False):
        steps = steps or engine.runtime.decode_steps_per_dispatch

        def run(params, k, v, last, lens, active, done_prev, _stop,
                hard_end, *rest):
            ready_at = sim.launch()
            toks = np.ones((steps, BS), np.int32)
            _act, n_valid, done, new_lens = stub_retire_block(
                active, done_prev, lens, hard_end, steps
            )
            return (
                k, v, last, new_lens,
                _LazyBlock(toks, ready_at), n_valid, done,
            )

        return run

    def fake_prefill_jit(bucket: int, rows: int, sampled: bool = False):
        def run(params, k, v, last, lens, tokens, slots, true_lens,
                *rest, tables=None, page_rows=None, scatter_ids=None):
            firsts = jnp.ones((rows,), jnp.int32)
            lens = stub_prefill_lens(lens, slots, true_lens)
            return k, v, tables, last, lens, *rest[:4], firsts

        return run

    engine._decode_jit = fake_decode
    engine._prefill_jit = fake_prefill_jit


def _p(values: "list[float]", q: float) -> float:
    if not values:
        return 0.0
    return float(np.percentile(np.asarray(values), q))


async def measure(max_pending: int, batch_ratio: float = 0.0) -> dict:
    config = preset("debug", max_seq_len=256)
    runtime = RuntimeConfig(
        max_batch_size=BS, max_seq_len=256, prefill_chunk=32,
        decode_steps_per_dispatch=STEPS, overlap_dispatch=True,
        max_pending=max_pending,
    )
    engine = InferenceEngine(config, runtime)
    sim = _DeviceSim(DEVICE_MS / 1000.0)
    _stub_jits(engine, sim)
    await engine.start()

    # per-class capture (ISSUE 20): with batch_ratio > 0, every second
    # submit opts into the batch class — the aggregate keys keep the
    # single-class arms' shape, the per_class split is what the mixed
    # arm gates on
    queue_wait_ms: "dict[str, list[float]]" = {
        "interactive": [], "batch": [],
    }
    shed_ms: list[float] = []
    served = {"interactive": 0, "batch": 0}
    shed = {"interactive": 0, "batch": 0}

    async def one(i: int) -> None:
        cls = "batch" if batch_ratio > 0.0 and i % 2 == 1 else "interactive"
        t0 = time.perf_counter()
        stream = engine.generate(
            [1 + (i % 50), 3, 5], max_new_tokens=NEW_TOKENS, priority=cls
        )
        try:
            first = True
            n = 0
            async for _ in stream:
                if first:
                    queue_wait_ms[cls].append(
                        (time.perf_counter() - t0) * 1000.0
                    )
                    first = False
                n += 1
            assert n == NEW_TOKENS, f"stub served {n} tokens"
            served[cls] += 1
        except EngineOverloadedError:
            shed_ms.append((time.perf_counter() - t0) * 1000.0)
            shed[cls] += 1

    t0 = time.perf_counter()
    await asyncio.gather(*[one(i) for i in range(OFFERED)])
    wall = time.perf_counter() - t0
    await engine.stop()

    all_waits = queue_wait_ms["interactive"] + queue_wait_ms["batch"]
    result = {
        "max_pending": max_pending,
        "offered": OFFERED,
        "served": served["interactive"] + served["batch"],
        "shed": shed["interactive"] + shed["batch"],
        "queue_wait_p50_ms": round(_p(all_waits, 50), 1),
        "queue_wait_p99_ms": round(_p(all_waits, 99), 1),
        "shed_p99_ms": round(_p(shed_ms, 99), 3),
        "engine_shed_counter": engine.stats.shed_requests,
        "wall_s": round(wall, 3),
    }
    if batch_ratio > 0.0:
        result["per_class"] = {
            cls: {
                "served": served[cls],
                "shed": shed[cls],
                "queue_wait_p50_ms": round(_p(queue_wait_ms[cls], 50), 1),
                "queue_wait_p99_ms": round(_p(queue_wait_ms[cls], 99), 1),
            }
            for cls in ("interactive", "batch")
        }
        result["engine_class_sheds"] = {
            "interactive": engine.stats.interactive_shed,
            "batch": engine.stats.batch_shed,
        }
    return result


async def run() -> dict:
    bounded = await measure(max_pending=BS)
    unbounded = await measure(max_pending=0)
    # the mixed-class arm (ISSUE 20): same bound, same 2x offered load,
    # every second caller batch-class.  The QoS promise is that the
    # interactive TAIL rides the same single-backlog bar as the
    # single-class capture — priority shedding evicts queued batch work
    # for arriving interactive requests, so adding batch load must not
    # stretch interactive p99 — and that sheds land batch-first.
    mixed = await measure(max_pending=BS, batch_ratio=0.5)
    assert unbounded["shed"] == 0 and unbounded["served"] == OFFERED
    assert bounded["shed"] == bounded["engine_shed_counter"] > 0
    assert mixed["shed"] == mixed["engine_shed_counter"] > 0
    tail_growth = unbounded["queue_wait_p99_ms"] / max(
        bounded["queue_wait_p99_ms"], 1.0
    )
    mixed_interactive = mixed["per_class"]["interactive"]
    class_sheds = mixed["engine_class_sheds"]
    ok = (
        bounded["queue_wait_p99_ms"] <= BOUNDED_P99_BAR_MS
        and bounded["shed_p99_ms"] < SHED_BAR_MS
        and tail_growth >= 2.0
        # interactive tail under mixed load holds the SAME absolute bar
        # as the single-class bounded arm — no regression from sharing
        # the engine with batch-class callers
        and mixed_interactive["queue_wait_p99_ms"] <= BOUNDED_P99_BAR_MS
        # shed-order law: degradation lands batch-first (interactive
        # sheds only once no batch request was left to evict)
        and class_sheds["batch"] >= class_sheds["interactive"]
        and class_sheds["batch"] > 0
    )
    return {
        "metric": "bounded_admission_ab[fixed-latency device stub, "
                  "2x oversubscription]",
        "value": round(tail_growth, 1),
        "unit": "x p99 queue-wait growth without the bound",
        "bounded_p99_bar_ms": round(BOUNDED_P99_BAR_MS, 1),
        "shed_bar_ms": SHED_BAR_MS,
        "ok": ok,
        "bounded": bounded,
        "unbounded": unbounded,
        "mixed": mixed,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
