"""Top-level driver: parse -> infer -> rules -> report."""

from __future__ import annotations

from meshlint import infer, rules
from meshlint.callgraph import Project
from meshlint.config import Config
from meshlint.report import Report


def analyze(config: Config) -> Report:
    project = Project.build(config.root, config.scan)
    infer.infer_effects(project)
    violations = rules.run_rules(project, config)
    waived = sum(
        1
        for fn in project.functions.values()
        for site in fn.effects
        if site.waived
    ) + sum(
        1
        for mod in project.modules.values()
        for site in mod.module_effects
        if site.waived
    )
    stats = {
        "modules": len(project.modules),
        "functions": len(project.functions),
        "edges": sum(len(f.edges) for f in project.functions.values()),
        "roots": sum(1 for f in project.functions.values() if f.markers),
        "hotpath": sum(1 for f in project.functions.values()
                       if "hotpath" in f.markers),
        "no_wallclock": sum(1 for f in project.functions.values()
                            if "no_wallclock" in f.markers),
        "async_defs": sum(
            1 for f in project.functions.values()
            if f.is_async and f.module.startswith(config.package_prefix)
        ) if config.package_prefix else 0,
        "waived": waived,
    }
    return Report(violations=violations, stats=stats)
