"""Shared AST helpers: dotted-name flattening and escape-comment scans."""

from __future__ import annotations

import ast


def dotted_name(node: ast.AST) -> "str | None":
    """``a.b.c`` for a Name/Attribute chain; None for computed bases
    (subscripts, call results) that cannot be resolved statically."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def comment_waiver(lines: "list[str]", lineno: int, mark: str) -> "str | None":
    """Return the waiver text when ``# <mark> <why>`` appears on
    ``lineno`` or anywhere in the contiguous comment block immediately
    above it (multi-line justifications sit above the statement).
    ``lines`` is the file split by newlines; ``lineno`` is 1-based."""
    def _scan(text: str) -> "str | None":
        at = text.find(mark)
        if at < 0:
            return None
        return text[at + len(mark):].strip() or "(no reason given)"

    if 1 <= lineno <= len(lines):
        found = _scan(lines[lineno - 1])
        if found is not None:
            return found
    n = lineno - 1
    while 1 <= n <= len(lines) and lines[n - 1].lstrip().startswith("#"):
        found = _scan(lines[n - 1])
        if found is not None:
            return found
        n -= 1
    return None


def walk_body(node: ast.AST):
    """Walk ``node`` without descending into nested function/class
    definitions — a nested ``def``'s body belongs to the nested
    function's own record, not its parent's (a jit body builder must not
    pollute the host function's effect set).  The nested def NODE itself
    is still yielded (callers index it); lambdas are descended into —
    they execute inline at their call site."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(child))


def decorator_markers(node: "ast.FunctionDef | ast.AsyncFunctionDef",
                      known: "frozenset[str]") -> "set[str]":
    """The effect-marker decorator names on ``node``: bare ``@hotpath``
    or dotted ``@effects.hotpath`` both count; anything else is ignored."""
    out: set[str] = set()
    for dec in node.decorator_list:
        name = dotted_name(dec)
        if name is None:
            continue
        tail = name.rsplit(".", 1)[-1]
        if tail in known:
            out.add(tail)
    return out
