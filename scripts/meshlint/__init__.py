"""meshlint — call-graph-aware effect checker for calfkit-tpu (ISSUE 12).

An AST-based, whole-package static analyzer, stdlib-only by design (the
CI lint lane's pip footprint must not grow).  Three layers:

1. :mod:`meshlint.callgraph` builds an intra-project call graph over the
   scanned tree: import resolution (absolute, aliased, relative), method
   dispatch through ``self.``/class attributes and simple local
   ``var = ClassName()`` inference, and a conservative bare-name
   fallback for receivers it cannot type.
2. :mod:`meshlint.infer` infers per-function EFFECTS: blocking
   primitives, logging, wall-clock and monotonic-clock reads, blocking
   device→host syncs, unbounded queue construction, string formatting,
   and await points — each tagged with any escape-comment waiver found
   at the site.
3. :mod:`meshlint.rules` propagates constraints declared at the
   definition site (the no-op markers in ``calfkit_tpu/effects.py``:
   ``@hotpath`` / ``@no_block`` / ``@no_wallclock`` / ``@no_log``)
   through the transitive call closure and reports violations as full
   call chains (``root → helper → offending file:line``), plus the
   whole-package event-loop stall rule, the await-point atomicity rule,
   and every rule migrated off the old ``scripts/lint_hotpath.py``
   (journal-append formatting, FlightRecorder.append body, unbounded
   queues, the simulator wall-clock ban, root-coverage loud-miss).

Entry points: ``python -m meshlint [--chains] [--json PATH] [--root D]``
(see :mod:`meshlint.__main__`), or programmatically::

    from meshlint import analyze, default_config
    report = analyze(default_config(repo_root))
    report.ok  # True when the tree is clean
"""

from meshlint.config import Config, default_config
from meshlint.report import Report, Violation
from meshlint.run import analyze

__all__ = ["Config", "default_config", "Report", "Violation", "analyze"]
