"""meshlint configuration: what to scan and where each rule applies.

``default_config`` is the calfkit-tpu instance; tests build their own
``Config`` around fixture trees.  Everything here is data, not code —
the rules in :mod:`meshlint.rules` read these scopes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class RequiredRoots:
    """Loud-miss floor: at least ``min_count`` functions under
    ``module_prefix`` must carry ``marker``.  This is the rename-proof
    replacement for the old hand-curated name lists: a wholesale
    decorator removal (or a module rename that drops the tree from the
    scan) fails the lint loudly instead of silently linting nothing."""
    module_prefix: str
    marker: str
    min_count: int
    hint: str = ""


@dataclass
class Config:
    root: Path
    # directories/files (relative to root) to parse into the call graph
    scan: "list[str]" = field(default_factory=lambda: ["calfkit_tpu"])
    # module prefix owning the whole-package async rules (event-loop
    # stall + await atomicity); "" disables both
    package_prefix: str = "calfkit_tpu"
    # module prefixes under the unbounded-queue rule (ISSUE 5 scope)
    queue_scope: "list[str]" = field(default_factory=list)
    # module prefix under the direct wall-clock ban (ISSUE 11); "" off
    sim_scope: str = ""
    # module whose `._journal.append(...)` sites must not format (ISSUE 4)
    journal_module: str = ""
    # (module, class, method) whose body is held to the O(1) journal
    # promise: no formatting, no logging, no time.time (ISSUE 4)
    flightrec_append: "tuple[str, str, str] | None" = None
    required_roots: "list[RequiredRoots]" = field(default_factory=list)


def default_config(root: "Path | str") -> Config:
    root = Path(root)
    return Config(
        root=root,
        scan=["calfkit_tpu", "bench.py", "scripts/perf_gate.py"],
        package_prefix="calfkit_tpu",
        queue_scope=[
            "calfkit_tpu.inference.engine",
            "calfkit_tpu.mesh.dispatch",
            "calfkit_tpu.fleet",
            "calfkit_tpu.sim",
            "calfkit_tpu.leases",
        ],
        sim_scope="calfkit_tpu.sim",
        journal_module="calfkit_tpu.inference.engine",
        flightrec_append=(
            "calfkit_tpu.observability.flightrec", "FlightRecorder", "append",
        ),
        required_roots=[
            RequiredRoots(
                "calfkit_tpu.inference.engine", "hotpath", 8,
                "the decode dispatch loop (ISSUE 2/3/6) and the "
                "priority-shed selection / class-weighted reap ordering "
                "(ISSUE 20) must stay rooted",
            ),
            RequiredRoots(
                "calfkit_tpu.fleet", "hotpath", 8,
                "the per-dispatch selection path (ISSUE 7/9) must stay "
                "rooted",
            ),
            RequiredRoots(
                "calfkit_tpu.leases", "hotpath", 5,
                "the orphan-reaper sweep reads (ISSUE 10) and the "
                "shed-order beat-age read (ISSUE 20) must stay rooted",
            ),
            RequiredRoots(
                "calfkit_tpu.qos", "hotpath", 2,
                "the per-delivery admission token-bucket check and the "
                "class-rank ordering key (ISSUE 20) must stay rooted",
            ),
            RequiredRoots(
                "calfkit_tpu.observability.flightrec", "hotpath", 1,
                "FlightRecorder.append's O(1) promise (ISSUE 4) must stay "
                "rooted",
            ),
            RequiredRoots(
                "calfkit_tpu.observability.runledger", "hotpath", 5,
                "the run ledger's O(1) append promise (ISSUE 17: begin/"
                "attempt/outcome/tokens/finish) must stay rooted",
            ),
            RequiredRoots(
                "calfkit_tpu.observability.runledger", "no_wallclock", 2,
                "the SLO rollup fold (ISSUE 17) is gated by the sim — it "
                "must never read host time",
            ),
            RequiredRoots(
                "calfkit_tpu.observability.capacity", "hotpath", 7,
                "the page ledger's O(1) mutation promise (ISSUE 19: "
                "alloc/free/transfer/acquire/release/evicted + sampler "
                "append) must stay rooted",
            ),
            RequiredRoots(
                "calfkit_tpu.observability.capacity", "no_wallclock", 2,
                "the capacity rollup math (ISSUE 19: breakdown, the "
                "analytic HBM model) is gated by the sim — it must never "
                "read host time",
            ),
            RequiredRoots(
                "perf_gate", "no_wallclock", 1,
                "the gate's metric compare must never read host time "
                "(ISSUE 11)",
            ),
            RequiredRoots(
                "bench", "no_wallclock", 1,
                "_perf_model's roofline math must never read host time",
            ),
        ],
    )
