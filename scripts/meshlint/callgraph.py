"""Intra-project call graph: modules, functions, and resolved call edges.

Resolution strategy, most-precise first:

1. module-local names (functions/classes defined in the same module);
2. the module's import map (absolute, aliased, and relative imports,
   resolved against the project module index — longest-prefix match so
   ``import calfkit_tpu.fleet.policy`` resolves dotted calls through the
   package path);
3. ``self.method()`` through the enclosing class and its project base
   classes (a static MRO walk over classes the project defines);
4. ``var.method()`` where ``var = ClassName(...)`` is a simple local
   single-assignment in the same function;
5. conservative bare-name fallback: an unresolved attribute call links
   to EVERY project function with that method name (capped, and skipped
   for ubiquitous container/stdlib method names) — over-approximation is
   the point: a helper two modules away must not escape the closure just
   because its receiver's type is dynamic.

Edges carry a KIND so rules can choose what propagates:

- ``normal``   — plain synchronous (or awaited) call;
- ``threaded`` — handed to another thread (``asyncio.to_thread`` /
  ``run_in_executor`` / ``threading.Thread(target=...)``): blocking
  there does not stall the caller;
- ``deferred`` — scheduled onto the event loop (``call_soon*`` /
  ``call_later`` / ``add_done_callback``): runs later, on the loop;
- ``spawn``    — a new task (``create_task`` / ``ensure_future``): the
  target coroutine is an ``async def`` and is independently rooted by
  the event-loop stall rule, so these edges are never traversed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from meshlint.astutil import decorator_markers, dotted_name, walk_body

MARKER_NAMES = frozenset({"hotpath", "no_block", "no_wallclock", "no_log"})

# attribute names too generic to fallback-link: every list/dict/set/str/
# asyncio primitive carries them, and a graph where every ``.get()``
# points at every project ``get`` is noise, not conservatism.
FALLBACK_SKIP_ATTRS = frozenset({
    "append", "appendleft", "add", "extend", "insert", "remove", "discard",
    "clear", "pop", "popleft", "get", "put", "put_nowait", "get_nowait",
    "update", "copy", "items", "keys", "values", "setdefault", "sort",
    "index", "count", "join", "split", "strip", "lstrip", "rstrip",
    "encode", "decode", "format", "lower", "upper", "startswith",
    "endswith", "replace", "read", "write", "readline", "flush", "close",
    "open", "result", "set_result", "set_exception", "done", "cancel",
    "cancelled", "exception", "release", "acquire", "locked", "wait",
    "wait_for", "notify", "notify_all", "set", "is_set", "sleep", "time",
    "monotonic", "perf_counter", "task_done", "send", "throw", "info",
    "debug", "warning", "error", "critical", "log", "observe", "inc",
    "dec", "labels", "next", "popitem", "move_to_end", "total_seconds",
    "item", "block_until_ready", "mkdir", "exists", "stat", "unlink",
})
FALLBACK_MAX_CANDIDATES = 8

_THREADED_TAILS = frozenset({"to_thread"})
_THREADED_ATTRS = frozenset({"run_in_executor"})
_DEFERRED_ATTRS = frozenset({
    "call_soon", "call_soon_threadsafe", "call_later", "call_at",
    "add_done_callback",
})
_SPAWN_TAILS = frozenset({"create_task", "ensure_future"})


@dataclass
class EffectSite:
    """One inferred effect occurrence inside a function body."""
    kind: str          # BLOCK | LOG | WALLCLOCK | MONOTONIC | DEVICE_SYNC |
    #                    UNBOUNDED_QUEUE | AWAIT
    lineno: int
    detail: str
    waiver: "str | None" = None   # escape-comment reason when waived

    @property
    def waived(self) -> bool:
        return self.waiver is not None


@dataclass
class CallEdge:
    lineno: int
    kind: str                 # normal | threaded | deferred | spawn
    targets: "tuple[str, ...]"  # resolved callee qnames
    via: str = ""             # the source text-ish name, for reports


@dataclass
class FunctionInfo:
    qname: str
    module: str
    name: str
    cls: "str | None"
    path: Path
    lineno: int
    is_async: bool
    markers: "set[str]" = field(default_factory=set)
    node: "ast.AST | None" = None
    effects: "list[EffectSite]" = field(default_factory=list)
    edges: "list[CallEdge]" = field(default_factory=list)


@dataclass
class ModuleInfo:
    name: str
    path: Path
    tree: ast.Module
    lines: "list[str]"
    is_package: bool = False  # an __init__ module: its name IS the package
    imports: "dict[str, str]" = field(default_factory=dict)
    functions: "dict[str, str]" = field(default_factory=dict)   # bare -> qname
    classes: "dict[str, 'ClassInfo']" = field(default_factory=dict)
    module_effects: "list[EffectSite]" = field(default_factory=list)


@dataclass
class ClassInfo:
    qname: str
    module: str
    name: str
    bases: "list[str]" = field(default_factory=list)   # base qnames (project)
    methods: "dict[str, str]" = field(default_factory=dict)  # bare -> qname


class Project:
    """The parsed project: module index, function index, call edges."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.by_bare_name: dict[str, list[str]] = {}
        self._closure_cache: dict[tuple[str, frozenset], set] = {}

    # ------------------------------------------------------------ build
    @classmethod
    def build(cls, root: Path, scan: "list[str]") -> "Project":
        project = cls()
        files = _discover(root, scan)
        for module_name, path in files:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
            project.modules[module_name] = ModuleInfo(
                name=module_name, path=path, tree=tree,
                lines=source.splitlines(),
                is_package=path.name == "__init__.py",
            )
        for mod in project.modules.values():
            _index_module(project, mod)
        for mod in project.modules.values():
            _resolve_module(project, mod)
        project._index_bare_names()
        for mod in project.modules.values():
            _resolve_calls(project, mod)
        return project

    def _index_bare_names(self) -> None:
        for qname, fn in self.functions.items():
            # nested functions are only callable from their enclosing
            # scope — never fallback candidates for a dynamic receiver
            if ".<locals>." in qname:
                continue
            self.by_bare_name.setdefault(fn.name, []).append(qname)

    # ---------------------------------------------------------- queries
    def closure(self, root: str, edge_kinds: "frozenset[str]") -> "set[str]":
        """Transitive callee closure of ``root`` (inclusive), traversing
        only edges whose kind is in ``edge_kinds``."""
        key = (root, edge_kinds)
        cached = self._closure_cache.get(key)
        if cached is not None:
            return cached
        seen: set[str] = set()
        stack = [root]
        while stack:
            qname = stack.pop()
            if qname in seen:
                continue
            seen.add(qname)
            fn = self.functions.get(qname)
            if fn is None:
                continue
            for edge in fn.edges:
                if edge.kind not in edge_kinds:
                    continue
                for target in edge.targets:
                    if target not in seen:
                        stack.append(target)
        self._closure_cache[key] = seen
        return seen

    def chain(self, root: str, target: str,
              edge_kinds: "frozenset[str]") -> "list[tuple[str, int]]":
        """Shortest call chain root → … → target as a list of
        ``(qname, call_lineno)`` hops (the root's lineno entry is the
        def line; each later entry carries the line of the call that
        reached it)."""
        if root == target:
            fn = self.functions.get(root)
            return [(root, fn.lineno if fn else 0)]
        parent: dict[str, tuple[str, int]] = {}
        seen = {root}
        frontier = [root]
        while frontier:
            nxt: list[str] = []
            for qname in frontier:
                fn = self.functions.get(qname)
                if fn is None:
                    continue
                for edge in fn.edges:
                    if edge.kind not in edge_kinds:
                        continue
                    for callee in edge.targets:
                        if callee in seen:
                            continue
                        seen.add(callee)
                        parent[callee] = (qname, edge.lineno)
                        if callee == target:
                            return self._unwind(root, target, parent)
                        nxt.append(callee)
            frontier = nxt
        return [(root, 0), (target, 0)]  # unreachable: defensive

    def _unwind(self, root: str, target: str,
                parent: "dict[str, tuple[str, int]]"
                ) -> "list[tuple[str, int]]":
        chain: list[tuple[str, int]] = []
        at = target
        while at != root:
            up, lineno = parent[at]
            chain.append((at, lineno))
            at = up
        fn = self.functions.get(root)
        chain.append((root, fn.lineno if fn else 0))
        chain.reverse()
        return chain


# ---------------------------------------------------------------- internal

def _discover(root: Path, scan: "list[str]") -> "list[tuple[str, Path]]":
    out: list[tuple[str, Path]] = []
    for entry in scan:
        path = root / entry
        if path.is_dir():
            for sub in sorted(path.rglob("*.py")):
                if "__pycache__" in sub.parts:
                    continue
                out.append((_module_name(root, sub), sub))
        elif path.is_file():
            out.append((_module_name(root, path), path))
    return out


def _module_name(root: Path, path: Path) -> str:
    rel = path.relative_to(root)
    parts = list(rel.parts)
    # scripts/ is not a package: scripts/perf_gate.py imports as perf_gate
    if parts[0] == "scripts":
        parts = parts[1:]
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts)


def _index_module(project: Project, mod: ModuleInfo) -> None:
    for node in mod.tree.body:
        _index_import(mod, node)
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _register_function(project, mod, node, cls=None)
        elif isinstance(node, ast.ClassDef):
            info = ClassInfo(
                qname=f"{mod.name}.{node.name}", module=mod.name,
                name=node.name,
            )
            mod.classes[node.name] = info
            project.classes[info.qname] = info
            for base in node.bases:
                name = dotted_name(base)
                if name:
                    info.bases.append(name)  # resolved lazily against imports
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = _register_function(project, mod, sub, cls=node.name)
                    info.methods[sub.name] = fn.qname


def _register_function(project: Project, mod: ModuleInfo, node,
                       cls: "str | None") -> FunctionInfo:
    qname = (f"{mod.name}.{cls}.{node.name}" if cls
             else f"{mod.name}.{node.name}")
    fn = FunctionInfo(
        qname=qname, module=mod.name, name=node.name, cls=cls,
        path=mod.path, lineno=node.lineno,
        is_async=isinstance(node, ast.AsyncFunctionDef),
        markers=decorator_markers(node, MARKER_NAMES),
        node=node,
    )
    project.functions[qname] = fn
    if cls is None:
        mod.functions[node.name] = qname
    _register_nested(project, mod, fn)
    return fn


def _register_nested(project: Project, mod: ModuleInfo,
                     parent: FunctionInfo) -> None:
    """Nested defs get their own records (``parent.<locals>.name``), so
    a jit body builder's device code never pollutes the host function's
    effect set — the parent only links to a nested def it actually
    CALLS by name."""
    for sub in walk_body(parent.node):
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        nested_q = f"{parent.qname}.<locals>.{sub.name}"
        if nested_q in project.functions:
            continue
        nested = FunctionInfo(
            qname=nested_q, module=mod.name, name=sub.name,
            cls=parent.cls, path=mod.path, lineno=sub.lineno,
            is_async=isinstance(sub, ast.AsyncFunctionDef),
            markers=decorator_markers(sub, MARKER_NAMES),
            node=sub,
        )
        project.functions[nested_q] = nested
        _register_nested(project, mod, nested)


def _index_import(mod: ModuleInfo, node: ast.AST) -> None:
    if isinstance(node, ast.Import):
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            target = alias.name if alias.asname else alias.name.split(".")[0]
            mod.imports[bound] = target
    elif isinstance(node, ast.ImportFrom):
        base = node.module or ""
        if node.level:
            parts = mod.name.split(".")
            # for a module p.q.m, level=1 resolves against p.q — strip
            # `level` trailing segments.  An __init__ module's name IS
            # its package (p.q for p/q/__init__.py), so level=1 resolves
            # against the name itself: strip one segment fewer.
            strip = node.level - 1 if mod.is_package else node.level
            if strip:
                parts = parts[: len(parts) - strip]
            base = ".".join(parts + ([node.module] if node.module else []))
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name
            mod.imports[bound] = f"{base}.{alias.name}" if base else alias.name


def _resolve_module(project: Project, mod: ModuleInfo) -> None:
    """Resolve class base names against the import map so the static MRO
    walk can cross modules."""
    for cls in mod.classes.values():
        resolved: list[str] = []
        for base in cls.bases:
            qname = _resolve_dotted(project, mod, base)
            if qname and qname in project.classes:
                resolved.append(qname)
        cls.bases = resolved


def _resolve_dotted(project: Project, mod: ModuleInfo,
                    dotted: str) -> "str | None":
    """Resolve a dotted reference in ``mod``'s namespace to a project
    qname (function, class, or module)."""
    parts = dotted.split(".")
    head = parts[0]
    if head in mod.imports:
        full = mod.imports[head] + ("." + ".".join(parts[1:])
                                    if len(parts) > 1 else "")
    elif head in mod.functions and len(parts) == 1:
        return mod.functions[head]
    elif head in mod.classes:
        cls = mod.classes[head]
        if len(parts) == 1:
            return cls.qname
        return _class_attr(project, cls, parts[1]) if len(parts) == 2 else None
    else:
        full = f"{mod.name}.{dotted}"
        if full not in project.functions and _prefix_module(
            project, full
        ) is None:
            return None
    if full in project.functions or full in project.classes:
        return full
    owner = _prefix_module(project, full)
    if owner is None:
        return None
    rest = full[len(owner.name):].lstrip(".").split(".") if len(
        full
    ) > len(owner.name) else []
    if not rest:
        return owner.name
    if len(rest) == 1:
        if rest[0] in owner.functions:
            return owner.functions[rest[0]]
        if rest[0] in owner.classes:
            return owner.classes[rest[0]].qname
        return None
    if len(rest) == 2 and rest[0] in owner.classes:
        return _class_attr(project, owner.classes[rest[0]], rest[1])
    return None


def _prefix_module(project: Project, dotted: str) -> "ModuleInfo | None":
    parts = dotted.split(".")
    for cut in range(len(parts), 0, -1):
        name = ".".join(parts[:cut])
        if name in project.modules:
            return project.modules[name]
    return None


def _class_attr(project: Project, cls: ClassInfo,
                method: str) -> "str | None":
    """Static MRO walk: the class, then its project bases, depth-first."""
    seen: set[str] = set()
    stack = [cls.qname]
    while stack:
        qname = stack.pop(0)
        if qname in seen:
            continue
        seen.add(qname)
        info = project.classes.get(qname)
        if info is None:
            continue
        if method in info.methods:
            return info.methods[method]
        stack.extend(info.bases)
    return None


def _resolve_calls(project: Project, mod: ModuleInfo) -> None:
    for qname, fn in list(project.functions.items()):
        if fn.module != mod.name or fn.node is None:
            continue
        _resolve_function_calls(project, mod, fn)


def _local_class_types(project: Project, mod: ModuleInfo,
                       fn: FunctionInfo) -> "dict[str, str]":
    """``var -> ClassQname`` for simple ``var = ClassName(...)`` local
    single-assignments (reassignment to a different class drops the
    binding — ambiguity resolves to the fallback path)."""
    out: dict[str, str] = {}
    dropped: set[str] = set()
    assigns = [n for n in walk_body(fn.node) if isinstance(n, ast.Assign)]
    # walk_body is LIFO, not source order — the reassignment-drops-binding
    # law below needs statements in textual order
    assigns.sort(key=lambda n: (n.lineno, n.col_offset))
    for node in assigns:
        if len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        callee = dotted_name(node.value.func)
        if callee is None:
            continue
        resolved = _resolve_dotted(project, mod, callee)
        if resolved and resolved in project.classes:
            if target.id in out and out[target.id] != resolved:
                dropped.add(target.id)
            out[target.id] = resolved
        elif target.id in out:
            dropped.add(target.id)
    for name in dropped:
        out.pop(name, None)
    return out


def _resolve_function_calls(project: Project, mod: ModuleInfo,
                            fn: FunctionInfo) -> None:
    local_types = _local_class_types(project, mod, fn)
    nested_local = {
        f.name: f.qname
        for f in project.functions.values()
        if f.qname.startswith(fn.qname + ".<locals>.")
    }
    # a spawn's coroutine argument (`create_task(self._bg())`) calls the
    # coroutine FUNCTION only to build the coroutine object — the body
    # runs on the spawned task, which the event-loop stall rule roots
    # independently.  Suppress the inner Call's own edge so a spawned
    # background coroutine's effects never leak into the spawner's
    # closure as if called synchronously (argument EXPRESSIONS inside it
    # still walk normally — they do evaluate at the spawn site).
    spawned_calls: set[int] = set()
    for node in walk_body(fn.node):
        if isinstance(node, ast.Call) and _call_kind(node)[0] == "spawn":
            for arg in node.args:
                if isinstance(arg, ast.Call):
                    spawned_calls.add(id(arg))
    for node in walk_body(fn.node):
        if not isinstance(node, ast.Call):
            continue
        if id(node) in spawned_calls:
            continue
        kind, ref = _call_kind(node)
        if kind != "normal":
            targets = _resolve_ref(project, mod, fn, ref, local_types,
                                   nested_local) if ref is not None else ()
            if targets:
                fn.edges.append(CallEdge(
                    lineno=node.lineno, kind=kind, targets=targets,
                    via=dotted_name(ref) or "<ref>",
                ))
            continue
        targets = _resolve_ref(project, mod, fn, node.func, local_types,
                               nested_local)
        if targets:
            fn.edges.append(CallEdge(
                lineno=node.lineno, kind="normal", targets=targets,
                via=dotted_name(node.func) or _attr_tail(node.func) or "?",
            ))


def _attr_tail(node: ast.AST) -> "str | None":
    return node.attr if isinstance(node, ast.Attribute) else None


def _call_kind(call: ast.Call) -> "tuple[str, ast.AST | None]":
    """Classify thread/loop handoffs.  Returns (kind, callable-ref):
    the ref is the function REFERENCE being handed off (to_thread's
    first arg, run_in_executor's second, Thread's target=...)."""
    func = call.func
    tail = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None
    )
    if tail in _THREADED_TAILS:
        return "threaded", call.args[0] if call.args else None
    if tail in _THREADED_ATTRS:
        return "threaded", call.args[1] if len(call.args) > 1 else None
    if tail == "Thread" or dotted_name(func) == "threading.Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return "threaded", kw.value
        return "threaded", None
    if tail in _SPAWN_TAILS:
        return "spawn", None
    if tail in _DEFERRED_ATTRS:
        return "deferred", call.args[0] if call.args else None
    return "normal", None


def _resolve_ref(project: Project, mod: ModuleInfo, fn: FunctionInfo,
                 ref: ast.AST, local_types: "dict[str, str]",
                 nested_local: "dict[str, str]") -> "tuple[str, ...]":
    """Resolve a callable reference to project function qnames."""
    # self.method() -> enclosing class MRO
    if (isinstance(ref, ast.Attribute)
            and isinstance(ref.value, ast.Name)
            and ref.value.id in ("self", "cls") and fn.cls is not None):
        cls = project.classes.get(f"{fn.module}.{fn.cls}")
        if cls is not None:
            hit = _class_attr(project, cls, ref.attr)
            if hit:
                return (hit,)
        return _fallback(project, ref.attr, is_attr=True)
    # var.method() with a locally-inferred class type
    if (isinstance(ref, ast.Attribute)
            and isinstance(ref.value, ast.Name)
            and ref.value.id in local_types):
        cls = project.classes.get(local_types[ref.value.id])
        if cls is not None:
            hit = _class_attr(project, cls, ref.attr)
            if hit:
                return (hit,)
        return _fallback(project, ref.attr, is_attr=True)
    # ClassName(...).method(): the receiver is a constructor call on a
    # resolvable project class — dispatch precisely, not by fallback
    if (isinstance(ref, ast.Attribute)
            and isinstance(ref.value, ast.Call)):
        ctor = dotted_name(ref.value.func)
        if ctor is not None:
            resolved = _resolve_dotted(project, mod, ctor)
            if resolved and resolved in project.classes:
                hit = _class_attr(project, project.classes[resolved],
                                  ref.attr)
                if hit:
                    return (hit,)
    dotted = dotted_name(ref)
    if dotted is not None:
        parts = dotted.split(".")
        if len(parts) == 1:
            if parts[0] in nested_local:
                return (nested_local[parts[0]],)
            resolved = _resolve_dotted(project, mod, dotted)
            if resolved and resolved in project.functions:
                return (resolved,)
            if resolved and resolved in project.classes:
                init = _class_attr(project, project.classes[resolved],
                                   "__init__")
                return (init,) if init else ()
            if parts[0] in mod.imports:
                # imported from a known non-project module (e.g.
                # ``from copy import deepcopy``): precisely resolved,
                # just not ours — never a fallback candidate
                return ()
            return _fallback(project, parts[0], is_attr=False)
        resolved = _resolve_dotted(project, mod, dotted)
        if resolved and resolved in project.functions:
            return (resolved,)
        if resolved and resolved in project.classes:
            init = _class_attr(project, project.classes[resolved], "__init__")
            return (init,) if init else ()
        if parts[0] in mod.imports or parts[0] in mod.classes:
            # the receiver IS known (an imported module like ``asyncio``
            # or a project class) — the attribute simply isn't a project
            # function.  Falling back by bare name here would link
            # ``asyncio.run`` to every project ``run``.
            return ()
        return _fallback(project, parts[-1], is_attr=True)
    if isinstance(ref, ast.Attribute):
        return _fallback(project, ref.attr, is_attr=True)
    return ()


def _fallback(project: Project, bare: str,
              *, is_attr: bool) -> "tuple[str, ...]":
    """Conservative name fallback: link to every project function with
    this bare name, unless the name is in the ubiquitous-method skip set
    or the candidate set is too large to be meaningful."""
    if bare in FALLBACK_SKIP_ATTRS or bare.startswith("__"):
        return ()
    candidates = project.by_bare_name.get(bare, ())
    if not candidates or len(candidates) > FALLBACK_MAX_CANDIDATES:
        return ()
    if not is_attr:
        # a bare-name call can only reach module-level / nested functions
        candidates = [q for q in candidates
                      if project.functions[q].cls is None]
    return tuple(candidates)
