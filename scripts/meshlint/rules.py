"""The rule engine: constraint propagation and the whole-package rules.

Every rule yields :class:`meshlint.report.Violation` records.  The root
rules propagate a marker's forbidden effect set through the transitive
call closure; the chain on each violation is the shortest call path from
the declared root to the offending function, so a report reads
``root → helper → offending file:line`` — the exact property the old
per-body name lists could not give.
"""

from __future__ import annotations

import ast

from meshlint import infer
from meshlint.astutil import comment_waiver, dotted_name, walk_body
from meshlint.callgraph import FunctionInfo, Project
from meshlint.config import Config
from meshlint.report import ChainHop, Violation

# edge sets per propagation class: blocking stalls only the calling
# thread, so thread handoffs break the chain; clock reads and logging
# poison the property no matter which thread runs them.  Spawn edges are
# never traversed — a spawned coroutine is an async def, independently
# rooted by the event-loop stall rule.
SYNC_EDGES = frozenset({"normal"})
LOOP_EDGES = frozenset({"normal", "deferred"})
ANY_THREAD_EDGES = frozenset({"normal", "threaded", "deferred"})

# marker -> list of (forbidden effect kinds, edge filter)
MARKER_CONSTRAINTS: "dict[str, list[tuple[frozenset, frozenset]]]" = {
    "hotpath": [
        (frozenset({infer.BLOCK, infer.DEVICE_SYNC}), SYNC_EDGES),
        (frozenset({infer.LOG, infer.WALLCLOCK}), ANY_THREAD_EDGES),
    ],
    "no_block": [(frozenset({infer.BLOCK}), SYNC_EDGES)],
    "no_wallclock": [
        (frozenset({infer.WALLCLOCK, infer.MONOTONIC}), ANY_THREAD_EDGES),
    ],
    "no_log": [(frozenset({infer.LOG}), ANY_THREAD_EDGES)],
}

_ATOMICITY_MARK = "atomicity-ok:"


def run_rules(project: Project, config: Config) -> "list[Violation]":
    out: list[Violation] = []
    out.extend(root_constraint_rule(project, config))
    out.extend(async_stall_rule(project, config))
    out.extend(await_atomicity_rule(project, config))
    out.extend(unbounded_queue_rule(project, config))
    out.extend(sim_wallclock_rule(project, config))
    out.extend(journal_site_rule(project, config))
    out.extend(flightrec_append_rule(project, config))
    out.extend(coverage_rule(project, config))
    return out


# ------------------------------------------------------- root closures

def _rel(config: Config, fn: FunctionInfo) -> str:
    try:
        return str(fn.path.relative_to(config.root))
    except ValueError:
        return str(fn.path)


def _chain_hops(project: Project, config: Config, root: str, target: str,
                edges: "frozenset[str]") -> "list[ChainHop]":
    hops: "list[ChainHop]" = []
    prev_path = ""
    for qname, lineno in project.chain(root, target, edges):
        fn = project.functions.get(qname)
        path = _rel(config, fn) if fn else "?"
        hops.append(ChainHop(
            qname=qname, path=path, lineno=lineno, call_path=prev_path,
        ))
        prev_path = path
    return hops


def root_constraint_rule(project: Project,
                         config: Config) -> "list[Violation]":
    out: list[Violation] = []
    for fn in project.functions.values():
        if not fn.markers:
            continue
        if "hotpath" in fn.markers and fn.is_async:
            out.append(Violation(
                rule="hotpath-sync-shape",
                message=(f"{fn.qname} is @hotpath but became `async def` — "
                         "the dispatch/selection paths are sync by contract "
                         "(no broker round-trips per routed call)"),
                path=_rel(config, fn), lineno=fn.lineno,
                chain=[ChainHop(fn.qname, _rel(config, fn), fn.lineno)],
                effect="ASYNC_SHAPE", detail="async def",
            ))
        for marker in sorted(fn.markers):
            for kinds, edges in MARKER_CONSTRAINTS.get(marker, ()):
                out.extend(_propagate(project, config, fn, marker,
                                      kinds, edges))
    return out


def _propagate(project: Project, config: Config, root: FunctionInfo,
               marker: str, kinds: "frozenset[str]",
               edges: "frozenset[str]") -> "list[Violation]":
    out: list[Violation] = []
    for qname in sorted(project.closure(root.qname, edges)):
        callee = project.functions.get(qname)
        if callee is None:
            continue
        for site in callee.effects:
            if site.kind not in kinds or site.waived:
                continue
            mark = infer.WAIVER_MARKS.get(site.kind, "blocking-ok:")
            out.append(Violation(
                rule=marker,
                message=(
                    f"@{marker} root {root.qname} transitively reaches "
                    f"{site.kind} effect `{site.detail}` in {callee.qname} "
                    f"(waive the site with '# {mark} <why>' if legitimate)"
                ),
                path=_rel(config, callee), lineno=site.lineno,
                chain=_chain_hops(project, config, root.qname, qname, edges),
                effect=site.kind, detail=site.detail,
            ))
    return out


# --------------------------------------------------- event-loop stalls

def async_stall_rule(project: Project, config: Config) -> "list[Violation]":
    """No ``async def`` anywhere in the package may transitively call a
    blocking primitive outside a ``to_thread``/executor handoff: one
    blocked coroutine stalls EVERY run on that worker's event loop."""
    if not config.package_prefix:
        return []
    out: list[Violation] = []
    seen_effects: set[tuple[str, int, str]] = set()
    for fn in project.functions.values():
        if not fn.is_async or not fn.module.startswith(
            config.package_prefix
        ):
            continue
        for qname in sorted(project.closure(fn.qname, LOOP_EDGES)):
            callee = project.functions.get(qname)
            if callee is None:
                continue
            for site in callee.effects:
                if site.kind != infer.BLOCK or site.waived:
                    continue
                # report each offending SITE once, under its shortest
                # async root — N async callers of one blocking helper
                # are one bug, not N
                key = (callee.qname, site.lineno, site.detail)
                if key in seen_effects:
                    continue
                seen_effects.add(key)
                out.append(Violation(
                    rule="async-stall",
                    message=(
                        f"async {fn.qname} transitively calls blocking "
                        f"`{site.detail}` in {callee.qname} — move it "
                        "behind asyncio.to_thread / an executor, or waive "
                        "the site with '# blocking-ok: <why>'"
                    ),
                    path=_rel(config, callee), lineno=site.lineno,
                    chain=_chain_hops(project, config, fn.qname, qname,
                                      LOOP_EDGES),
                    effect=infer.BLOCK, detail=site.detail,
                ))
    return out


# ----------------------------------------------- await-point atomicity

def await_atomicity_rule(project: Project,
                         config: Config) -> "list[Violation]":
    """Flag read-then-write of the same ``self.<attr>`` across an
    intervening ``await``: the loop may interleave another coroutine
    between the read and the write, and the write then clobbers state
    based on a stale read.  A fresh re-read after the last await (e.g.
    ``self.x += 1``, or the write's RHS reading the attr) clears the
    flag; legitimate check-then-act patterns carry
    ``# atomicity-ok: <why>``."""
    if not config.package_prefix:
        return []
    out: list[Violation] = []
    for fn in project.functions.values():
        if not fn.is_async or not fn.module.startswith(
            config.package_prefix
        ) or fn.node is None:
            continue
        out.extend(_atomicity_scan(project, config, fn))
    return out


def _atomicity_scan(project: Project, config: Config,
                    fn: FunctionInfo) -> "list[Violation]":
    reads: dict[str, list[tuple[int, int]]] = {}
    awaits: list[tuple[int, int]] = []
    # writes: (attr, stmt_start, stmt_end) — the span lets an RHS
    # re-read on the write statement itself count as fresh
    writes: list[tuple[str, tuple[int, int], tuple[int, int]]] = []
    for node in walk_body(fn.node):
        if isinstance(node, ast.Await):
            awaits.append((node.lineno, node.col_offset))
        elif isinstance(node, ast.AugAssign):
            target = node.target
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            # read+write in one statement: the read is fresh by
            # construction (asyncio interleaves only at awaits)
            pos = (node.lineno, node.col_offset)
            end = (node.end_lineno or node.lineno, node.end_col_offset or 0)
            reads.setdefault(target.attr, []).append(end)
            writes.append((target.attr, pos, end))
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else (
                [node.target]
            )
            pos = (node.lineno, node.col_offset)
            end = (node.end_lineno or node.lineno, node.end_col_offset or 0)
            for target in targets:
                for sub in ast.walk(target):
                    if (isinstance(sub, ast.Attribute)
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == "self"
                            and isinstance(sub.ctx, ast.Store)):
                        writes.append((sub.attr, pos, end))
        elif _self_attr(node) and isinstance(node.ctx, ast.Load):
            reads.setdefault(node.attr, []).append(
                (node.lineno, node.col_offset)
            )
    if not awaits:
        return []
    mod = project.modules.get(fn.module)
    lines = mod.lines if mod else []
    out: list[Violation] = []
    flagged: set[tuple[str, int]] = set()
    for attr, wpos, wend in writes:
        attr_reads = reads.get(attr, [])
        before = [a for a in awaits if a < wpos]
        if not before:
            continue
        a_star = max(before)
        if any(a_star < r <= wend for r in attr_reads):
            continue  # fresh read after the last await
        stale = [r for r in attr_reads if r < a_star]
        if not stale:
            continue
        if (attr, wpos[0]) in flagged:
            continue
        flagged.add((attr, wpos[0]))
        if (comment_waiver(lines, wpos[0], _ATOMICITY_MARK) is not None
                or comment_waiver(lines, fn.lineno, _ATOMICITY_MARK)
                is not None):
            continue
        read_line = max(stale)[0]
        await_line = a_star[0]
        out.append(Violation(
            rule="await-atomicity",
            message=(
                f"{fn.qname}: `self.{attr}` read at line {read_line} may "
                f"be stale by the write at line {wpos[0]} — the await at "
                f"line {await_line} yields the event loop between them "
                "(re-read after the await, or annotate the write with "
                "'# atomicity-ok: <why>')"
            ),
            path=_rel(config, fn), lineno=wpos[0],
            chain=[ChainHop(fn.qname, _rel(config, fn), fn.lineno)],
            effect="STALE_WRITE", detail=f"self.{attr}",
        ))
    return out


def _self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


# ------------------------------------------------ module-scoped rules

def _module_wide_effects(project: Project, prefix: str):
    for mod in project.modules.values():
        if not mod.name.startswith(prefix):
            continue
        for site in mod.module_effects:
            yield mod, None, site
        for fn in project.functions.values():
            if fn.module != mod.name:
                continue
            for site in fn.effects:
                yield mod, fn, site


def unbounded_queue_rule(project: Project,
                         config: Config) -> "list[Violation]":
    out: list[Violation] = []
    seen: set[tuple[str, int]] = set()
    for prefix in config.queue_scope:
        for mod, fn, site in _module_wide_effects(project, prefix):
            if site.kind != infer.UNBOUNDED_QUEUE or site.waived:
                continue
            key = (mod.name, site.lineno)
            if key in seen:
                continue
            seen.add(key)
            where = fn.qname if fn else mod.name
            out.append(Violation(
                rule="unbounded-queue",
                message=(
                    f"unbounded {site.detail} in {where} without an "
                    "'# unbounded-ok: <why>' justification (name the "
                    "admission bound / permit / reaper that bounds it)"
                ),
                path=str(mod.path.relative_to(config.root)),
                lineno=site.lineno,
                chain=[], effect=site.kind, detail=site.detail,
            ))
    return out


def sim_wallclock_rule(project: Project,
                       config: Config) -> "list[Violation]":
    """ISSUE 11: NO direct host-clock read anywhere in the simulator —
    byte-identical SIM.json per seed holds only while every timestamp
    flows through the ``cancellation.wall_clock`` seam."""
    if not config.sim_scope:
        return []
    out: list[Violation] = []
    seen: set[tuple[str, int]] = set()
    for mod, fn, site in _module_wide_effects(project, config.sim_scope):
        if site.kind not in (infer.WALLCLOCK, infer.MONOTONIC):
            continue
        if site.waived:
            continue
        key = (mod.name, site.lineno)
        if key in seen:
            continue
        seen.add(key)
        where = fn.qname if fn else mod.name
        out.append(Violation(
            rule="sim-wallclock",
            message=(
                f"sim wall-clock read `{site.detail}` in {where} — all "
                "timestamps must flow through cancellation.wall_clock "
                "(or carry '# wallclock-ok: <why>')"
            ),
            path=str(mod.path.relative_to(config.root)),
            lineno=site.lineno, chain=[], effect=site.kind,
            detail=site.detail,
        ))
    return out


# --------------------------------------------- flight-recorder rules

def _is_journal_append(call: ast.Call) -> bool:
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr == "append"
        and isinstance(fn.value, ast.Attribute)
        and fn.value.attr == "_journal"
    )


def journal_site_rule(project: Project, config: Config) -> "list[Violation]":
    """Every ``*._journal.append(...)`` call site in the engine must pass
    precomputed values only — the journal is on by default in production
    and its O(1)-per-event promise starts at the call site."""
    mod = project.modules.get(config.journal_module)
    if mod is None:
        return []
    out: list[Violation] = []
    for call in ast.walk(mod.tree):
        if not (isinstance(call, ast.Call) and _is_journal_append(call)):
            continue
        for arg in [*call.args, *call.keywords]:
            for lineno, what in infer.formatting_sites(arg):
                out.append(Violation(
                    rule="journal-append-site",
                    message=f"journal append site: {what} — pass "
                            "precomputed values only",
                    path=str(mod.path.relative_to(config.root)),
                    lineno=lineno, chain=[], effect="FORMAT", detail=what,
                ))
    return out


def flightrec_append_rule(project: Project,
                          config: Config) -> "list[Violation]":
    if config.flightrec_append is None:
        return []
    mod_name, cls, method = config.flightrec_append
    qname = f"{mod_name}.{cls}.{method}"
    fn = project.functions.get(qname)
    mod = project.modules.get(mod_name)
    if fn is None or fn.node is None or mod is None:
        return [Violation(
            rule="flightrec-append",
            message=f"{qname} not found (a rename must break this lint "
                    "loudly, not silently lint nothing — update the "
                    "meshlint config)",
            path=str(mod.path.relative_to(config.root)) if mod else mod_name,
            lineno=0, chain=[], effect="MISSING", detail=qname,
        )]
    out: list[Violation] = []
    for lineno, what in infer.formatting_sites(fn.node):
        out.append(Violation(
            rule="flightrec-append",
            message=f"{cls}.{method}: {what} — the O(1) lock-free append "
                    "promise is why the journal may stay on in production",
            path=str(mod.path.relative_to(config.root)), lineno=lineno,
            chain=[], effect="FORMAT", detail=what,
        ))
    for site in fn.effects:
        if site.kind in (infer.LOG, infer.WALLCLOCK) and not site.waived:
            out.append(Violation(
                rule="flightrec-append",
                message=f"{cls}.{method}: {site.detail} — no logging or "
                        "wall-clock syscalls in the append body",
                path=str(mod.path.relative_to(config.root)),
                lineno=site.lineno, chain=[], effect=site.kind,
                detail=site.detail,
            ))
    return out


# ------------------------------------------------------ loud-miss floor

def coverage_rule(project: Project, config: Config) -> "list[Violation]":
    out: list[Violation] = []
    for req in config.required_roots:
        count = sum(
            1 for fn in project.functions.values()
            if fn.module.startswith(req.module_prefix)
            and req.marker in fn.markers
        )
        if count < req.min_count:
            out.append(Violation(
                rule="root-coverage",
                message=(
                    f"only {count} @{req.marker} roots under "
                    f"{req.module_prefix} (need >= {req.min_count}): "
                    f"{req.hint} — decorator coverage dropped, or the "
                    "module moved out of the scan"
                ),
                path=req.module_prefix, lineno=0, chain=[],
                effect="COVERAGE", detail=req.marker,
            ))
    return out
