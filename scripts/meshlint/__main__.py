"""CLI: ``python -m meshlint [--root DIR] [--chains] [--json PATH]``.

Exit 0 when the tree is clean; exit 1 with a violation listing (and,
with ``--chains``, the full root → … → offending file:line call chain
per finding) otherwise.  ``--json`` additionally writes the
machine-readable report — CI uploads it as an artifact.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from meshlint.config import default_config
from meshlint.run import analyze


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="meshlint",
        description="call-graph-aware effect checker for calfkit-tpu",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root (default: two levels above this package)",
    )
    parser.add_argument(
        "--chains", action="store_true",
        help="print the full call chain for every violation",
    )
    parser.add_argument(
        "--json", default=None, metavar="PATH",
        help="also write the machine-readable report to PATH",
    )
    args = parser.parse_args(argv)
    root = Path(args.root) if args.root else (
        Path(__file__).resolve().parent.parent.parent
    )
    report = analyze(default_config(root))
    if args.json:
        Path(args.json).write_text(report.to_json())
    print(report.render(chains=args.chains))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
