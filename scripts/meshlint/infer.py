"""Per-function effect inference.

Each function body is scanned once for the effect vocabulary; every site
is checked for the matching escape comment so rules never have to touch
source text again:

==============  ====================================================
effect          primitives
==============  ====================================================
BLOCK           ``time.sleep`` (incl. from-imported ``sleep``),
                ``open``/``input``, ``subprocess.*``, ``os.system``/
                ``os.popen``, ``socket.socket``/``create_connection``/
                ``getaddrinfo``, ``select.select``
LOG             ``print``, any ``logger.*``/``logging.*`` call,
                ``warnings.warn``
WALLCLOCK       ``time.time``/``time_ns``, ``datetime.now``/``utcnow``/
                ``today`` (any ``datetime``-rooted chain)
MONOTONIC       ``time.monotonic``/``perf_counter`` (+ ``_ns``)
DEVICE_SYNC     ``np.asarray``/``np.array``/``jax.device_get``,
                ``.block_until_ready()``/``.item()`` on any receiver
UNBOUNDED_QUEUE ``asyncio.Queue()``/``deque()``/… with no bound and no
                ``# unbounded-ok:`` justification (incl.
                ``default_factory=``)
AWAIT           any ``await`` expression (feeds the atomicity rule)
==============  ====================================================

Escape comments waive a SITE, never a function: ``# blocking-ok:`` for
BLOCK/DEVICE_SYNC, ``# wallclock-ok:`` for WALLCLOCK/MONOTONIC,
``# unbounded-ok:`` for UNBOUNDED_QUEUE.
"""

from __future__ import annotations

import ast

from meshlint.astutil import comment_waiver, dotted_name, walk_body
from meshlint.callgraph import (
    EffectSite,
    FunctionInfo,
    ModuleInfo,
    Project,
)

BLOCK = "BLOCK"
LOG = "LOG"
WALLCLOCK = "WALLCLOCK"
MONOTONIC = "MONOTONIC"
DEVICE_SYNC = "DEVICE_SYNC"
UNBOUNDED_QUEUE = "UNBOUNDED_QUEUE"
AWAIT = "AWAIT"

WAIVER_MARKS = {
    BLOCK: "blocking-ok:",
    DEVICE_SYNC: "blocking-ok:",
    # a log line is an I/O stall: same waiver family as blocking
    LOG: "blocking-ok:",
    WALLCLOCK: "wallclock-ok:",
    MONOTONIC: "wallclock-ok:",
    UNBOUNDED_QUEUE: "unbounded-ok:",
}

_BLOCK_DOTTED = {
    "time.sleep", "os.system", "os.popen", "select.select",
    "socket.socket", "socket.create_connection", "socket.getaddrinfo",
}
_BLOCK_ROOTS = {"subprocess"}
_BLOCK_BARE = {"open", "input"}
# from-imported bare names that become blocking calls
_BLOCK_FROM = {"sleep": "time"}

_LOG_RECEIVERS = {"logger", "logging"}
_LOG_DOTTED = {"warnings.warn"}

_WALLCLOCK_TAILS = {"time", "time_ns", "now", "utcnow", "today"}
_WALLCLOCK_ROOTS = {"time", "datetime", "date"}
_MONOTONIC_TAILS = {
    "monotonic", "perf_counter", "monotonic_ns", "perf_counter_ns",
}

_SYNC_DOTTED = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get",
}
_SYNC_METHODS = {"block_until_ready", "item"}

_QUEUE_NAMES = {"Queue", "deque", "LifoQueue", "PriorityQueue",
                "SimpleQueue"}
_QUEUE_MODULES = {"asyncio", "collections", "queue"}
_BOUND_KWARGS = {"maxsize", "maxlen"}


def infer_effects(project: Project) -> None:
    """Fill ``FunctionInfo.effects`` for every function and
    ``ModuleInfo.module_effects`` (module-/class-level queue
    constructions and clock reads outside any function)."""
    for mod in project.modules.values():
        from_clocks = _from_imported_clocks(mod)
        for fn in project.functions.values():
            if fn.module != mod.name or fn.node is None:
                continue
            fn.effects = _scan(mod, fn.node, from_clocks)
        mod.module_effects = _scan_module_level(mod, from_clocks)


def _from_imported_clocks(mod: ModuleInfo) -> "dict[str, str]":
    """Bare names that arrived via ``from time import monotonic`` style
    imports, mapped to their effect kind."""
    out: dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ImportFrom) or node.module not in (
            "time", "datetime"
        ):
            continue
        for alias in node.names:
            bound = alias.asname or alias.name
            if alias.name in _MONOTONIC_TAILS:
                out[bound] = MONOTONIC
            elif alias.name in _WALLCLOCK_TAILS:
                out[bound] = WALLCLOCK
            elif alias.name in _BLOCK_FROM:
                out[bound] = BLOCK
    return out


def _scan(mod: ModuleInfo, root: ast.AST,
          from_clocks: "dict[str, str]") -> "list[EffectSite]":
    out: list[EffectSite] = []
    for node in walk_body(root):
        out.extend(_node_effects(mod, node, from_clocks))
    return out


def _scan_module_level(mod: ModuleInfo,
                       from_clocks: "dict[str, str]") -> "list[EffectSite]":
    """Module- and class-body statements (incl. dataclass
    ``field(default_factory=deque)``) — everything outside a def."""
    out: list[EffectSite] = []
    stack: list[ast.AST] = list(ast.iter_child_nodes(mod.tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        out.extend(_node_effects(mod, node, from_clocks))
        stack.extend(ast.iter_child_nodes(node))
    return out


def _site(mod: ModuleInfo, kind: str, lineno: int,
          detail: str) -> EffectSite:
    mark = WAIVER_MARKS.get(kind)
    waiver = comment_waiver(mod.lines, lineno, mark) if mark else None
    return EffectSite(kind=kind, lineno=lineno, detail=detail,
                      waiver=waiver)


def _node_effects(mod: ModuleInfo, node: ast.AST,
                  from_clocks: "dict[str, str]") -> "list[EffectSite]":
    out: list[EffectSite] = []
    if isinstance(node, ast.Await):
        out.append(EffectSite(kind=AWAIT, lineno=node.lineno,
                              detail="await"))
        return out
    if isinstance(node, ast.keyword) and node.arg == "default_factory":
        ctor = _queue_ctor_name(node.value)
        if ctor is not None:
            out.append(_site(mod, UNBOUNDED_QUEUE, node.value.lineno,
                             f"default_factory={ctor}"))
        return out
    if not isinstance(node, ast.Call):
        return out
    ctor = _queue_ctor_name(node.func)
    if ctor is not None and not _is_bounded_call(node):
        out.append(_site(mod, UNBOUNDED_QUEUE, node.lineno, f"{ctor}()"))
    fn = node.func
    # .block_until_ready()/.item() block on ANY receiver — checked before
    # dotted resolution so `arr.item()` and `self._k.block_until_ready()`
    # both count
    if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS:
        out.append(_site(mod, DEVICE_SYNC, node.lineno,
                         f".{fn.attr}() [any receiver]"))
    dotted = dotted_name(fn)
    if dotted is not None:
        parts = dotted.split(".")
        if len(parts) == 1:
            bare = parts[0]
            if bare in _BLOCK_BARE:
                out.append(_site(mod, BLOCK, node.lineno, f"{bare}()"))
            elif bare == "print":
                out.append(_site(mod, LOG, node.lineno, "print()"))
            elif bare in from_clocks:
                out.append(_site(mod, from_clocks[bare], node.lineno,
                                 f"{bare}() [from-imported]"))
        else:
            root, tail = parts[0], parts[-1]
            if dotted in _BLOCK_DOTTED or root in _BLOCK_ROOTS:
                out.append(_site(mod, BLOCK, node.lineno, f"{dotted}()"))
            elif root in _LOG_RECEIVERS or dotted in _LOG_DOTTED:
                out.append(_site(mod, LOG, node.lineno, f"{dotted}()"))
            elif dotted in _SYNC_DOTTED:
                out.append(_site(mod, DEVICE_SYNC, node.lineno,
                                 f"{dotted}()"))
            elif tail in _WALLCLOCK_TAILS and root in _WALLCLOCK_ROOTS:
                out.append(_site(mod, WALLCLOCK, node.lineno,
                                 f"{dotted}()"))
            elif tail in _MONOTONIC_TAILS and root == "time":
                out.append(_site(mod, MONOTONIC, node.lineno,
                                 f"{dotted}()"))
    return out


# ------------------------------------------------ unbounded-queue lore
# ported verbatim in spirit from lint_hotpath.py (ISSUE 5): asyncio/
# queue treat maxsize<=0 as UNLIMITED (the exact regression the rule
# catches) while deque(maxlen=0) is a real bound (an always-empty deque)

def _queue_ctor_name(node: ast.AST) -> "str | None":
    if isinstance(node, ast.Name) and node.id in _QUEUE_NAMES:
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and node.attr in _QUEUE_NAMES
        and isinstance(node.value, ast.Name)
        and node.value.id in _QUEUE_MODULES
    ):
        return f"{node.value.id}.{node.attr}"
    return None


def _bound_value_ok(node: ast.AST, is_deque: bool) -> bool:
    if not isinstance(node, ast.Constant):
        return True
    if node.value is None:
        return False
    if is_deque:
        return True
    return not (
        isinstance(node.value, int)
        and not isinstance(node.value, bool)
        and node.value <= 0
    )


def _is_bounded_call(call: ast.Call) -> bool:
    is_deque = _queue_ctor_name(call.func) in ("deque", "collections.deque")
    for kw in call.keywords:
        if kw.arg in _BOUND_KWARGS:
            return _bound_value_ok(kw.value, is_deque)
    if is_deque:
        return len(call.args) >= 2 and _bound_value_ok(call.args[1], True)
    return bool(call.args) and _bound_value_ok(call.args[0], False)


# ----------------------------------------------------- formatting scan
# used by the journal-append rules (not a per-function effect: f-strings
# are legal everywhere EXCEPT at flight-recorder append sites)

def formatting_sites(root: ast.AST) -> "list[tuple[int, str]]":
    out: list[tuple[int, str]] = []
    for node in ast.walk(root):
        if isinstance(node, ast.JoinedStr):
            out.append((node.lineno, "f-string"))
        elif isinstance(node, (ast.Dict, ast.DictComp, ast.SetComp,
                               ast.ListComp, ast.GeneratorExp)):
            out.append((node.lineno, f"{type(node).__name__} construction"))
        elif (
            isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.Mod)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)
        ):
            out.append((node.lineno, "%-formatting"))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"
        ):
            out.append((node.lineno, ".format() call"))
    return out


def function_effects(fn: FunctionInfo,
                     kinds: "frozenset[str]") -> "list[EffectSite]":
    return [e for e in fn.effects if e.kind in kinds and not e.waived]
