"""Violation records, chain rendering, and the machine-readable report."""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class ChainHop:
    qname: str
    path: str       # file DEFINING this hop's function
    lineno: int     # line of the call that reached it (root: its def line)
    call_path: str = ""  # file containing that call (the caller's file)


@dataclass
class Violation:
    rule: str
    message: str
    path: str
    lineno: int
    chain: "list[ChainHop]"
    effect: str
    detail: str

    def sort_key(self) -> tuple:
        return (self.path, self.lineno, self.rule, self.detail)


@dataclass
class Report:
    violations: "list[Violation]"
    stats: "dict[str, int]" = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    # -------------------------------------------------------- rendering
    def render(self, *, chains: bool = False) -> str:
        lines: list[str] = []
        for v in sorted(self.violations, key=Violation.sort_key):
            lines.append(f"{v.path}:{v.lineno}: [{v.rule}] {v.message}")
            if chains and len(v.chain) > 1:
                for depth, hop in enumerate(v.chain):
                    head = "  " + "   " * depth
                    if depth == 0:
                        at = (f" ({hop.path}:{hop.lineno})"
                              if hop.lineno else "")
                        lines.append(f"{head}{hop.qname}{at}")
                        continue
                    where = hop.call_path or hop.path
                    at = (f" (called at {where}:{hop.lineno})"
                          if hop.lineno else "")
                    lines.append(f"{head}-> {hop.qname}{at}")
                last = "  " + "   " * len(v.chain)
                lines.append(
                    f"{last}!! {v.effect} `{v.detail}` at "
                    f"{v.path}:{v.lineno}"
                )
        if self.violations:
            lines.append(
                f"meshlint: {len(self.violations)} violation(s) across "
                f"{len({v.rule for v in self.violations})} rule(s)"
            )
        else:
            lines.append(self.summary_line())
        return "\n".join(lines)

    def summary_line(self) -> str:
        s = self.stats
        return (
            "meshlint: clean "
            f"({s.get('modules', 0)} modules, "
            f"{s.get('functions', 0)} functions, "
            f"{s.get('edges', 0)} call edges, "
            f"{s.get('roots', 0)} declared roots "
            f"[{s.get('hotpath', 0)} hotpath / "
            f"{s.get('no_wallclock', 0)} no_wallclock], "
            f"{s.get('async_defs', 0)} async defs stall-checked, "
            f"{s.get('waived', 0)} waived sites)"
        )

    # ------------------------------------------------------------- json
    def to_json(self) -> str:
        return json.dumps({
            "version": 1,
            "ok": self.ok,
            "stats": self.stats,
            "violations": [
                {
                    "rule": v.rule,
                    "message": v.message,
                    "path": v.path,
                    "lineno": v.lineno,
                    "effect": v.effect,
                    "detail": v.detail,
                    "chain": [
                        # path = the hop's DEFINING file; lineno = the
                        # call line that reached it, which lives in
                        # call_path (the caller's file) — navigate with
                        # call_path:lineno, like the text renderer
                        {"qname": h.qname, "path": h.path,
                         "lineno": h.lineno, "call_path": h.call_path}
                        for h in v.chain
                    ],
                }
                for v in sorted(self.violations, key=Violation.sort_key)
            ],
        }, indent=2, sort_keys=True) + "\n"
