"""Profile XLA vs Pallas decode attention on the current backend.

Times a FULL decode dispatch (the engine's scheduler unit: ``steps``
decode_step_ring iterations under lax.scan + one ring consolidation) for
each attention implementation, at the bench's TinyLlama shapes and the
Llama-3-8B paged shapes.  This is the measurement that decides what
``RuntimeConfig(attention_impl="auto")`` resolves to on hardware
(VERDICT round-1 "weak" #3).

Usage:  python scripts/profile_attention.py [--config tinyllama|llama8b|both]
Prints one JSON line per (config, impl) with ms/dispatch and tok/s.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def profile_dense(preset_name: str, B: int, W: int, steps: int, impls,
                  rows=None) -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from calfkit_tpu.inference import model as M
    from calfkit_tpu.inference.config import preset

    cfg = preset(preset_name)
    dtype = jnp.bfloat16
    params = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda k: M.init_params(cfg, k), jax.random.key(0)),
    )
    k = jnp.zeros((cfg.n_layers, B, cfg.n_kv_heads, W, cfg.head_dim), dtype)
    v = jnp.zeros_like(k)
    last = jnp.ones((B,), jnp.int32)
    lens = jnp.full((B,), W // 2, jnp.int32)

    for impl in impls:
        def dispatch(params, k, v, last, lens):
            ring = (
                jnp.zeros((cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim), dtype),
                jnp.zeros((cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim), dtype),
            )

            def step(carry, t):
                ring, last = carry
                lg, ring = M.decode_step_ring(
                    params, cfg, last[:, None], (k, v), ring, t, lens,
                    attn_impl=impl,
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                return (ring, nxt), nxt

            (ring, last), toks = lax.scan(step, (ring, last), jnp.arange(steps))
            k2, v2 = M.consolidate_ring((k, v), ring, lens)
            return k2, v2, toks

        fn = jax.jit(dispatch, donate_argnums=(1, 2))
        k2, v2, toks = fn(params, k, v, last, lens)
        toks.block_until_ready()
        k, v = k2, v2
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            k2, v2, toks = fn(params, k, v, last, lens)
            toks.block_until_ready()
            times.append(time.perf_counter() - t0)
            k, v = k2, v2
        ms = min(times) * 1000.0
        row = {
            "path": "decode",
            "config": f"{preset_name} dense B={B} W={W} steps={steps}",
            "impl": impl,
            "ms_per_dispatch": round(ms, 2),
            "tok_s": round(B * steps / (ms / 1000.0), 1),
        }
        print(json.dumps(row))
        if rows is not None:
            rows.append(row)


def profile_prefill(preset_name: str, R: int, S: int, impls,
                    rows=None) -> None:
    """Time one prefill-wave forward ([R, S] into a fresh scratch cache)
    per attention impl — the flash kernel's shape of interest."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from calfkit_tpu.inference import model as M
    from calfkit_tpu.inference.config import preset

    cfg = preset(preset_name)
    dtype = jnp.bfloat16
    params = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda k: M.init_params(cfg, k), jax.random.key(0)),
    )
    tokens = jnp.ones((R, S), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (R, S))
    lens = jnp.full((R,), S, jnp.int32)

    for impl in impls:
        def prefill(params, tokens):
            scratch = (
                jnp.zeros((cfg.n_layers, R, cfg.n_kv_heads, S, cfg.head_dim), dtype),
                jnp.zeros((cfg.n_layers, R, cfg.n_kv_heads, S, cfg.head_dim), dtype),
            )
            logits, _ = M.forward(
                params, cfg, tokens, pos, scratch, lens, attn_impl=impl
            )
            return logits[:, -1]

        fn = jax.jit(prefill)
        out = fn(params, tokens)
        np.asarray(jnp.float32(out)).sum()  # force a real fetch
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = fn(params, tokens)
            np.asarray(jnp.float32(out)).sum()
            times.append(time.perf_counter() - t0)
        ms = min(times) * 1000.0
        row = {
            "path": "prefill",
            "config": f"{preset_name} prefill R={R} S={S}",
            "impl": impl,
            "ms_per_dispatch": round(ms, 2),
            "prefill_tok_s": round(R * S / (ms / 1000.0), 1),
        }
        print(json.dumps(row))
        if rows is not None:
            rows.append(row)


def profile_paged(preset_name: str, B: int, wpages: int, steps: int,
                  page: int, impls, n_layers: int | None = None,
                  rows=None) -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from calfkit_tpu.inference import model as M
    from calfkit_tpu.inference.config import preset

    cfg = preset(preset_name, **({"n_layers": n_layers} if n_layers else {}))
    dtype = jnp.bfloat16
    params = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda k: M.init_params(cfg, k), jax.random.key(0)),
    )
    N = B * wpages + 1
    pool_k = jnp.zeros((cfg.n_layers, N, cfg.n_kv_heads, page, cfg.head_dim), dtype)
    pool_v = jnp.zeros_like(pool_k)
    tables = (jnp.arange(B * wpages, dtype=jnp.int32).reshape(B, wpages) + 1)
    last = jnp.ones((B,), jnp.int32)
    lens = jnp.full((B,), wpages * page // 2, jnp.int32)
    active = jnp.ones((B,), bool)

    for impl in impls:
        def dispatch(params, pool_k, pool_v, tables, last, lens):
            ring = (
                jnp.zeros((cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim), dtype),
                jnp.zeros((cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim), dtype),
            )

            def step(carry, t):
                ring, last = carry
                lg, ring = M.decode_step_ring_paged(
                    params, cfg, last[:, None], (pool_k, pool_v), tables,
                    ring, t, lens, wpages=wpages, attn_impl=impl,
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                return (ring, nxt), nxt

            (ring, last), toks = lax.scan(step, (ring, last), jnp.arange(steps))
            pk, pv = M.consolidate_ring_paged(
                (pool_k, pool_v), ring, tables, lens, active
            )
            return pk, pv, toks

        fn = jax.jit(dispatch, donate_argnums=(1, 2))
        pk, pv, toks = fn(params, pool_k, pool_v, tables, last, lens)
        toks.block_until_ready()
        pool_k, pool_v = pk, pv
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            pk, pv, toks = fn(params, pool_k, pool_v, tables, last, lens)
            toks.block_until_ready()
            times.append(time.perf_counter() - t0)
            pool_k, pool_v = pk, pv
        ms = min(times) * 1000.0
        row = {
            "path": "paged_decode",
            "config": f"{preset_name} paged B={B} wpages={wpages} page={page} steps={steps}",
            "impl": impl,
            "ms_per_dispatch": round(ms, 2),
            "tok_s": round(B * steps / (ms / 1000.0), 1),
        }
        print(json.dumps(row))
        if rows is not None:
            rows.append(row)


def _time_min(fn, *args) -> float:
    """THE timing law shared by the ragged profilers: warm once (jit
    build outside the window), then min of 5 synced reps, in ms — one
    copy, so the cross-path comparison that steers
    ``attention_impl="auto"`` cannot drift between paths."""
    out = fn(*args)
    out.block_until_ready()
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = fn(*args)
        out.block_until_ready()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0


def _ragged_rows(B: int, S: int, W: int):
    """Mixed ragged row kinds at wave shape [B, S]: one third decode
    (q_len=1, start=kv_len=lens), one third prefill-chunk (q_len=S,
    start=offset, kv_len=offset+S), one third spec-verify (q_len=k+1,
    start=kv_len=base_lens) — the three row kinds the unified wave and
    the verify dispatch actually serve (calfkit_tpu/inference/ragged.py
    descriptor vocabulary).  Queries past a row's true q_len are padding
    the kernel computes-and-ignores, exactly as in production."""
    import numpy as np

    lens0 = W // 2
    offset = W // 4
    starts = np.zeros((B,), np.int32)
    kv_lens = np.zeros((B,), np.int32)
    for b in range(B):
        kind = b % 3
        if kind == 0:  # decode row
            starts[b] = lens0
            kv_lens[b] = lens0
        elif kind == 1:  # prefill-chunk row
            starts[b] = offset
            kv_lens[b] = offset + S
        else:  # verify row (k+1 queries against the settled cache)
            starts[b] = lens0
            kv_lens[b] = lens0
    return starts, kv_lens


def profile_ragged(preset_name: str, B: int, W: int, S: int, impls,
                   rows=None) -> None:
    """Time the ragged multi-query attention kernel (dense window) on a
    mixed decode/chunk/verify wave — the shape ``attention_impl="auto"``
    resolves the VERIFY dispatch (and any ragged consumer) with (path
    ``ragged``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from calfkit_tpu.inference import model as M
    from calfkit_tpu.inference import pallas_attention as P
    from calfkit_tpu.inference.config import preset

    cfg = preset(preset_name)
    dtype = jnp.bfloat16
    K, hd = cfg.n_kv_heads, cfg.head_dim
    H = cfg.n_heads
    G = H // K
    starts_np, kv_np = _ragged_rows(B, S, W)
    q = jnp.ones((B, S, H, hd), dtype)
    k = jnp.zeros((cfg.n_layers, B, K, W, hd), dtype)
    v = jnp.zeros_like(k)
    starts = jnp.asarray(starts_np)
    kv_lens = jnp.asarray(kv_np)

    for impl in impls:
        # EVERY operand is a traced jit argument (q, caches, starts,
        # kv_lens) in BOTH branches — a baked-in constant query would
        # let XLA fold/specialize asymmetrically and skew the winner
        # artifact that steers production attention_impl="auto"
        if impl.startswith("pallas"):
            interpret = impl == "pallas_interpret"

            def dispatch(q_in, k, v, st, kv, interpret=interpret):
                qg = q_in.reshape(B, S, K, G, hd).transpose(0, 2, 1, 3, 4)

                def one_layer(_, kv_layer):
                    lk, lv = kv_layer
                    o, m, z = P.ragged_attention_pallas(
                        qg, lk, lv, st, kv, interpret=interpret
                    )
                    out = o / jnp.maximum(z[..., None], 1e-30)
                    return None, out.astype(qg.dtype)

                _, outs = lax.scan(one_layer, None, (k, v))
                return outs
        else:

            def dispatch(q_in, k, v, st, kv):
                def one_layer(_, kv_layer):
                    lk, lv = kv_layer
                    return None, M.ragged_attention_xla(
                        q_in, lk, lv, st, kv
                    )

                _, outs = lax.scan(one_layer, None, (k, v))
                return outs

        ms = _time_min(jax.jit(dispatch), q, k, v, starts, kv_lens)
        row = {
            "path": "ragged",
            "config": f"{preset_name} ragged B={B} S={S} W={W}",
            "impl": impl,
            "ms_per_dispatch": round(ms, 2),
            "ragged_q_tok_s": round(B * S / (ms / 1000.0), 1),
        }
        print(json.dumps(row))
        if rows is not None:
            rows.append(row)


def profile_ragged_paged(preset_name: str, B: int, wpages: int, S: int,
                         page: int, impls, n_layers: int | None = None,
                         rows=None) -> None:
    """Paged analog of :func:`profile_ragged`: the ragged kernel reading
    through block tables (path ``paged_ragged`` — resolves the paged
    verify dispatch under ``attention_impl="auto"``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from calfkit_tpu.inference import model as M
    from calfkit_tpu.inference import pallas_attention as P
    from calfkit_tpu.inference.config import preset

    cfg = preset(preset_name, **({"n_layers": n_layers} if n_layers else {}))
    dtype = jnp.bfloat16
    K, hd = cfg.n_kv_heads, cfg.head_dim
    H = cfg.n_heads
    G = H // K
    W = wpages * page
    N = B * wpages + 1
    pool_k = jnp.zeros((cfg.n_layers, N, K, page, hd), dtype)
    pool_v = jnp.zeros_like(pool_k)
    tables = (jnp.arange(B * wpages, dtype=jnp.int32).reshape(B, wpages) + 1)
    starts_np, kv_np = _ragged_rows(B, S, W)
    q = jnp.ones((B, S, H, hd), dtype)
    starts = jnp.asarray(starts_np)
    kv_lens = jnp.asarray(kv_np)

    for impl in impls:
        # all operands traced, both branches (see profile_ragged)
        if impl.startswith("pallas"):
            interpret = impl == "pallas_interpret"

            def dispatch(q_in, pool_k, pool_v, tb, st, kv,
                         interpret=interpret):
                qg = q_in.reshape(B, S, K, G, hd).transpose(0, 2, 1, 3, 4)

                def one_layer(_, layer):
                    o, m, z = P.ragged_attention_paged_pallas(
                        qg, pool_k, pool_v, layer, tb, st, kv,
                        wpages=wpages, interpret=interpret,
                    )
                    out = o / jnp.maximum(z[..., None], 1e-30)
                    return None, out.astype(qg.dtype)

                _, outs = lax.scan(
                    one_layer, None,
                    jnp.arange(pool_k.shape[0], dtype=jnp.int32),
                )
                return outs
        else:

            def dispatch(q_in, pool_k, pool_v, tb, st, kv):
                def one_layer(_, kv_layer):
                    lk, lv = kv_layer
                    return None, M.ragged_attention_paged_xla(
                        q_in, lk, lv, tb, st, kv, wpages=wpages,
                    )

                _, outs = lax.scan(one_layer, None, (pool_k, pool_v))
                return outs

        ms = _time_min(
            jax.jit(dispatch), q, pool_k, pool_v, tables, starts, kv_lens
        )
        row = {
            "path": "paged_ragged",
            "config": (
                f"{preset_name} paged-ragged B={B} S={S} "
                f"wpages={wpages} page={page}"
            ),
            "impl": impl,
            "ms_per_dispatch": round(ms, 2),
            "ragged_q_tok_s": round(B * S / (ms / 1000.0), 1),
        }
        print(json.dumps(row))
        if rows is not None:
            rows.append(row)


def compute_winners(rows: list[dict], margin: float = 0.97) -> dict:
    """Per-path winner for the auto-resolution artifact.

    Conservative rule: "pallas" wins a path only when it beat XLA by
    >= (1 - margin) on EVERY config measured for that path — a single
    losing shape keeps the safe XLA default (the engine serves all shapes
    with one setting per path, so the winner must generalize)."""
    by_path: dict[str, dict[str, dict[str, float]]] = {}
    for row in rows:
        by_path.setdefault(row["path"], {}).setdefault(
            row["config"], {}
        )[row["impl"]] = row["ms_per_dispatch"]
    winners: dict[str, str] = {}
    for path, configs in by_path.items():
        comparable = [
            c for c in configs.values() if "xla" in c and "pallas" in c
        ]
        if comparable and all(
            c["pallas"] < margin * c["xla"] for c in comparable
        ):
            winners[path] = "pallas"
        elif comparable:
            winners[path] = "xla"
    return winners


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="both",
                    choices=("tinyllama", "llama8b", "both"))
    ap.add_argument("--impls", default="xla,pallas")
    ap.add_argument("--out", default=None, help=(
        "write the per-path winner artifact here (the engine's "
        "attention_impl='auto' reads it via $CALFKIT_ATTN_PROFILE or "
        "~/.cache/calfkit_tpu_attn_profile.json)"
    ))
    ap.add_argument("--install", action="store_true", help=(
        "also copy the artifact to ~/.cache/calfkit_tpu_attn_profile.json "
        "so auto picks it up on this machine"
    ))
    args = ap.parse_args()
    impls = args.impls.split(",")

    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/calfkit_tpu_xla"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:  # noqa: BLE001 - cache is best-effort
        pass

    platform = jax.devices()[0].platform
    print(f"# platform={platform} devices={len(jax.devices())}",
          file=sys.stderr)
    rows: list[dict] = []
    if args.config in ("tinyllama", "both"):
        # bench tinyllama shape: bs=64, window bucket 1024, 32-step dispatch
        profile_dense("tinyllama-1.1b", B=64, W=1024, steps=32, impls=impls,
                      rows=rows)
        profile_paged("tinyllama-1.1b", B=64, wpages=16, steps=32, page=64,
                      impls=impls, rows=rows)
        profile_prefill("tinyllama-1.1b", R=8, S=512, impls=impls, rows=rows)
        # ragged multi-query shapes (ISSUE 10 satellite): mixed
        # decode/chunk/verify waves, so attention_impl="auto" resolves
        # the ragged kernels (verify dispatch, unified-wave consumers)
        # from measured winners instead of riding the legacy paths
        profile_ragged("tinyllama-1.1b", B=64, W=1024, S=16, impls=impls,
                       rows=rows)
        # spec-verify width (k+1 = 5): the other production ragged shape
        profile_ragged("tinyllama-1.1b", B=64, W=1024, S=5, impls=impls,
                       rows=rows)
        profile_ragged_paged("tinyllama-1.1b", B=64, wpages=16, S=16,
                             page=64, impls=impls, rows=rows)
    if args.config in ("llama8b", "both"):
        # bench llama8b ATTENTION shapes (bs=32, 4 pages/row reserve) on a
        # 4-layer slice: bf16 zero-params at full depth would not fit 16 GB
        # next to the pool, and the impl comparison is per-layer anyway
        profile_paged("llama-3-8b", B=32, wpages=4, steps=32, page=64,
                      impls=impls, n_layers=4, rows=rows)
        profile_ragged_paged("llama-3-8b", B=32, wpages=4, S=5, page=64,
                             impls=impls, n_layers=4, rows=rows)

    if args.out or args.install:
        verdict = {
            "platform": platform,
            "captured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "winners": compute_winners(rows),
            "rows": rows,
        }
        payload = json.dumps(verdict, indent=1)
        targets = []
        if args.out:
            targets.append(args.out)
        if args.install:
            targets.append(
                os.path.expanduser("~/.cache/calfkit_tpu_attn_profile.json")
            )
        for target in targets:
            os.makedirs(os.path.dirname(os.path.abspath(target)), exist_ok=True)
            with open(target, "w") as f:
                f.write(payload)
        print(json.dumps({"winners": verdict["winners"],
                          "written": targets}))


if __name__ == "__main__":
    main()
