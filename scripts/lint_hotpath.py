"""CI lint: the decode hot path must stay free of per-token overhead.

Parses ``calfkit_tpu/inference/engine.py`` and checks the dispatch-loop
functions (the per-tick code that runs between device dispatches) for
constructs the telemetry PR explicitly bans there (ISSUE 2):

- ``time.time()`` — the wall clock syscall is slower than
  ``time.perf_counter()`` and wrong for durations; latency attribution in
  the dispatch loop must use perf_counter.
- logging calls (``logger.*``, ``logging.*``, ``print``) — a log line per
  dispatch (let alone per token) is an I/O stall on the serving path;
  telemetry goes through the O(1) metrics instruments instead.
- blocking device→host syncs (``np.asarray``/``np.array``/
  ``jax.device_get``/``.block_until_ready()``/``.item()`` on device
  arrays) anywhere in the OVERLAP-critical functions except the single
  designated sync point ``_sync_host`` (ISSUE 3): double-buffered
  dispatch only reclaims the inter-dispatch bubble if the launch path
  never stalls on the device, and a stray ``np.asarray`` silently turns
  overlap back into lockstep.  ``jnp.asarray`` (host→device) stays legal.

Exit 0 when clean; exit 1 with a file:line listing otherwise.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ENGINE = Path(__file__).resolve().parent.parent / (
    "calfkit_tpu/inference/engine.py"
)

# the dispatch loop: every function that runs per decode tick (or inside
# one) on the scheduler/decode threads
HOT_FUNCTIONS = {
    "_decode_tick",
    "_decode_tick_lockstep",
    "_launch_decode",
    "_land_decode",
    "_drain_decode",
    "_decode_args",
    "_retire_args",
    "_free_deferred",
    "_observe_gap",
    "_spec_decode_tick",
    "_long_decode_tick",
    "_note_dispatch",
    "_observe",
    "_update_active_gauge",
    "_sync_metric_counters",
    "_record_token",
    "_retire_slot",
    "_retirement_near",
    "_retirement_bound",
    "_deliver_batch",
}

# pure host-side metric/heap helpers: never handed a device array, so the
# blocking-sync ban would be noise there.  Everything ELSE in the dispatch
# loop is overlap-critical — a blocking device→host sync reopens the
# serialization bubble the double buffering exists to close.  Deriving the
# overlap set by subtraction (instead of a second hand-maintained list)
# means a future dispatch-loop function added to HOT_FUNCTIONS gets the
# sync ban automatically.  The single legal sync point is ``_sync_host``
# (checked to exist below).
METRIC_HELPERS = {
    "_observe",
    "_update_active_gauge",
    "_sync_metric_counters",
    "_retirement_near",
    "_retirement_bound",
}
OVERLAP_FUNCTIONS = HOT_FUNCTIONS - METRIC_HELPERS

BANNED_CALL_NAMES = {"print"}
BANNED_ATTR_CALLS = {
    ("time", "time"),  # wall clock on the hot path
}
BANNED_RECEIVERS = {"logger", "logging"}  # any logging call

# blocking device→host syncs, banned in OVERLAP_FUNCTIONS (jnp.asarray is
# host→device and stays legal; the host-side numpy constructors np.zeros/
# np.full/np.ascontiguousarray never block on the device)
BANNED_SYNC_ATTRS = {
    ("np", "asarray"),
    ("np", "array"),
    ("numpy", "asarray"),
    ("numpy", "array"),
    ("jax", "device_get"),
}
BANNED_SYNC_METHODS = {"block_until_ready", "item"}  # any receiver


def _violations(tree: ast.AST) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in HOT_FUNCTIONS:
            continue
        overlap = node.name in OVERLAP_FUNCTIONS
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            if isinstance(fn, ast.Name) and fn.id in BANNED_CALL_NAMES:
                out.append((call.lineno, f"{node.name}: call to {fn.id}()"))
            elif isinstance(fn, ast.Attribute):
                if overlap and fn.attr in BANNED_SYNC_METHODS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: .{fn.attr}() — blocking device "
                         "sync outside _sync_host")
                    )
                if not isinstance(fn.value, ast.Name):
                    continue
                pair = (fn.value.id, fn.attr)
                if pair in BANNED_ATTR_CALLS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {pair[0]}.{pair[1]}() (use "
                         "time.perf_counter)")
                    )
                elif fn.value.id in BANNED_RECEIVERS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {fn.value.id}.{fn.attr}() — no "
                         "logging on the dispatch loop")
                    )
                elif overlap and pair in BANNED_SYNC_ATTRS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {pair[0]}.{pair[1]}() — blocking "
                         "host sync outside the designated _sync_host "
                         "point")
                    )
    return sorted(out)


def main() -> int:
    source = ENGINE.read_text()
    tree = ast.parse(source, filename=str(ENGINE))
    found = _violations(tree)
    # the guarded function set must actually exist — a rename must break
    # this lint loudly, not silently lint nothing
    names = {
        n.name
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    missing = {
        "_decode_tick", "_record_token", "_note_dispatch",
        "_launch_decode", "_land_decode", "_sync_host",
    } - names
    if missing:
        print(f"lint_hotpath: guarded functions missing from engine.py: "
              f"{sorted(missing)} (update HOT_FUNCTIONS)")
        return 1
    if found:
        for line, message in found:
            print(f"{ENGINE}:{line}: {message}")
        print(f"lint_hotpath: {len(found)} hot-path violation(s)")
        return 1
    print(
        f"lint_hotpath: clean ({len(HOT_FUNCTIONS & names)} dispatch-loop "
        "functions checked)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
