"""CI lint: the decode hot path must stay free of per-token overhead.

Parses ``calfkit_tpu/inference/engine.py`` and checks the dispatch-loop
functions (the per-tick code that runs between device dispatches) for
constructs the telemetry PR explicitly bans there (ISSUE 2):

- ``time.time()`` — the wall clock syscall is slower than
  ``time.perf_counter()`` and wrong for durations; latency attribution in
  the dispatch loop must use perf_counter.
- logging calls (``logger.*``, ``logging.*``, ``print``) — a log line per
  dispatch (let alone per token) is an I/O stall on the serving path;
  telemetry goes through the O(1) metrics instruments instead.

Exit 0 when clean; exit 1 with a file:line listing otherwise.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ENGINE = Path(__file__).resolve().parent.parent / (
    "calfkit_tpu/inference/engine.py"
)

# the dispatch loop: every function that runs per decode tick (or inside
# one) on the scheduler/decode threads
HOT_FUNCTIONS = {
    "_decode_tick",
    "_spec_decode_tick",
    "_note_dispatch",
    "_observe",
    "_update_active_gauge",
    "_sync_metric_counters",
    "_record_token",
    "_retire_slot",
    "_retirement_near",
    "_retirement_bound",
    "_deliver_batch",
}

BANNED_CALL_NAMES = {"print"}
BANNED_ATTR_CALLS = {
    ("time", "time"),  # wall clock on the hot path
}
BANNED_RECEIVERS = {"logger", "logging"}  # any logging call


def _violations(tree: ast.AST) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in HOT_FUNCTIONS:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            if isinstance(fn, ast.Name) and fn.id in BANNED_CALL_NAMES:
                out.append((call.lineno, f"{node.name}: call to {fn.id}()"))
            elif isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name
            ):
                pair = (fn.value.id, fn.attr)
                if pair in BANNED_ATTR_CALLS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {pair[0]}.{pair[1]}() (use "
                         "time.perf_counter)")
                    )
                elif fn.value.id in BANNED_RECEIVERS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {fn.value.id}.{fn.attr}() — no "
                         "logging on the dispatch loop")
                    )
    return sorted(out)


def main() -> int:
    source = ENGINE.read_text()
    tree = ast.parse(source, filename=str(ENGINE))
    found = _violations(tree)
    # the guarded function set must actually exist — a rename must break
    # this lint loudly, not silently lint nothing
    names = {
        n.name
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    missing = {"_decode_tick", "_record_token", "_note_dispatch"} - names
    if missing:
        print(f"lint_hotpath: guarded functions missing from engine.py: "
              f"{sorted(missing)} (update HOT_FUNCTIONS)")
        return 1
    if found:
        for line, message in found:
            print(f"{ENGINE}:{line}: {message}")
        print(f"lint_hotpath: {len(found)} hot-path violation(s)")
        return 1
    print(
        f"lint_hotpath: clean ({len(HOT_FUNCTIONS & names)} dispatch-loop "
        "functions checked)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
