"""CI lint: the decode hot path must stay free of per-token overhead.

Parses ``calfkit_tpu/inference/engine.py`` and checks the dispatch-loop
functions (the per-tick code that runs between device dispatches) for
constructs the telemetry PR explicitly bans there (ISSUE 2):

- ``time.time()`` — the wall clock syscall is slower than
  ``time.perf_counter()`` and wrong for durations; latency attribution in
  the dispatch loop must use perf_counter.
- logging calls (``logger.*``, ``logging.*``, ``print``) — a log line per
  dispatch (let alone per token) is an I/O stall on the serving path;
  telemetry goes through the O(1) metrics instruments instead.
- blocking device→host syncs (``np.asarray``/``np.array``/
  ``jax.device_get``/``.block_until_ready()``/``.item()`` on device
  arrays) anywhere in the OVERLAP-critical functions except the single
  designated sync point ``_sync_host`` (ISSUE 3): double-buffered
  dispatch only reclaims the inter-dispatch bubble if the launch path
  never stalls on the device, and a stray ``np.asarray`` silently turns
  overlap back into lockstep.  ``jnp.asarray`` (host→device) stays legal.
- flight-recorder appends (ISSUE 4): EVERY ``*._journal.append(...)``
  call site in engine.py — hot function or not — must pass precomputed
  values only: no f-strings, no ``%``/``.format`` formatting, no
  dict/set/comprehension construction in the arguments.  The same bans
  (plus logging and ``time.time``) apply to the body of
  ``FlightRecorder.append`` itself in observability/flightrec.py: the
  journal's O(1)-per-event promise is the whole reason it may stay on
  in production.
- unbounded queues (ISSUE 5): every ``asyncio.Queue()`` / ``deque()``
  construction (including ``default_factory=asyncio.Queue`` /
  ``default_factory=deque``) in engine.py and mesh/dispatch.py must
  either pass an explicit bound (``maxsize=``/``maxlen=``) or carry an
  ``# unbounded-ok: <why>`` justification on its own line or the line
  above.  The overload-protection PR exists because two silent unbounded
  deques turned saturation into invisible queue-wait growth — a new one
  must state which admission bound, permit, or reaper makes it safe.
- the fleet router's per-dispatch selection path (ISSUE 7): the
  functions every routed call runs through — ``FleetRouter.select`` /
  ``_outstanding``, every policy ``select`` body, the registry's
  ``eligible``/``replicas``/``parse_replicas`` reads, and the pure
  selection primitives — must not block (no ``time.sleep``, no
  ``open``/``input``/``subprocess``, no ``await``-bearing broker
  round-trips: these are sync functions by contract, enforced by their
  ``def``-not-``async def`` shape), must not log or call ``time.time``,
  and the fleet modules may not construct unbounded queues/deques
  without the same ``# unbounded-ok:`` justification.
- the fleet simulator (ISSUE 11): NO wall-clock read anywhere in
  ``calfkit_tpu/sim/`` — ``time.time``/``time.monotonic``/
  ``time.perf_counter``/``datetime.now``/``datetime.utcnow`` are all
  banned.  The simulator's determinism contract (byte-identical
  SIM.json per seed) holds only while every timestamp flows through the
  ``cancellation.wall_clock`` seam; one stray host-clock read silently
  turns a reproducible report into a flaky one.  A genuinely needed
  host-time read (none exist today) must carry ``# wallclock-ok:``
  with a reason, mirroring the unbounded-queue rule.

Exit 0 when clean; exit 1 with a file:line listing otherwise.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ENGINE = Path(__file__).resolve().parent.parent / (
    "calfkit_tpu/inference/engine.py"
)
FLIGHTREC = Path(__file__).resolve().parent.parent / (
    "calfkit_tpu/observability/flightrec.py"
)
DISPATCH = Path(__file__).resolve().parent.parent / (
    "calfkit_tpu/mesh/dispatch.py"
)
FLEET_DIR = Path(__file__).resolve().parent.parent / "calfkit_tpu/fleet"
LEASES = Path(__file__).resolve().parent.parent / "calfkit_tpu/leases.py"
SIM_DIR = Path(__file__).resolve().parent.parent / "calfkit_tpu/sim"

# caller-liveness reads on the reaper's sweep path (ISSUE 10): the
# engine calls these per registered-expiry pop, between device
# dispatches — no logging, no wall-clock syscall (they read the
# cancellation.wall_clock seam), no blocking calls.  Loud-miss on
# rename, like every other guarded set.
LEASE_READ_FUNCTIONS = {
    "note_beat", "note_admission", "lease_lapsed", "lease_expiry",
}

# the dispatch loop: every function that runs per decode tick (or inside
# one) on the scheduler/decode threads
HOT_FUNCTIONS = {
    "_decode_tick",
    "_decode_tick_lockstep",
    "_launch_decode",
    "_land_decode",
    "_drain_decode",
    "_decode_args",
    "_retire_args",
    "_free_deferred",
    "_observe_gap",
    "_spec_decode_tick",
    "_long_decode_tick",
    "_note_dispatch",
    "_observe",
    "_update_active_gauge",
    "_sync_metric_counters",
    "_record_token",
    "_retire_slot",
    "_retirement_near",
    "_retirement_bound",
    "_deliver_batch",
    # ragged unified waves (ISSUE 6): the fused-lane tick/launch, the
    # budget/absorption math, and the wave-formation packing loop — the
    # descriptor build and packing must stay sync-free and never format
    # or journal-format on the lane (the fused launch is the overlap
    # launch; a stray host sync would serialize the unified dispatch)
    "_ragged_tick",
    "_launch_ragged",
    "_stage_pend",
    "_absorb_fits",
    "_ragged_wave_cap",
    "_form_wave",
    # caller liveness (ISSUE 10): the orphan reaper's per-pass sweep and
    # the lease-registration sites run on the serve loop between device
    # dispatches — same no-logging/no-time.time/no-formatting contract
    # as the deadline reaper they're shaped after
    "_check_orphans",
    "_check_deadlines",
    "_submit_lease",
    "_drop_lease",
}

# pure host-side metric/heap helpers: never handed a device array, so the
# blocking-sync ban would be noise there.  Everything ELSE in the dispatch
# loop is overlap-critical — a blocking device→host sync reopens the
# serialization bubble the double buffering exists to close.  Deriving the
# overlap set by subtraction (instead of a second hand-maintained list)
# means a future dispatch-loop function added to HOT_FUNCTIONS gets the
# sync ban automatically.  The single legal sync point is ``_sync_host``
# (checked to exist below).
METRIC_HELPERS = {
    "_observe",
    "_update_active_gauge",
    "_sync_metric_counters",
    "_retirement_near",
    "_retirement_bound",
    # serve-loop heap sweeps: pure host state, never handed device arrays
    "_check_orphans",
    "_check_deadlines",
    "_submit_lease",
    "_drop_lease",
}
OVERLAP_FUNCTIONS = HOT_FUNCTIONS - METRIC_HELPERS

BANNED_CALL_NAMES = {"print"}
BANNED_ATTR_CALLS = {
    ("time", "time"),  # wall clock on the hot path
}
BANNED_RECEIVERS = {"logger", "logging"}  # any logging call

# blocking device→host syncs, banned in OVERLAP_FUNCTIONS (jnp.asarray is
# host→device and stays legal; the host-side numpy constructors np.zeros/
# np.full/np.ascontiguousarray never block on the device)
BANNED_SYNC_ATTRS = {
    ("np", "asarray"),
    ("np", "array"),
    ("numpy", "asarray"),
    ("numpy", "array"),
    ("jax", "device_get"),
}
BANNED_SYNC_METHODS = {"block_until_ready", "item"}  # any receiver


def _violations(tree: ast.AST) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in HOT_FUNCTIONS:
            continue
        overlap = node.name in OVERLAP_FUNCTIONS
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            if isinstance(fn, ast.Name) and fn.id in BANNED_CALL_NAMES:
                out.append((call.lineno, f"{node.name}: call to {fn.id}()"))
            elif isinstance(fn, ast.Attribute):
                if overlap and fn.attr in BANNED_SYNC_METHODS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: .{fn.attr}() — blocking device "
                         "sync outside _sync_host")
                    )
                if not isinstance(fn.value, ast.Name):
                    continue
                pair = (fn.value.id, fn.attr)
                if pair in BANNED_ATTR_CALLS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {pair[0]}.{pair[1]}() (use "
                         "time.perf_counter)")
                    )
                elif fn.value.id in BANNED_RECEIVERS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {fn.value.id}.{fn.attr}() — no "
                         "logging on the dispatch loop")
                    )
                elif overlap and pair in BANNED_SYNC_ATTRS:
                    out.append(
                        (call.lineno,
                         f"{node.name}: {pair[0]}.{pair[1]}() — blocking "
                         "host sync outside the designated _sync_host "
                         "point")
                    )
    return sorted(out)


def _is_journal_append(call: ast.Call) -> bool:
    """``<anything>._journal.append(...)``."""
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr == "append"
        and isinstance(fn.value, ast.Attribute)
        and fn.value.attr == "_journal"
    )


def _formatting_violations(
    root: ast.AST, where: str
) -> "list[tuple[int, str]]":
    """The allocation/formatting bans shared by journal-append call sites
    and the append body: f-strings, %%-on-a-literal, ``.format()``, and
    dict/set/comprehension construction."""
    out: list[tuple[int, str]] = []
    for node in ast.walk(root):
        if isinstance(node, ast.JoinedStr):
            out.append((node.lineno, f"{where}: f-string"))
        elif isinstance(node, (ast.Dict, ast.DictComp, ast.SetComp,
                               ast.ListComp, ast.GeneratorExp)):
            out.append(
                (node.lineno,
                 f"{where}: {type(node).__name__} construction")
            )
        elif (
            isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.Mod)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)
        ):
            out.append((node.lineno, f"{where}: %-formatting"))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"
        ):
            out.append((node.lineno, f"{where}: .format() call"))
    return out


def _journal_site_violations(tree: ast.AST) -> "list[tuple[int, str]]":
    """Every journal-append call site in engine.py, in ANY function (the
    event-loop admission path must stay as dict-churn-free as the decode
    thread — the journal is on by default in production)."""
    out: list[tuple[int, str]] = []
    for call in ast.walk(tree):
        if isinstance(call, ast.Call) and _is_journal_append(call):
            for arg in [*call.args, *call.keywords]:
                out.extend(
                    _formatting_violations(arg, "journal append site")
                )
    return out


def _append_body_violations(tree: ast.AST) -> "list[tuple[int, str]]":
    """The FlightRecorder.append body itself: the O(1) lock-free promise.
    Returns a sentinel violation when the method cannot be found — a
    rename must break this lint loudly, not silently lint nothing."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "FlightRecorder":
            for fn in node.body:
                if (
                    isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name == "append"
                ):
                    out = _formatting_violations(fn, "FlightRecorder.append")
                    for call in ast.walk(fn):
                        if not isinstance(call, ast.Call):
                            continue
                        f = call.func
                        if isinstance(f, ast.Name) and f.id in BANNED_CALL_NAMES:
                            out.append(
                                (call.lineno,
                                 f"FlightRecorder.append: {f.id}()")
                            )
                        elif isinstance(f, ast.Attribute) and isinstance(
                            f.value, ast.Name
                        ):
                            pair = (f.value.id, f.attr)
                            if pair in BANNED_ATTR_CALLS:
                                out.append(
                                    (call.lineno,
                                     "FlightRecorder.append: time.time()")
                                )
                            elif f.value.id in BANNED_RECEIVERS:
                                out.append(
                                    (call.lineno,
                                     f"FlightRecorder.append: "
                                     f"{f.value.id}.{f.attr}() — no logging")
                                )
                    return out
    return [(0, "FlightRecorder.append not found in flightrec.py "
               "(update lint_hotpath)")]


# ------------------------------------------------- fleet selection path
# (ISSUE 7) every routed call runs these synchronously between "the
# caller wants a topic" and "the publish happens": a blocking call or a
# log line here is a per-request stall multiplied across the fleet.
# parse_replicas is deliberately NOT guarded: it is the shared
# render/CLI read helper and owns the undecodable-record debug floor
# (lazily formatted); the per-dispatch functions below must stay clean.
FLEET_SELECT_FUNCTIONS = {
    "router.py": {"select", "_outstanding", "_sweep_inflight"},
    "policy.py": {"select", "_least", "affinity_key_for"},
    "registry.py": {
        "eligible", "replicas", "_parsed", "eligibility_verdict", "replica",
    },
    "selection.py": {
        "lane_of", "stable_hash", "rendezvous_rank", "page_aligned_prefix",
    },
    # failure recovery (ISSUE 9): the dead-placement probe runs every
    # probe_interval per OUTSTANDING call, and the stream dedupe filter
    # runs per token-step event — same no-blocking/no-logging contract
    "failover.py": {"placement_verdict", "filter"},
}

_FLEET_BANNED_CALLS = {"print", "open", "input", "exec", "eval"}
_FLEET_BANNED_ATTR_CALLS = {
    ("time", "time"),
    ("time", "sleep"),
    ("os", "system"),
    ("subprocess", "run"),
    ("subprocess", "Popen"),
    ("socket", "socket"),
}


def _fleet_violations() -> "list[tuple[Path, int, str]]":
    out: list[tuple[Path, int, str]] = []
    for filename, wanted in sorted(FLEET_SELECT_FUNCTIONS.items()):
        path = FLEET_DIR / filename
        if not path.exists():
            out.append((path, 0, "fleet module missing (update lint_hotpath)"))
            continue
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        found_names: set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in wanted:
                continue
            found_names.add(node.name)
            if isinstance(node, ast.AsyncFunctionDef):
                # the selection path is sync BY CONTRACT: an await here
                # means a broker round-trip snuck into per-call routing
                out.append(
                    (path, node.lineno,
                     f"{node.name}: selection-path function became async "
                     "(no broker round-trips per routed call)")
                )
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                fn = call.func
                if isinstance(fn, ast.Name) and fn.id in _FLEET_BANNED_CALLS:
                    out.append(
                        (path, call.lineno,
                         f"{node.name}: blocking/banned call {fn.id}()")
                    )
                elif isinstance(fn, ast.Attribute) and isinstance(
                    fn.value, ast.Name
                ):
                    pair = (fn.value.id, fn.attr)
                    if pair in _FLEET_BANNED_ATTR_CALLS:
                        out.append(
                            (path, call.lineno,
                             f"{node.name}: {pair[0]}.{pair[1]}() on the "
                             "selection path")
                        )
                    elif fn.value.id in BANNED_RECEIVERS:
                        out.append(
                            (path, call.lineno,
                             f"{node.name}: {fn.value.id}.{fn.attr}() — no "
                             "logging on the selection path")
                        )
        missing = wanted - found_names
        if missing:
            out.append(
                (path, 0,
                 f"guarded selection functions missing: {sorted(missing)} "
                 "(update FLEET_SELECT_FUNCTIONS)")
            )
        # the unbounded-queue rule covers the whole fleet module set: a
        # router buffering routed calls in an unbounded queue would
        # rebuild exactly the silent-saturation failure ISSUE 5 killed
        out.extend(_unbounded_queue_violations(tree, source, path))
    return out


# ---------------------------------------------------- unbounded queues
# (ISSUE 5) a Queue/deque with no bound and no justification is exactly
# how the pre-overload engine turned saturation into silent queue growth

_QUEUE_NAMES = {"Queue", "deque", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_BOUND_KWARGS = {"maxsize", "maxlen"}
_OK_MARK = "unbounded-ok:"


def _queue_ctor_name(node: ast.AST) -> "str | None":
    """'asyncio.Queue' / 'deque' when ``node`` references a queue type."""
    if isinstance(node, ast.Name) and node.id in _QUEUE_NAMES:
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and node.attr in _QUEUE_NAMES
        and isinstance(node.value, ast.Name)
        and node.value.id in ("asyncio", "collections", "queue")
    ):
        return f"{node.value.id}.{node.attr}"
    return None


def _bound_value_ok(node: ast.AST, is_deque: bool) -> bool:
    """A bound expression counts unless it is statically, verifiably
    unbounded: a literal ``None`` for either type, or a literal ``<= 0``
    for Queue kinds (asyncio/queue treat ``maxsize<=0`` as UNLIMITED —
    the exact regression the rule exists to catch — while a deque
    ``maxlen=0`` is a real bound: an always-empty deque).  Non-literal
    expressions pass; the lint cannot evaluate them."""
    if not isinstance(node, ast.Constant):
        return True
    if node.value is None:
        return False
    if is_deque:
        return True
    return not (
        isinstance(node.value, int)
        and not isinstance(node.value, bool)
        and node.value <= 0
    )


def _is_bounded_call(call: ast.Call) -> bool:
    is_deque = _queue_ctor_name(call.func) in ("deque", "collections.deque")
    for kw in call.keywords:
        if kw.arg in _BOUND_KWARGS:
            return _bound_value_ok(kw.value, is_deque)
    # positional bound: deque(iterable, maxlen) / Queue(maxsize)
    if is_deque:
        return len(call.args) >= 2 and _bound_value_ok(call.args[1], True)
    return bool(call.args) and _bound_value_ok(call.args[0], False)


def _justified(lines: "list[str]", lineno: int) -> bool:
    """``# unbounded-ok:`` on the construction line or anywhere in the
    contiguous comment block immediately above it (multi-line
    justifications sit above the statement)."""
    if 1 <= lineno <= len(lines) and _OK_MARK in lines[lineno - 1]:
        return True
    n = lineno - 1
    while 1 <= n <= len(lines) and lines[n - 1].lstrip().startswith("#"):
        if _OK_MARK in lines[n - 1]:
            return True
        n -= 1
    return False


def _unbounded_queue_violations(
    tree: ast.AST, source: str, where: Path
) -> "list[tuple[Path, int, str]]":
    lines = source.splitlines()
    out: list[tuple[Path, int, str]] = []
    for node in ast.walk(tree):
        name = None
        lineno = 0
        if isinstance(node, ast.Call):
            ctor = _queue_ctor_name(node.func)
            if ctor is not None and not _is_bounded_call(node):
                name, lineno = f"{ctor}()", node.lineno
        elif isinstance(node, ast.keyword) and node.arg == "default_factory":
            ctor = _queue_ctor_name(node.value)
            if ctor is not None:
                name, lineno = f"default_factory={ctor}", node.value.lineno
        if name and not _justified(lines, lineno):
            out.append(
                (where, lineno,
                 f"unbounded {name} without an '# {_OK_MARK} <why>' "
                 "justification (name the admission bound / permit / "
                 "reaper that bounds it)")
            )
    return out


# ------------------------------------------------- simulator wall clock
# (ISSUE 11) the determinism contract: every timestamp in the sim
# package flows through the cancellation.wall_clock seam.  Any direct
# host-clock read would leak real time into SIM.json and break the
# byte-identical repeat-run guarantee the perf gate stands on.

_SIM_BANNED_CLOCK_NAMES = {
    "time", "monotonic", "perf_counter",
    "time_ns", "monotonic_ns", "perf_counter_ns",
    "now", "utcnow", "today",
}
# dotted suffixes: matches `time.time()`, `datetime.datetime.now()`,
# `datetime.date.today()` — any attribute-chain call whose LAST segment
# is a clock read and whose chain starts at the time/datetime modules
_SIM_BANNED_CLOCK_ROOTS = {"time", "datetime", "date"}
_SIM_OK_MARK = "wallclock-ok:"
# the promoted chaos-test helpers that predate the simulator and run
# only in REAL-time chaos tests (never inside a scenario's event loop):
# resume_heartbeat re-arms the real tick loop's monotonic stamp
_SIM_ALLOWED_FUNCTIONS = {"resume_heartbeat"}


def _sim_violations() -> "list[tuple[Path, int, str]]":
    out: list[tuple[Path, int, str]] = []
    if not SIM_DIR.exists():
        return [(SIM_DIR, 0, "sim package missing (update lint_hotpath)")]
    checked = 0
    for path in sorted(SIM_DIR.glob("*.py")):
        source = path.read_text()
        lines = source.splitlines()
        tree = ast.parse(source, filename=str(path))
        checked += 1
        # map every call to its enclosing function name (for allowlist)
        enclosing: dict[int, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        enclosing.setdefault(id(sub), node.name)
        # from-imported clock names ("from time import monotonic") make
        # bare-name calls bannable; without the import a local helper
        # coincidentally named `time` stays legal
        from_imported: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module in (
                "time", "datetime"
            ):
                for alias in node.names:
                    from_imported.add(alias.asname or alias.name)
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            dotted = _dotted_name(call.func)
            banned = False
            if dotted is not None:
                parts = dotted.split(".")
                if len(parts) == 1:
                    # bare call: banned only when the name arrived via a
                    # from-import of the time/datetime modules
                    banned = (
                        parts[0] in _SIM_BANNED_CLOCK_NAMES
                        and parts[0] in from_imported
                    )
                else:
                    banned = (
                        parts[-1] in _SIM_BANNED_CLOCK_NAMES
                        and parts[0] in _SIM_BANNED_CLOCK_ROOTS
                    )
            if not banned:
                continue
            if enclosing.get(id(call)) in _SIM_ALLOWED_FUNCTIONS:
                continue
            if _sim_justified(lines, call.lineno):
                continue
            out.append(
                (path, call.lineno,
                 f"sim wall-clock read {dotted}() — all "
                 "timestamps must flow through cancellation.wall_clock "
                 f"(or carry '# {_SIM_OK_MARK} <why>')")
            )
        out.extend(_unbounded_queue_violations(tree, source, path))
    if checked == 0:
        out.append(
            (SIM_DIR, 0, "sim package empty (update lint_hotpath)")
        )
    return out


def _dotted_name(node: ast.AST) -> "str | None":
    """``a.b.c`` for a Name/Attribute chain; None for computed bases
    (subscripts, calls) the lint cannot resolve statically."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _sim_justified(lines: "list[str]", lineno: int) -> bool:
    if 1 <= lineno <= len(lines) and _SIM_OK_MARK in lines[lineno - 1]:
        return True
    n = lineno - 1
    while 1 <= n <= len(lines) and lines[n - 1].lstrip().startswith("#"):
        if _SIM_OK_MARK in lines[n - 1]:
            return True
        n -= 1
    return False


def _leases_violations() -> "list[tuple[Path, int, str]]":
    """The lease store's sweep-path reads (ISSUE 10): same no-blocking /
    no-logging / no-time.time contract as the fleet selection path."""
    out: list[tuple[Path, int, str]] = []
    if not LEASES.exists():
        return [(LEASES, 0, "leases module missing (update lint_hotpath)")]
    tree = ast.parse(LEASES.read_text(), filename=str(LEASES))
    found: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in LEASE_READ_FUNCTIONS:
            continue
        found.add(node.name)
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            if isinstance(fn, ast.Name) and fn.id in _FLEET_BANNED_CALLS:
                out.append(
                    (LEASES, call.lineno,
                     f"{node.name}: blocking/banned call {fn.id}()")
                )
            elif isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name
            ):
                pair = (fn.value.id, fn.attr)
                if pair in _FLEET_BANNED_ATTR_CALLS:
                    out.append(
                        (LEASES, call.lineno,
                         f"{node.name}: {pair[0]}.{pair[1]}() on the "
                         "orphan-sweep path")
                    )
                elif fn.value.id in BANNED_RECEIVERS:
                    out.append(
                        (LEASES, call.lineno,
                         f"{node.name}: {fn.value.id}.{fn.attr}() — no "
                         "logging on the orphan-sweep path")
                    )
    missing = LEASE_READ_FUNCTIONS - found
    if missing:
        out.append(
            (LEASES, 0,
             f"guarded lease functions missing: {sorted(missing)} "
             "(update LEASE_READ_FUNCTIONS)")
        )
    return out


def main() -> int:
    source = ENGINE.read_text()
    tree = ast.parse(source, filename=str(ENGINE))
    found = _violations(tree)
    found += _journal_site_violations(tree)
    fr_tree = ast.parse(FLIGHTREC.read_text(), filename=str(FLIGHTREC))
    fr_found = _append_body_violations(fr_tree)
    if fr_found:
        for line, message in sorted(fr_found):
            print(f"{FLIGHTREC}:{line}: {message}")
    dispatch_source = DISPATCH.read_text()
    dispatch_tree = ast.parse(dispatch_source, filename=str(DISPATCH))
    queue_found = _unbounded_queue_violations(tree, source, ENGINE)
    queue_found += _unbounded_queue_violations(
        dispatch_tree, dispatch_source, DISPATCH
    )
    queue_found += _fleet_violations()
    queue_found += _leases_violations()
    queue_found += _sim_violations()
    if queue_found:
        for path, line, message in sorted(queue_found):
            print(f"{path}:{line}: {message}")
    # the guarded function set must actually exist — a rename must break
    # this lint loudly, not silently lint nothing
    names = {
        n.name
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    missing = {
        "_decode_tick", "_record_token", "_note_dispatch",
        "_launch_decode", "_land_decode", "_sync_host",
        "_ragged_tick", "_launch_ragged", "_form_wave",
        "_check_orphans", "_submit_lease",
    } - names
    if missing:
        print(f"lint_hotpath: guarded functions missing from engine.py: "
              f"{sorted(missing)} (update HOT_FUNCTIONS)")
        return 1
    if found or fr_found or queue_found:
        for line, message in sorted(found):
            print(f"{ENGINE}:{line}: {message}")
        print(
            f"lint_hotpath: {len(found) + len(fr_found) + len(queue_found)} "
            "hot-path violation(s)"
        )
        return 1
    journal_sites = sum(
        isinstance(c, ast.Call) and _is_journal_append(c)
        for c in ast.walk(tree)
    )
    fleet_guarded = sum(len(v) for v in FLEET_SELECT_FUNCTIONS.values())
    sim_files = len(list(SIM_DIR.glob("*.py"))) if SIM_DIR.exists() else 0
    print(
        f"lint_hotpath: clean ({len(HOT_FUNCTIONS & names)} dispatch-loop "
        f"functions, {journal_sites} journal-append sites, "
        f"{fleet_guarded} fleet selection-path functions checked, "
        f"{sim_files} sim modules wall-clock-free, "
        "unbounded-queue rule enforced)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
