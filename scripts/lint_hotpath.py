"""CI lint: the hot-path effect rules — now a thin shim over meshlint.

Every rule this script historically enforced by hand-curated name lists
(ISSUE 2/3/4/5/6/7/10/11: no logging/wall-clock/blocking-sync in the
dispatch loop, O(1) flight-recorder appends, unbounded-queue
justification, the fleet selection path, the lease sweep, the simulator
wall-clock ban) now lives in ``scripts/meshlint/`` — an AST call-graph
analyzer that propagates constraints declared at the definition site
(``calfkit_tpu/effects.py`` markers) through the transitive call
closure, so a hot function calling a helper two modules away that logs
or blocks is caught, and a rename can never silently drop coverage.

This shim keeps CI wiring and muscle memory working:
``python scripts/lint_hotpath.py`` == ``python -m meshlint --chains``.
See docs/static-analysis.md for the rule and vocabulary reference.
"""

from __future__ import annotations

import sys
from pathlib import Path

_SCRIPTS = Path(__file__).resolve().parent
if str(_SCRIPTS) not in sys.path:
    sys.path.insert(0, str(_SCRIPTS))

from meshlint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--chains", *sys.argv[1:]]))
