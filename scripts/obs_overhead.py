"""Observability-overhead microbench → OBS_OVERHEAD.json.

SCHED_OVERHEAD-style host-stub measurement: the device is removed (decode
and prefill jits replaced by shape-faithful instant stubs), so the tok/s
measured is pure host-side scheduler + telemetry cost.  The workload runs
with observability ON (the default: histograms observed per dispatch,
tracer enabled) and OFF (``REGISTRY.set_enabled(False)`` +
``TRACER.set_enabled(False)``).

Acceptance bar (ISSUE 2, extended by ISSUE 4): **< 2% decode throughput
delta**, now covering the flight recorder too.  Estimators in the
artifact:

- ``implied_delta_pct`` (THE gated value): the per-dispatch
  instrumentation bundle (exactly what ``_note_dispatch`` adds — two
  histogram observes, a gauge set, the counter sync) PLUS the dispatch
  loop's flight-recorder appends (launch + land), each timed directly
  over many iterations, converted to a throughput delta against the
  measured host cost per dispatch.  Deterministic at the sub-percent
  level.
- ``journal_implied_delta_pct``: the flight-recorder share alone
  (measured appends-per-dispatch × directly-timed append cost).
- ``ledger_implied_delta_pct`` (ISSUE 17): the run-ledger share — the
  full per-supervised-call append bundle (begin → attempt → token →
  outcome → finish, including LRU eviction at cap) timed directly,
  amortized over the call's dispatches.  The ledger rides the CLIENT
  supervisor path, so this is the honest ledger-on/off delta: its cost
  is exactly these appends (there is no other ledger work on the hot
  path), and it folds into the same gated ``implied_delta_pct`` bar.
- ``capacity_implied_delta_pct`` (ISSUE 19): the page-attribution
  share — the ledger's per-request steady-state mutation bundle
  (alloc → acquire → release → free, the O(1) mirrors at the engine's
  existing page sites) timed directly and amortized over the call's
  dispatches.  Attribution is ALWAYS on for paged engines, so this
  folds into the gated ``implied_delta_pct``.  The occupancy sampler is
  opt-in (``capacity_samples=0`` default — one attribute check per
  dispatch); its per-append cost is reported separately as
  ``capacity_sampler_implied_delta_pct`` and NOT folded into the gated
  value, matching the shipped default.
- ``ab_delta_pct`` / ``journal_ab_delta_pct`` (evidence, not gated):
  best-of-N tok/s with observability on vs off, and with the journal on
  (``flightrec_events`` default) vs off (0).  On a shared-CPU container,
  individual runs jitter ±15-25% — far above a 2% effect — so the A/B
  numbers are reported for transparency but cannot gate (observed here:
  the sign flips rep-to-rep).

Prints one JSON line; ``--out PATH`` writes the committed artifact.
Exits non-zero when the bar is violated.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from scripts._stub_common import (  # noqa: E402
    stub_prefill_lens,
    stub_retire_block,
)
from calfkit_tpu.observability.metrics import REGISTRY  # noqa: E402
from calfkit_tpu.observability.trace import TRACER  # noqa: E402

BS = 64
STEPS = 32
NEW_TOKENS = 128
REPS = 8
DELTA_BAR_PCT = 2.0


def _stub_jits(engine: InferenceEngine, bs: int) -> None:
    """Shape-faithful instant stubs at the JIT boundary (the
    scripts/sched_overhead.py discipline): all real host-side scheduler
    AND telemetry work still runs and is what gets measured."""

    def fake_decode(window: int, steps: int | None = None, sampled: bool = False):
        steps = steps or engine.runtime.decode_steps_per_dispatch

        def run(params, k, v, *rest):
            toks = jnp.ones((steps, bs), jnp.int32)
            if engine._paged:
                tables, last, lens, active, done_prev, _stop, hard_end, *_ = rest
            else:
                last, lens, active, done_prev, _stop, hard_end, *_ = rest
            # mirror the device-retirement contract (the engine retires on
            # the stub's verdict)
            _act, n_valid, done, new_lens = stub_retire_block(
                active, done_prev, lens, hard_end, steps
            )
            return k, v, last, new_lens, toks, n_valid, done

        return run

    def fake_prefill_jit(bucket: int, rows: int, sampled: bool = False):
        def run(params, k, v, last, lens, tokens, slots, true_lens,
                slot_keys, temp, top_k, top_p,
                seeds, w_temp, w_top_k, w_top_p,
                tables=None, page_rows=None, scatter_ids=None):
            firsts = jnp.ones((rows,), jnp.int32)
            lens = stub_prefill_lens(lens, slots, true_lens)
            return k, v, tables, last, lens, slot_keys, temp, top_k, top_p, firsts

        return run

    engine._decode_jit = fake_decode
    engine._prefill_jit = fake_prefill_jit


async def _one_rep(flightrec_events: int = 4096) -> dict:
    """One full serve of 2*BS requests; returns decode tok/s (host wall)
    plus the flight-recorder's append count and the dispatch count (the
    measured appends-per-dispatch feeds the implied journal estimator)."""
    config = preset("debug", max_seq_len=256)
    runtime = RuntimeConfig(
        max_batch_size=BS, max_seq_len=256, prefill_chunk=32,
        decode_steps_per_dispatch=STEPS, flightrec_events=flightrec_events,
    )
    engine = InferenceEngine(config, runtime)
    _stub_jits(engine, BS)
    await engine.start()

    async def one(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            list(range(1, 18)), max_new_tokens=NEW_TOKENS
        ):
            n += 1
        return n

    t0 = time.perf_counter()
    counts = await asyncio.gather(*[one(i) for i in range(2 * BS)])
    wall = time.perf_counter() - t0
    tokens = engine.stats.decode_tokens
    appended = engine._journal.counts()["appended"]
    dispatches = engine.stats.decode_dispatches
    await engine.stop()
    assert all(c == NEW_TOKENS for c in counts), "stub served wrong lengths"
    return {
        "tok_s": tokens / wall,
        "appended": appended,
        "dispatches": dispatches,
    }


def _instrumentation_bundle_us(iters: int = 20000) -> float:
    """Median-of-5 timing of one dispatch's instrumentation: exactly the
    calls ``_note_dispatch`` adds — dual histogram observes (process +
    per-engine registries), the gauge set, and the locked counter sync
    against a drifting stats object."""
    import threading

    from calfkit_tpu.inference.engine import EngineStats, _engine_metrics
    from calfkit_tpu.observability.metrics import MetricsRegistry

    m = _engine_metrics()
    own = _engine_metrics(MetricsRegistry())
    stats = EngineStats()
    counted = {"decode_tokens": 0, "prefill_tokens": 0,
               "spec_proposed": 0, "spec_accepted": 0}
    lock = threading.Lock()
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        for i in range(iters):
            stats.decode_tokens += BS * STEPS  # the sync always has work
            for pair_key, value in (
                ("decode_dispatch_ms", 18.0), ("inter_token_ms", 18.0 / STEPS)
            ):
                m[pair_key].observe(value)
                own[pair_key].observe(value)
            m["active_requests"].set(BS)
            with lock:
                for key in counted:
                    value = getattr(stats, key)
                    if value != counted[key]:
                        m[key].inc(value - counted[key])
                        counted[key] = value
        samples.append((time.perf_counter() - t0) / iters * 1e6)
    samples.sort()
    return samples[2]


def _journal_append_us(iters: int = 100000) -> float:
    """Median-of-5 timing of one flight-recorder append — the exact call
    the dispatch loop's launch/land sites pay."""
    from calfkit_tpu.observability.flightrec import (
        EV_DISPATCH_LAUNCH,
        FlightRecorder,
    )

    journal = FlightRecorder(4096)
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(iters):
            journal.append(EV_DISPATCH_LAUNCH, None, -1, STEPS, BS)
        samples.append((time.perf_counter() - t0) / iters * 1e6)
    samples.sort()
    return samples[2]


def _ledger_call_us(iters: int = 50000) -> float:
    """Median-of-5 timing of one supervised call's ENTIRE run-ledger
    bundle (ISSUE 17): begin_run + note_attempt + add_tokens +
    note_outcome + finish_run, with a fresh run id per call so the LRU
    eviction at cap is billed too — the steady state of a long-lived
    client."""
    from calfkit_tpu.observability.runledger import RunLedger

    ledger = RunLedger(cap=1024)
    samples = []
    for rep in range(5):
        t0 = time.perf_counter()
        for i in range(iters):
            run_id = "r%05d-%d" % (i, rep)
            ledger.begin_run(
                run_id, agent="svc", client_id="c", started_at=1.0
            )
            ledger.note_attempt(
                run_id, attempt_no=0, correlation_id="c0", kind="first",
                placement="svc@i0", agent="svc", started_at=1.0,
            )
            ledger.add_tokens(run_id, "c0", 1)
            ledger.note_outcome(
                run_id, "c0", outcome="ok", finished_at=2.0
            )
            ledger.finish_run(run_id, outcome="ok", finished_at=2.0)
        samples.append((time.perf_counter() - t0) / iters * 1e6)
    samples.sort()
    return samples[2]


def _capacity_ledger_us(iters: int = 50000) -> float:
    """Median-of-5 timing of one request's ENTIRE page-attribution
    bundle (ISSUE 19): the steady-state mirrors the engine pays per
    admission/retirement on the paged path — alloc (private pages to the
    slot) → acquire (the shared prefix chain) → release → free.  The
    chain-registration ``transfer`` happens once per NEW chain, not per
    request, so it is not billed here; attribution has no other hot-path
    work."""
    from calfkit_tpu.observability.capacity import PageLedger

    ledger = PageLedger(4096)
    shared = list(range(4000, 4004))
    ledger.transfer(999, shared, [b"chain-%d" % p for p in shared])
    ledger.release(shared)
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        for i in range(iters):
            slot = i & 63
            ledger.alloc(slot, 4, "corr-%05d" % slot, "run-%05d" % slot,
                         "decode")
            ledger.acquire(shared)
            ledger.release(shared)
            ledger.free(slot)
        samples.append((time.perf_counter() - t0) / iters * 1e6)
    samples.sort()
    return samples[2]


def _capacity_sampler_us(iters: int = 100000) -> float:
    """Median-of-5 timing of one occupancy-timeline append — the exact
    call ``_note_dispatch`` pays per landing when ``capacity_samples``
    is nonzero (the opt-in path; at 0 the cost is a single attribute
    check and this estimator does not apply)."""
    from calfkit_tpu.observability.capacity import CapacitySampler

    sampler = CapacitySampler(4096, label="bench")
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(iters):
            sampler.append(512, 512, 128, BS, 0, float(STEPS), 0.0)
        samples.append((time.perf_counter() - t0) / iters * 1e6)
    samples.sort()
    return samples[2]


async def run() -> dict:
    # one discarded warmup rep: jit tracing / allocator warmup must not be
    # billed to either mode
    await _one_rep()
    on_runs: list[float] = []
    off_runs: list[float] = []
    appends_per_dispatch = 0.0
    for rep in range(REPS):
        order = (True, False) if rep % 2 == 0 else (False, True)
        for mode_on in order:
            REGISTRY.set_enabled(mode_on)
            TRACER.set_enabled(mode_on)
            result = await _one_rep()
            (on_runs if mode_on else off_runs).append(result["tok_s"])
            if result["dispatches"]:
                # the journal records regardless of the registry switch:
                # any rep measures the real appends-per-dispatch ratio
                appends_per_dispatch = (
                    result["appended"] / result["dispatches"]
                )
    REGISTRY.set_enabled(True)
    TRACER.set_enabled(True)
    # flight-recorder A/B (ISSUE 4): journal on (default ring) vs off
    # (flightrec_events=0), observability on in both — same jitter caveat
    # as the registry A/B, reported as evidence only
    journal_off_runs = [
        (await _one_rep(flightrec_events=0))["tok_s"]
        for _ in range(max(2, REPS // 2))
    ]
    best_on, best_off = max(on_runs), max(off_runs)
    best_journal_off = max(journal_off_runs)
    ab_delta_pct = (best_off - best_on) / best_off * 100.0
    journal_ab_delta_pct = (
        (best_journal_off - best_on) / best_journal_off * 100.0
    )

    # the gated estimator: time EXACTLY the per-dispatch instrumentation
    # bundle + the journal's measured appends-per-dispatch, convert to a
    # throughput delta against the measured host cost of one dispatch
    # (host-stub throughput is host-bound, so the added fraction of
    # dispatch time IS the throughput delta)
    bundle_us = _instrumentation_bundle_us()
    append_us = _journal_append_us()
    journal_us = append_us * appends_per_dispatch
    # run ledger (ISSUE 17): the per-call append bundle amortizes over
    # the call's dispatches (NEW_TOKENS tokens / STEPS per dispatch)
    ledger_call_us = _ledger_call_us()
    dispatches_per_call = max(1.0, NEW_TOKENS / STEPS)
    ledger_us = ledger_call_us / dispatches_per_call
    # page attribution (ISSUE 19): the per-request mutation bundle
    # amortizes the same way; always on for paged engines, so it joins
    # the gated sum.  The occupancy sampler is opt-in (capacity_samples=0
    # default) — reported, not gated.
    capacity_call_us = _capacity_ledger_us()
    capacity_us = capacity_call_us / dispatches_per_call
    sampler_append_us = _capacity_sampler_us()
    tokens_per_dispatch = BS * STEPS
    host_us_per_dispatch = tokens_per_dispatch / best_on * 1e6
    journal_implied_delta_pct = journal_us / host_us_per_dispatch * 100.0
    ledger_implied_delta_pct = ledger_us / host_us_per_dispatch * 100.0
    capacity_implied_delta_pct = capacity_us / host_us_per_dispatch * 100.0
    capacity_sampler_implied_delta_pct = (
        sampler_append_us / host_us_per_dispatch * 100.0
    )
    implied_delta_pct = (
        (bundle_us + journal_us + ledger_us + capacity_us)
        / host_us_per_dispatch * 100.0
    )
    ok = implied_delta_pct < DELTA_BAR_PCT
    return {
        "metric": f"obs_overhead[host-stub bs={BS} steps={STEPS}]",
        "value": round(implied_delta_pct, 4),
        "unit": "pct_decode_throughput_delta_implied",
        "bar_pct": DELTA_BAR_PCT,
        "ok": ok,
        "instrumentation_us_per_dispatch": round(bundle_us, 3),
        "journal_append_us": round(append_us, 4),
        "journal_appends_per_dispatch": round(appends_per_dispatch, 3),
        "journal_us_per_dispatch": round(journal_us, 3),
        "journal_implied_delta_pct": round(journal_implied_delta_pct, 4),
        "ledger_call_us": round(ledger_call_us, 3),
        "ledger_us_per_dispatch": round(ledger_us, 3),
        "ledger_implied_delta_pct": round(ledger_implied_delta_pct, 4),
        "capacity_call_us": round(capacity_call_us, 3),
        "capacity_us_per_dispatch": round(capacity_us, 3),
        "capacity_implied_delta_pct": round(capacity_implied_delta_pct, 4),
        "capacity_sampler_append_us": round(sampler_append_us, 4),
        "capacity_sampler_implied_delta_pct": round(
            capacity_sampler_implied_delta_pct, 4
        ),
        "host_us_per_dispatch": round(host_us_per_dispatch, 1),
        "tok_s_observability_on": round(best_on, 1),
        "tok_s_observability_off": round(best_off, 1),
        "tok_s_journal_off": round(best_journal_off, 1),
        "ab_delta_pct_best_of": round(ab_delta_pct, 3),
        "journal_ab_delta_pct_best_of": round(journal_ab_delta_pct, 3),
        "ab_note": (
            "A/B wall-clock deltas on this container jitter far above the "
            "2% bar (sign flips rep-to-rep); the implied delta from the "
            "directly-timed instrumentation bundle + journal appends is "
            "the gated value"
        ),
        "runs_on": [round(r, 1) for r in on_runs],
        "runs_off": [round(r, 1) for r in off_runs],
        "runs_journal_off": [round(r, 1) for r in journal_off_runs],
        "reps": REPS,
        "new_tokens_per_request": NEW_TOKENS,
        "requests": 2 * BS,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
