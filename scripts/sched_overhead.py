"""Host-side scheduler-overhead microbench (VERDICT r2 item 8).

Measures what the CONTINUOUS-BATCHING SCHEDULER itself costs per decode
dispatch at bs=128 — admission, wave formation, page reservation,
retirement tracking, cancellation reaping, token fan-out — with the device
entirely removed: every jit cache is replaced by a host-side stub that
returns correctly-shaped numpy/jnp arrays instantly.  The printed number
is therefore pure Python bookkeeping; on hardware it rides alongside
dispatches that take O(ms), so scheduler cost should stay far below one
dispatch (<~1 ms at bs=128) or the engine's scale claim is hollow.

Prints one JSON line:
  {"metric": "scheduler_overhead_us_per_dispatch[bs=128 paged]", ...}
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402

BS = 128
STEPS = 4
NEW_TOKENS = 16
REQUESTS = 4 * BS


def _stub_jits(engine: InferenceEngine) -> None:
    """Replace the device path with shape-faithful host stubs."""

    def fake_decode(window: int, steps: int, sampled: bool = False):
        def run(params, k, v, *rest):
            # token 1 is never a stop (eos defaults elsewhere); [steps, B]
            toks = jnp.ones((steps, BS), jnp.int32)
            if engine._paged:
                tables, last, lens, *_ = rest
            else:
                last, lens, *_ = rest
            return k, v, last, lens, toks

        return run

    def fake_prefill_wave(wave, bucket):
        # mimic _prefill_wave's host-visible effects without device work
        lens = [len(r.prompt) for r in wave]
        firsts = np.ones((len(wave),), np.int64)
        engine._land_wave(wave, np.asarray(lens), firsts, 0.0)

    engine._decode_jit = fake_decode
    engine._prefill_wave = fake_prefill_wave


async def run() -> dict:
    config = preset("debug", max_seq_len=256)
    runtime = RuntimeConfig(
        max_batch_size=BS, max_seq_len=256, prefill_chunk=32,
        decode_steps_per_dispatch=STEPS, kv_layout="paged", page_size=16,
        num_kv_pages=2 * BS + 1,
    )
    engine = InferenceEngine(config, runtime)
    _stub_jits(engine)
    await engine.start()

    async def one(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            [1 + (i % 50), 3, 5], max_new_tokens=NEW_TOKENS
        ):
            n += 1
        return n

    # warm the scheduler paths
    await asyncio.gather(*[one(i) for i in range(BS)])
    stats = engine.stats
    stats.decode_dispatches = 0
    stats.decode_time_s = 0.0

    t0 = time.perf_counter()
    counts = await asyncio.gather(*[one(i) for i in range(REQUESTS)])
    wall = time.perf_counter() - t0
    await engine.stop()

    assert all(c == NEW_TOKENS for c in counts), "stub served wrong lengths"
    dispatches = stats.decode_dispatches
    # wall here is ~pure scheduler: stubs return instantly
    per_dispatch_us = wall / max(1, dispatches) * 1e6
    per_token_us = wall / (len(counts) * NEW_TOKENS) * 1e6
    return {
        "metric": f"scheduler_overhead_us_per_dispatch[bs={BS} paged host-stub]",
        "value": round(per_dispatch_us, 1),
        "unit": "us/dispatch",
        "detail": {
            "per_token_us": round(per_token_us, 2),
            "dispatches": dispatches,
            "requests": REQUESTS,
            "steps_per_dispatch": STEPS,
        },
    }


if __name__ == "__main__":
    print(json.dumps(asyncio.run(run())))
