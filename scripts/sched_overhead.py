"""Host-side scheduler-overhead microbench (VERDICT r2 item 8, r3 weak #1).

Measures what the CONTINUOUS-BATCHING SCHEDULER costs with the device
entirely removed: every jit cache is replaced by a host-side stub that
returns correctly-shaped arrays instantly, so all remaining wall is pure
Python/host bookkeeping.

The r3 version divided TOTAL wall (admission for 4xBS requests included)
by decode-dispatch count alone and reported 47.6 ms/dispatch — conflating
per-admission cost with per-tick cost.  This version attributes time at
the source:

- ``decode_host_us_per_token`` — time INSIDE ``_decode_tick`` (wave-window
  selection, retirement-heap peek, args assembly, token fan-out) divided
  by tokens decoded.  Bar: **< 10 us/token at bs=128, steps=32**.  The
  old bar was "<1 ms per dispatch", which is mis-dimensioned: a full
  bs=128 x 32-step dispatch carries 4096 tokens and takes O(100 ms) of
  DEVICE time at the north-star rate, so the per-dispatch host cost
  (dominated by fixed jnp/np transfer calls that ride alongside the
  device work) is not what limits scale — per-token bookkeeping is.  At
  the BASELINE 2,000 tok/s/chip target the per-token budget is 500 us;
  10 us host cost caps scheduler overhead at 2%.  (Measured r4: ~0.9
  us/token, vs the ~93 us/token the conflated r3 metric implied.)
- ``admission_us_per_request`` — time inside the admission path
  (``_admit``: wave formation, page reservation, array prep, jit-stub
  call, landing + first-token fan-out, activation, thread hops) divided
  by requests admitted.  Bar: **< 1000 us/request** — prefill itself is
  O(10 ms) of device time per wave, so sub-ms host cost per admitted
  request keeps admission off the critical path.
- ``gap_us_per_dispatch`` — the engine's own ``dispatch_gap_ms``
  histogram: the host-side span a launch spent with NO dispatch in
  flight.  With overlapped execution on (the default measured here) a
  steady-state launch finds a dispatch already in flight and observes a
  structural zero, so the MEAN would let one huge uncovered gap hide
  among a thousand zeros — the bar (**< 200 us at bs=128/steps=32**) is
  therefore taken on the histogram's TOP-BUCKET estimate
  (``percentile(1.0)``, the bucket upper bound of the worst observed
  gap; all-zero runs report the first bucket, 100 us).  It pins the
  launches that genuinely found the device uncovered (drain boundaries,
  post-admission ramp).  A blocking sync smuggled into the launch path
  would NOT move this number; that regression is guarded structurally
  by scripts/lint_hotpath.py's sync ban and behaviorally by
  scripts/overlap_overhead.py's fixed-latency-stub A/B (OVERLAP.json),
  not here.

Run at the REAL bench config (steps=32; bs=64 and bs=128, paged KV, pool
sized so every slot's full reservation fits — an undersized pool silently
caps concurrency below bs and validates the bar against a smaller batch).
Prints one JSON line; ``--out PATH`` also writes it as the committed
artifact.  Exits non-zero when a bar is violated.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from scripts._stub_common import (  # noqa: E402
    stub_prefill_lens,
    stub_retire_block,
)

STEPS = 32  # the real bench's decode_steps_per_dispatch
NEW_TOKENS = 128
DECODE_BAR_US_PER_TOKEN = 10.0
ADMIT_BAR_US = 1000.0
GAP_BAR_US = 200.0


def _stub_jits(engine: InferenceEngine, bs: int) -> None:
    """Replace the device path with shape-faithful host stubs.

    Stubs sit at the JIT boundary (not the method boundary) so the real
    host-side work — wave formation, page reservation, array prep,
    landing, fan-out — still runs and is measured.  The stub mirrors the
    device-side retirement contract (lens advance + n_valid/done from the
    hard-bound array) because with overlap_dispatch on, the DEVICE is the
    retirement authority — a stub that never reports done would serve
    forever."""

    def fake_decode(window: int, steps: int | None = None, sampled: bool = False):
        steps = steps or engine.runtime.decode_steps_per_dispatch

        def run(params, k, v, *rest):
            if engine._paged:
                tables, last, lens, active, done_prev, _stop, hard_end, *_ = rest
            else:
                last, lens, active, done_prev, _stop, hard_end, *_ = rest
            # token 1 is never a stop (no stop_tokens configured); [steps, B]
            toks = jnp.ones((steps, bs), jnp.int32)
            _act, n_valid, done, new_lens = stub_retire_block(
                active, done_prev, lens, hard_end, steps
            )
            return k, v, last, new_lens, toks, n_valid, done

        return run

    def fake_prefill_jit(bucket: int, rows: int, sampled: bool = False):
        def run(params, k, v, last, lens, tokens, slots, true_lens,
                slot_keys, temp, top_k, top_p,
                seeds, w_temp, w_top_k, w_top_p,
                tables=None, page_rows=None, scatter_ids=None):
            firsts = jnp.ones((rows,), jnp.int32)
            lens = stub_prefill_lens(lens, slots, true_lens)
            return k, v, tables, last, lens, slot_keys, temp, top_k, top_p, firsts

        return run

    engine._decode_jit = fake_decode
    engine._prefill_jit = fake_prefill_jit


class _Attributed:
    """Wrap an engine's decode tick and admission path with timers."""

    def __init__(self, engine: InferenceEngine):
        self.engine = engine
        self.decode_s = 0.0
        self.admit_s = 0.0
        self._tick = engine._decode_tick
        self._admit = engine._admit

        def timed_tick():
            t0 = time.perf_counter()
            self._tick()
            self.decode_s += time.perf_counter() - t0

        async def timed_admit():
            t0 = time.perf_counter()
            out = await self._admit()
            self.admit_s += time.perf_counter() - t0
            return out

        engine._decode_tick = timed_tick
        engine._admit = timed_admit

    def reset(self) -> None:
        self.decode_s = 0.0
        self.admit_s = 0.0


async def measure(bs: int) -> dict:
    from calfkit_tpu.inference.paged import pages_needed

    requests = 4 * bs
    config = preset("debug", max_seq_len=256)
    # pool must cover EVERY slot's full reservation (prompt + NEW_TOKENS),
    # or admission control silently caps concurrency below bs and the bar
    # is validated against a smaller batch than the metric name claims
    per_request = pages_needed(min(3 + NEW_TOKENS + 1, 256), 16)
    runtime = RuntimeConfig(
        max_batch_size=bs, max_seq_len=256, prefill_chunk=32,
        decode_steps_per_dispatch=STEPS, kv_layout="paged", page_size=16,
        num_kv_pages=bs * per_request + 1,
    )
    engine = InferenceEngine(config, runtime)
    _stub_jits(engine, bs)
    timers = _Attributed(engine)
    await engine.start()

    async def one(i: int) -> int:
        n = 0
        async for _ in engine.generate(
            [1 + (i % 50), 3, 5], max_new_tokens=NEW_TOKENS
        ):
            n += 1
        return n

    # warm the scheduler paths
    await asyncio.gather(*[one(i) for i in range(bs)])
    stats = engine.stats
    stats.decode_dispatches = 0
    stats.decode_tokens = 0
    stats.decode_time_s = 0.0
    timers.reset()

    t0 = time.perf_counter()
    counts = await asyncio.gather(*[one(i) for i in range(requests)])
    wall = time.perf_counter() - t0
    await engine.stop()

    assert all(c == NEW_TOKENS for c in counts), "stub served wrong lengths"
    dispatches = stats.decode_dispatches
    tokens = stats.decode_tokens
    gap = engine.latency["dispatch_gap_ms"]
    # worst observed gap (bucket upper bound): the mean would dilute one
    # real bubble with the structural zeros of covered launches
    gap_us = gap.percentile(1.0) * 1000.0 if gap.count else 0.0
    return {
        "bs": bs,
        "steps_per_dispatch": STEPS,
        "requests": requests,
        "dispatches": dispatches,
        "overlap_dispatch": engine.runtime.overlap_dispatch,
        "decode_us_per_dispatch": round(timers.decode_s / max(1, dispatches) * 1e6, 1),
        "decode_host_us_per_token": round(timers.decode_s / max(1, tokens) * 1e6, 2),
        "admission_us_per_request": round(timers.admit_s / requests * 1e6, 1),
        # worst device-idle bubble the engine observed at any launch
        # (zero whenever a dispatch was already in flight — the overlap
        # contract this artifact pins; top-bucket estimate, so 100.0
        # means "all launches fell in the lowest 0.1 ms bucket")
        "gap_us_per_dispatch": round(gap_us, 1),
        "wasted_tokens": stats.overlap_wasted_tokens,
        "wall_s": round(wall, 3),
        "decode_s": round(timers.decode_s, 3),
        "admit_s": round(timers.admit_s, 3),
        # consumer coroutines, queue churn, event-loop machinery
        "unattributed_s": round(wall - timers.decode_s - timers.admit_s, 3),
    }


async def run() -> dict:
    runs = [await measure(64), await measure(128)]
    at128 = runs[-1]
    ok = (
        at128["decode_host_us_per_token"] < DECODE_BAR_US_PER_TOKEN
        and at128["admission_us_per_request"] < ADMIT_BAR_US
        and at128["gap_us_per_dispatch"] < GAP_BAR_US
    )
    return {
        "metric": "scheduler_overhead[host-stub paged steps=32 overlap]",
        "value": at128["decode_host_us_per_token"],
        "unit": "us/token",
        "bars": {
            "decode_host_us_per_token": DECODE_BAR_US_PER_TOKEN,
            "admission_us_per_request": ADMIT_BAR_US,
            "gap_us_per_dispatch": GAP_BAR_US,
        },
        "ok": ok,
        "runs": runs,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
