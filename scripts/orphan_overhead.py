"""Orphan-reap A/B microbench (ISSUE 10 acceptance artifact).

Caller death mid fire-and-forget run, on the REAL mesh → worker → engine
path: an in-memory mesh, one Worker hosting an agent over a REAL debug
inference engine (control plane on — its caller-liveness feed folds
``mesh.caller_liveness`` into the process lease store), and a LEASED
client that ``send()``s runs nobody awaits, then dies hard (its
heartbeat task is killed — beats stop, no tombstone, exactly a crashed
process).

Two arms, identical workload and death:

- **leases on** — every call carried ``x-mesh-lease``; when the beats
  stop, the engine's orphan reaper abandons the runs within ~one lease
  TTL: slots/pages free, ORPHANS counts, the journal records
  ORPHAN → … → SLOT_FREE.  The headline number is death → engine
  drained.
- **leases off** — the pre-ISSUE-10 behavior: nothing notices the death;
  every run decodes its full token budget for a caller that no longer
  exists, and death → drained is the whole remaining generation.

Prints one JSON line (written to ORPHAN.json via --out); exits non-zero
unless the leased arm reaps EVERY run (orphaned == offered, zero leaked
slots/pages) in under half the baseline burn AND within a bounded
multiple of the lease TTL.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.client import Client  # noqa: E402
from calfkit_tpu.controlplane import ControlPlaneConfig  # noqa: E402
from calfkit_tpu.inference import model as M  # noqa: E402
from calfkit_tpu.inference.client import JaxLocalModelClient  # noqa: E402
from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.nodes import Agent  # noqa: E402
from calfkit_tpu.worker import Worker  # noqa: E402

from tests._chaos import assert_engine_drained  # noqa: E402 - the no-leak oracle

AGENT = "svc"
OFFERED = 3  # fire-and-forget runs in flight when the caller dies
NEW_TOKENS = 320  # the budget an unreaped run burns whole
DEADLINE_S = 60.0  # deliberately huge: the deadline reaper must NOT help
LEASE_TTL_S = 0.6
PACE_S = 0.02  # per-dispatch pacing: generation outlives the death
REAP_BAR_FRACTION = 0.5  # leased reap must beat half the baseline burn
REAP_TTL_MULT = 8.0  # ...and land within this many TTLs of the death

CFG = preset("debug")
PARAMS = M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _engine():
    runtime = RuntimeConfig(
        max_batch_size=4, max_seq_len=512, prefill_chunk=16,
        decode_steps_per_dispatch=4, page_size=16, kv_layout="paged",
        flightrec_events=1 << 14,
    )
    engine = InferenceEngine(CFG, runtime, params=PARAMS)
    model = JaxLocalModelClient(
        config=CFG, runtime=runtime, engine=engine,
        max_new_tokens=NEW_TOKENS,
    )
    return engine, model


async def _until(condition, *, seconds: float = 60.0, what: str = "") -> None:
    deadline = time.perf_counter() + seconds
    while not condition():
        if time.perf_counter() > deadline:
            raise RuntimeError(f"never settled: {what}")
        await asyncio.sleep(0.01)


def _drained(engine) -> bool:
    return (
        not engine._active and engine._pend is None
        and engine._inflight is None and not engine._admitting
        and not engine._pending and not engine._carry
        and len(engine._free) == engine.runtime.max_batch_size
    )


async def measure(leases_on: bool) -> dict:
    engine, model = _engine()
    total_free = engine._page_alloc.free_pages
    mesh = InMemoryMesh()
    agent = Agent(AGENT, model=model)
    worker = Worker(
        [agent], mesh=mesh,
        control_plane=ControlPlaneConfig(heartbeat_interval=0.1),
    )
    async with worker:
        def pace(point):
            if point == "dispatch":
                time.sleep(PACE_S)

        client = Client.connect(
            mesh, lease_ttl=LEASE_TTL_S if leases_on else None
        )
        # warm the engine (prefill+decode jits) OUTSIDE the measured
        # window, so the baseline burn measures decoding, not XLA builds
        warm = await client.agent(AGENT).start("warm up", timeout=DEADLINE_S)
        await warm.result()
        engine._chaos = pace

        for i in range(OFFERED):
            await client.agent(AGENT).send(f"fire and forget {i}")
        await _until(
            lambda: engine._active,
            what="no fire-and-forget run ever reached the engine",
        )
        # the caller dies HARD: beats stop, no tombstone (a clean close
        # would release the lease — a different, faster path)
        t_death = time.perf_counter()
        if client._lease_task is not None:
            client._lease_task.cancel()
        await _until(
            lambda: _drained(engine),
            what="the engine never drained after the caller died",
        )
        drained_s = round(time.perf_counter() - t_death, 3)
        assert_engine_drained(engine, total_free)
        out = {
            "leases": leases_on,
            "offered": OFFERED,
            "death_to_drained_s": drained_s,
            "orphaned_requests": engine.stats.orphaned_requests,
            "decode_tokens": engine.stats.decode_tokens,
            "free_pages": engine._page_alloc.free_pages,
            "total_pages": total_free,
        }
        # the dead caller's mesh state must not leak either
        await client.close()
    await engine.stop()
    await mesh.stop()
    return out


async def run() -> dict:
    on = await measure(True)
    off = await measure(False)
    reap = on["death_to_drained_s"]
    burn = off["death_to_drained_s"]
    ok = (
        on["orphaned_requests"] == OFFERED
        and off["orphaned_requests"] == 0
        and reap < burn * REAP_BAR_FRACTION
        and reap < LEASE_TTL_S * REAP_TTL_MULT
        and on["free_pages"] == on["total_pages"]
    )
    return {
        "metric": "orphan_reap_ab[caller death mid fire-and-forget send(), "
                  "real mesh->worker->engine path, real debug engine, "
                  "leased vs unleased caller]",
        "value": reap,
        "unit": "s death->engine-drained with leases on (vs the full "
                "generation burn the unleased baseline pays)",
        "lease_ttl_s": LEASE_TTL_S,
        "baseline_burn_s": burn,
        "reclaimed_s": round(burn - reap, 3),
        "reap_bar_s": round(burn * REAP_BAR_FRACTION, 3),
        "ok": ok,
        "on": on,
        "off": off,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
