"""The deterministic CI perf gate (ISSUE 11 acceptance artifact).

Runs the pinned fleet-simulation suite (``calfkit_tpu/sim/suite.py``)
through the REAL mesh → worker → router path on virtual time, writes the
structured ``SIM.json`` report, and gates two things:

1. **scenario verdicts** — every pinned scenario's checks must pass
   (completion, zero faults, skew/depth bounds, prefix hit rate,
   corpse isolation, lease lapse law);
2. **baseline regression** — every gated metric is compared against the
   checked-in ``SIM_BASELINE.json`` within its per-metric tolerance.
   Only deterministic virtual-clock and counter metrics are gated —
   NEVER host wall-clock (the CI hosts vary ~6x between sessions; wall
   time appears only in the report's ``capture`` block, as provenance).

Tolerances (docs/simulation.md "Tolerance policy"): the suite is
byte-deterministic for a fixed seed, so in principle tolerance could be
zero — but legitimate changes (a new rng consumer, a scheduling-order
refactor) shift exact values without regressing behavior.  Each gated
metric therefore carries a relative band (default ±10%) plus an
absolute slack for near-zero values; metrics where ANY movement is a
bug (``delivered_while_dead``) get tolerance 0 in the baseline.

Usage:
    python scripts/perf_gate.py                  # gate against baseline
    python scripts/perf_gate.py --out SIM.json   # also write the report
    python scripts/perf_gate.py --write-baseline # regenerate baseline
    python scripts/perf_gate.py --scale 0.15     # scaled run (no gate)
    python scripts/perf_gate.py --degrade routing  # seeded-regression
        seam: replaces every scenario's policy with a worst-loaded
        router; the gate MUST fail (tested in tests/test_sim.py)

Exit codes: 0 = all verdicts + baseline pass; 1 = regression or failed
verdict; 2 = harness error (missing baseline, bad flags).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Any, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# determinism requires a pinned hash seed (str-keyed set iteration order
# feeds nothing load-bearing today, but "today" is not a contract):
# re-exec once with PYTHONHASHSEED=0 so SIM.json is comparable across
# hosts and sessions
if os.environ.get("PYTHONHASHSEED") != "0":
    env = dict(os.environ, PYTHONHASHSEED="0")
    os.execve(sys.executable, [sys.executable, *sys.argv], env)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from calfkit_tpu.effects import no_wallclock  # noqa: E402
from calfkit_tpu.fleet.registry import Replica  # noqa: E402
from calfkit_tpu.sim import SimReport, SimRunner  # noqa: E402
from calfkit_tpu.sim.report import strip_capture  # noqa: E402
from calfkit_tpu.sim.suite import (  # noqa: E402
    PINNED_SUITE,
    SUITE_NAME,
    scaled_suite,
)

BASELINE_PATH = os.path.join(REPO, "SIM_BASELINE.json")
DEFAULT_REL_TOL = 0.10
DEFAULT_ABS_TOL = 2.0
# metrics where ANY movement is a regression, not drift
EXACT_METRICS = {
    "requests.completed",
    "routing.delivered_while_dead",
    # run-level completion (ISSUE 17): a run the ledger lost or failed
    # to close is a correctness bug, never drift
    "runs.completion_ratio",
}
# ratio-valued gated metrics (ISSUE 20): the default 2.0 absolute slack
# would swallow the whole [0, 1] range — band them on an absolute ratio
# delta instead (wide enough for legitimate rng-order drift, narrow
# enough that a class-ordering regression cannot hide)
RATIO_METRICS = {
    "qos.interactive.completion_ratio",
    "qos.batch.completion_ratio",
    "qos.shed_fairness_ratio",
}
RATIO_ABS_TOL = 0.05


class _WorstLoaded:
    """The seeded-regression policy (--degrade routing): deliberately
    picks the DEEPEST queue — the exact inversion of least-loaded.  A
    gate that cannot catch this is not a gate."""

    def select(
        self, candidates: "Sequence[Replica]", request: Any
    ) -> "Replica | None":
        return max(
            candidates,
            key=lambda r: (r.queue_depth, r.key),
            default=None,
        )


def _git(*args: str) -> "str | None":
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "-C", REPO, *args],
            capture_output=True, text=True, timeout=20,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return proc.stdout.strip() if proc.returncode == 0 else None


async def run_suite(
    *, scale: float = 1.0, degrade: "str | None" = None
) -> SimReport:
    """Run the pinned suite (optionally scaled / degraded) and return
    the report.  Scenarios run sequentially on one loop — each run
    installs its own virtual clock and id seam, so isolation holds."""
    scenarios = (
        PINNED_SUITE if scale == 1.0 else scaled_suite(scale)
    )
    policy = _WorstLoaded() if degrade == "routing" else None
    if policy is not None:
        # the degrade seam exists to prove the gate goes red, and the
        # load-balancing scenarios prove it decisively (skew explodes,
        # sheds cascade into fault storms).  On the failover-supervised
        # scenarios a worst-loaded policy additionally herds every
        # re-dispatch onto one replica's hours-deep virtual backlog —
        # minutes of host time to learn nothing new — so they are
        # skipped here (their own regression coverage is the baseline
        # gate on the UNdegraded run).
        scenarios = tuple(s for s in scenarios if not s.failover)
    report = SimReport(suite=SUITE_NAME)
    for scenario in scenarios:
        t0 = time.perf_counter()
        try:
            result = await SimRunner(scenario, policy=policy).run()
        except Exception as exc:  # noqa: BLE001 - a crash IS a gate fail
            from calfkit_tpu.sim.report import CheckResult, ScenarioReport

            result = ScenarioReport(
                name=scenario.name,
                seed=scenario.seed,
                replicas=scenario.replicas,
                metrics={"error": f"{type(exc).__name__}: {exc}"},
                checks=[
                    CheckResult(
                        name="scenario_ran",
                        metric="error",
                        op="==",
                        bound=0.0,
                        value=None,
                        passed=False,
                    )
                ],
                gated=scenario.gated,
            )
        wall = time.perf_counter() - t0
        verdict = "PASS" if result.passed else "FAIL"
        offered = result.metric("requests.offered")
        print(
            f"[perf_gate] {scenario.name}: {verdict} "
            # a crashed scenario has no metrics tree — the status line
            # must not crash the crash-reporting path
            f"offered={'?' if offered is None else int(offered)} "
            f"wall={wall:.1f}s",
            file=sys.stderr,
        )
        for check in result.checks:
            if not check.passed:
                print(
                    f"[perf_gate]   check {check.name}: {check.metric} "
                    f"{check.op} {check.bound} got {check.value}",
                    file=sys.stderr,
                )
        report.scenarios.append(result)
    return report


@no_wallclock
def compare_to_baseline(
    report: SimReport, baseline: "dict[str, Any]"
) -> "list[str]":
    """Regressions (empty = gate passes).  Baseline shape:
    ``{"scenarios": {name: {metric: {"value": v, "rel_tol": r,
    "abs_tol": a}}}}``.  A gated metric missing from the run or from
    the baseline is itself a regression — silence must not pass."""
    problems: list[str] = []
    base_scenarios = baseline.get("scenarios", {})
    for scenario in report.scenarios:
        base = base_scenarios.get(scenario.name)
        if base is None:
            problems.append(
                f"{scenario.name}: no baseline entry "
                "(regenerate with --write-baseline)"
            )
            continue
        gated = scenario.gated_metrics()
        for metric in scenario.gated:
            entry = base.get(metric)
            value = gated.get(metric)
            if entry is None:
                problems.append(
                    f"{scenario.name}.{metric}: gated but not in baseline"
                )
                continue
            if value is None:
                problems.append(
                    f"{scenario.name}.{metric}: missing from this run "
                    f"(baseline {entry['value']})"
                )
                continue
            expected = float(entry["value"])
            rel = float(entry.get("rel_tol", DEFAULT_REL_TOL))
            abs_tol = float(entry.get("abs_tol", DEFAULT_ABS_TOL))
            band = max(abs(expected) * rel, abs_tol)
            if abs(value - expected) > band:
                problems.append(
                    f"{scenario.name}.{metric}: {value} vs baseline "
                    f"{expected} (band ±{band:.4g})"
                )
        if not scenario.passed:
            failed = [c.name for c in scenario.checks if not c.passed]
            problems.append(
                f"{scenario.name}: scenario verdict FAILED ({failed})"
            )
    return problems


@no_wallclock
def baseline_from(report: SimReport) -> "dict[str, Any]":
    scenarios: dict[str, Any] = {}
    for scenario in report.scenarios:
        entry: dict[str, Any] = {}
        for metric, value in scenario.gated_metrics().items():
            if metric in EXACT_METRICS:
                entry[metric] = {"value": value, "rel_tol": 0.0, "abs_tol": 0.0}
            elif metric in RATIO_METRICS:
                entry[metric] = {
                    "value": value,
                    "rel_tol": 0.0,
                    "abs_tol": RATIO_ABS_TOL,
                }
            else:
                entry[metric] = {
                    "value": value,
                    "rel_tol": DEFAULT_REL_TOL,
                    "abs_tol": DEFAULT_ABS_TOL,
                }
        scenarios[scenario.name] = entry
    return {
        "suite": SUITE_NAME,
        "tolerance_policy": (
            f"per-metric band = max(|value| * rel_tol, abs_tol); "
            f"defaults rel={DEFAULT_REL_TOL} abs={DEFAULT_ABS_TOL}; "
            "exact metrics carry 0/0 (see docs/simulation.md)"
        ),
        "scenarios": scenarios,
    }


def capture_block(*, wall_s: float, scale: float) -> "dict[str, Any]":
    """Host-varying provenance ONLY — everything deterministic lives in
    the scenarios tree (see sim/report.py)."""
    return {
        "captured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "git_sha": _git("rev-parse", "HEAD"),
        "wall_s": round(wall_s, 1),
        "scale": scale,
        "python_hash_seed": os.environ.get("PYTHONHASHSEED"),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None, help="write SIM.json here")
    parser.add_argument(
        "--baseline", default=BASELINE_PATH,
        help="baseline to gate against (default SIM_BASELINE.json)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from this run instead of gating",
    )
    parser.add_argument(
        "--scale", type=float, default=1.0,
        help="scaled suite factor (1.0 = pinned full size)",
    )
    parser.add_argument(
        "--degrade", choices=("routing",), default=None,
        help="seeded-regression seam: run with a deliberately bad "
             "policy; the gate must fail",
    )
    ns = parser.parse_args()

    t0 = time.perf_counter()
    report = asyncio.run(run_suite(scale=ns.scale, degrade=ns.degrade))
    wall = time.perf_counter() - t0
    document = report.to_dict(
        capture=capture_block(wall_s=wall, scale=ns.scale)
    )
    if ns.out:
        with open(ns.out, "w") as f:
            json.dump(document, f, sort_keys=True, indent=1)
            f.write("\n")
        print(f"[perf_gate] wrote {ns.out}", file=sys.stderr)

    if ns.write_baseline:
        if ns.scale != 1.0 or ns.degrade:
            print(
                "[perf_gate] refusing to write a baseline from a scaled "
                "or degraded run", file=sys.stderr,
            )
            return 2
        with open(ns.baseline, "w") as f:
            json.dump(baseline_from(report), f, sort_keys=True, indent=1)
            f.write("\n")
        print(f"[perf_gate] wrote {ns.baseline}", file=sys.stderr)
        return 0 if report.passed else 1

    if ns.scale != 1.0:
        # scaled runs have no baseline: verdicts only
        print(
            f"[perf_gate] scaled run ({ns.scale}): verdicts "
            f"{'PASS' if report.passed else 'FAIL'}, no baseline gate",
            file=sys.stderr,
        )
        return 0 if report.passed else 1

    try:
        with open(ns.baseline) as f:
            baseline = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"[perf_gate] baseline unreadable: {exc}", file=sys.stderr)
        return 2

    problems = compare_to_baseline(report, baseline)
    if problems:
        for problem in problems:
            print(f"[perf_gate] REGRESSION: {problem}", file=sys.stderr)
        print(
            f"[perf_gate] FAILED: {len(problems)} problem(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"[perf_gate] PASS: {len(report.scenarios)} scenarios, all "
        "verdicts + baseline bands hold "
        f"(wall {wall:.1f}s — not gated)",
        file=sys.stderr,
    )
    return 0


# re-exported for tests (the determinism test compares stripped docs)
__all__ = [
    "run_suite",
    "compare_to_baseline",
    "baseline_from",
    "strip_capture",
    "_WorstLoaded",
]


if __name__ == "__main__":
    sys.exit(main())
