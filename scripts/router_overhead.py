"""Fleet-routing A/B microbench (ISSUE 7 acceptance artifact).

Two experiments, both through the REAL mesh → worker → engine path (an
in-memory mesh, two Workers each hosting a replica of one agent, a
fleet-routed Client — the exact production topology collapsed into one
process):

- **placement**: random vs load-aware (least-loaded) routing over a
  2x-SKEWED fleet — replica 0's device stub runs every dispatch at
  twice the latency of replica 1's (the fixed-latency device sim the
  other artifacts use).  Random placement keeps feeding the slow
  replica, whose backlog stretches the p99 engine queue-wait; the
  load-aware policy reads the same heartbeats the router ships and
  drains traffic toward the fast replica.  The headline value is the
  ratio of p99 queue-waits (random / load-aware) — ratio-based on
  purpose: absolute wall-clock on the CI hosts varies ~6x between
  sessions.
- **affinity**: prefix-cache hit rate on a repeat-session workload
  (S sessions × R identical-prefix requests each, served by REAL debug
  engines with ``prefix_cache=True``) with prefix-affinity routing ON
  (rendezvous stickiness) vs OFF (seeded random placement).  Affinity
  lands every turn of a session on the replica already holding its
  shared-prefix pages; random placement re-pays the prefill whenever a
  turn lands on the other replica.

Prints one JSON line (written to ROUTER.json via --out); exits non-zero
unless load-aware placement beats random by the ratio bar AND affinity
strictly raises the measured hit rate past its floor.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from calfkit_tpu.client import Client  # noqa: E402
from calfkit_tpu.controlplane import ControlPlaneConfig  # noqa: E402
from calfkit_tpu.fleet import FleetRouter, RandomChoice  # noqa: E402
from calfkit_tpu.inference.client import JaxLocalModelClient  # noqa: E402
from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.nodes import Agent  # noqa: E402
from calfkit_tpu.worker import Worker  # noqa: E402
from scripts._stub_common import (  # noqa: E402
    stub_prefill_lens,
    stub_retire_block,
)

AGENT = "svc"
BS = 4  # slots per replica
STEPS = 8
NEW_TOKENS = 64  # 8 device dispatches per request
# fast replica; the slow replica runs at 2x.  Large on purpose: host
# per-turn overhead (agent turn, rendering, lane hops — ~10ms, and up
# to ~6x worse on a throttled CI host) must stay SMALL against the
# simulated device time, or it dilutes the 2x skew the experiment is
# about and the A/B measures the host, not the policy.
DEVICE_MS = 20.0
# offered load sits BETWEEN twice the slow replica's capacity and the
# fleet total (full 4-row generation: slow 8×40ms=320ms → ~12.5 req/s,
# fast ~25, fleet ~37; offered ~30/s): blind 50/50 placement overloads
# the slow replica (its share exceeds its capacity, backlog and tail
# grow for the whole window) while a load-aware split keeps both sides
# under capacity.  An arrival window much shorter than service would
# defeat ANY depth-based policy — every pick would happen before the
# first completion — so requests arrive over ~2s, comparable to drain.
OFFERED = 64
STAGGER_S = 0.033
HEARTBEAT_S = 0.02
PLACEMENT_RATIO_BAR = 1.3  # random p99 must be ≥ 1.3x load-aware p99

SESSIONS = 8
TURNS = 4
AFFINITY_FLOOR = 0.6  # affinity-on hit rate must clear this


# ---------------------------------------------------------- device stub
class _DeviceSim:
    """Serialized fixed-latency device (see shed_overhead.py)."""

    def __init__(self, latency_s: float):
        self.latency_s = latency_s
        self.busy_until: float | None = None
        self.dispatches = 0

    def launch(self) -> float:
        now = time.perf_counter()
        start = max(now, self.busy_until or now)
        self.busy_until = start + self.latency_s
        self.dispatches += 1
        return self.busy_until


class _LazyBlock:
    def __init__(self, arr: np.ndarray, ready_at: float):
        self._arr = arr
        self._ready_at = ready_at

    def __array__(self, dtype=None, copy=None):
        delay = self._ready_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        return self._arr if dtype is None else self._arr.astype(dtype)

    @property
    def T(self):
        return np.asarray(self).T


def _stub_jits(engine: InferenceEngine, sim: _DeviceSim) -> None:
    def fake_decode(window: int, steps: int | None = None, sampled: bool = False):
        steps = steps or engine.runtime.decode_steps_per_dispatch

        def run(params, k, v, last, lens, active, done_prev, _stop,
                hard_end, *rest):
            ready_at = sim.launch()
            toks = np.ones((steps, BS), np.int32)
            _act, n_valid, done, new_lens = stub_retire_block(
                active, done_prev, lens, hard_end, steps
            )
            return (
                k, v, last, new_lens,
                _LazyBlock(toks, ready_at), n_valid, done,
            )

        return run

    def fake_prefill_jit(bucket: int, rows: int, sampled: bool = False):
        def run(params, k, v, last, lens, tokens, slots, true_lens,
                *rest, tables=None, page_rows=None, scatter_ids=None):
            firsts = jnp.ones((rows,), jnp.int32)
            lens = stub_prefill_lens(lens, slots, true_lens)
            return k, v, tables, last, lens, *rest[:4], firsts

        return run

    engine._decode_jit = fake_decode
    engine._prefill_jit = fake_prefill_jit


async def _until(condition, *, seconds: float = 10.0, what: str = "") -> None:
    deadline = time.perf_counter() + seconds
    while not condition():
        if time.perf_counter() > deadline:
            raise RuntimeError(f"never settled: {what}")
        await asyncio.sleep(0.01)


async def _fleet(models, *, heartbeat: float = HEARTBEAT_S):
    mesh = InMemoryMesh()
    config = ControlPlaneConfig(
        heartbeat_interval=heartbeat, stale_multiplier=1000.0
    )
    workers = [
        Worker([Agent(AGENT, model=m)], mesh=mesh, control_plane=config)
        for m in models
    ]
    for worker in workers:
        await worker.start()
    return mesh, config, workers


# ----------------------------------------------------------- placement
async def measure_placement(policy, label: str) -> dict:
    config = preset("debug", max_seq_len=256)
    engines, models, sims = [], [], []
    for i in range(2):
        runtime = RuntimeConfig(
            max_batch_size=BS, max_seq_len=256, prefill_chunk=32,
            decode_steps_per_dispatch=STEPS, overlap_dispatch=True,
        )
        engine = InferenceEngine(config, runtime)
        sim = _DeviceSim((DEVICE_MS * (2 if i == 0 else 1)) / 1000.0)
        _stub_jits(engine, sim)
        engines.append(engine)
        sims.append(sim)
        models.append(
            JaxLocalModelClient(
                config=config, runtime=runtime, engine=engine,
                max_new_tokens=NEW_TOKENS,
            )
        )
    mesh, cp_config, workers = await _fleet(models)
    router = FleetRouter(mesh, policy, stale_after=cp_config.stale_after)
    client = Client.connect(mesh, router=router)
    await router.start()
    await _until(
        lambda: len(router.registry.eligible(AGENT)) == 2,
        what="both replicas eligible",
    )

    latencies_ms: list[float] = []

    async def one(i: int):
        t_req = time.perf_counter()
        result = await client.agent(AGENT).execute(
            f"request {i}: payload", timeout=240
        )
        assert result.output is not None  # stub tokens may detokenize empty
        latencies_ms.append((time.perf_counter() - t_req) * 1000.0)

    t0 = time.perf_counter()
    tasks = []
    for i in range(OFFERED):
        tasks.append(asyncio.create_task(one(i)))
        await asyncio.sleep(STAGGER_S)
    await asyncio.gather(*tasks)
    wall = time.perf_counter() - t0

    # client-observed per-request wall time: queue-wait dominates it
    # under backlog (service time is fixed by the device sim), and
    # unlike the engine histograms it cannot saturate a bucket bound.
    # The headline tail is p95: with this sample size p99 is the single
    # worst request — lane-collision noise — while p95 still sits deep
    # in the backlogged-replica region the experiment is about.
    lat = np.asarray(latencies_ms)
    out = {
        "policy": label,
        "offered": OFFERED,
        "latency_p50_ms": round(float(np.percentile(lat, 50)), 1),
        "latency_p95_ms": round(float(np.percentile(lat, 95)), 1),
        "latency_p99_ms": round(float(np.percentile(lat, 99)), 1),
        # the fleet-level engine tail is the WORST replica's tail: that
        # is what a random-placement victim experiences (bucketed, may
        # clip — detail only, the headline ratio uses client latency)
        "engine_queue_wait_p99_ms": max(
            round(e.latency["queue_wait_ms"].percentile(0.99), 1)
            for e in engines
        ),
        "dispatches_per_replica": [s.dispatches for s in sims],
        "wall_s": round(wall, 3),
    }
    await client.close()
    for worker in workers:
        await worker.stop()
    for engine in engines:
        await engine.stop()
    await mesh.stop()
    return out


# ------------------------------------------------------------- affinity
async def measure_affinity(policy, label: str) -> dict:
    config = preset("debug", max_seq_len=256)
    engines, models = [], []
    for _ in range(2):
        runtime = RuntimeConfig(
            max_batch_size=BS, max_seq_len=256, page_size=16,
            kv_layout="paged", chunked_prefill=True, prefill_chunk=32,
            prefix_cache=True,
        )
        engine = InferenceEngine(config, runtime)  # REAL jits: real cache
        engines.append(engine)
        models.append(
            JaxLocalModelClient(
                config=config, runtime=runtime, engine=engine,
                max_new_tokens=8,
            )
        )
    mesh, cp_config, workers = await _fleet(models)
    router = FleetRouter(mesh, policy, stale_after=cp_config.stale_after)
    client = Client.connect(mesh, router=router)
    await router.start()
    await _until(
        lambda: len(router.registry.eligible(AGENT)) == 2,
        what="both replicas eligible",
    )

    # repeat-session workload: each session re-sends its own shared
    # prefix (the agent-serving pattern the PrefixCache exists for);
    # turns run sequentially per session, sessions round-robin
    prompts = [
        f"session-{s:02d}: you are the support agent for tenant {s}. " * 2
        for s in range(SESSIONS)
    ]
    for turn in range(TURNS):
        for prompt in prompts:
            result = await client.agent(AGENT).execute(prompt, timeout=240)
            assert result.output is not None
    total = SESSIONS * TURNS
    hits = sum(e.stats.prefix_hits for e in engines)
    reused = sum(e.stats.prefix_reused_tokens for e in engines)
    out = {
        "policy": label,
        "sessions": SESSIONS,
        "turns": TURNS,
        "requests": total,
        "prefix_hits": int(hits),
        "hit_rate": round(hits / total, 3),
        "reused_tokens": int(reused),
    }
    await client.close()
    for worker in workers:
        await worker.stop()
    for engine in engines:
        await engine.stop()
    await mesh.stop()
    return out


async def run() -> dict:
    import random

    # two trials per arm, interleaved (host throttling drifts over
    # seconds; interleaving spreads it across both arms), tails averaged
    load_trials, random_trials = [], []
    for trial in range(2):
        load_trials.append(
            await measure_placement("least-loaded", "least-loaded")
        )
        random_trials.append(
            await measure_placement(
                RandomChoice(rng=random.Random(trial).random), "random"
            )
        )
    mean_la = sum(t["latency_p95_ms"] for t in load_trials) / len(load_trials)
    mean_rand = sum(
        t["latency_p95_ms"] for t in random_trials
    ) / len(random_trials)
    ratio = mean_rand / max(mean_la, 0.001)

    affinity_on = await measure_affinity("prefix-affinity", "prefix-affinity")
    affinity_off = await measure_affinity(
        RandomChoice(rng=random.Random(1).random), "random"
    )

    ok = (
        ratio >= PLACEMENT_RATIO_BAR
        and affinity_on["hit_rate"] > affinity_off["hit_rate"]
        and affinity_on["hit_rate"] >= AFFINITY_FLOOR
    )
    return {
        "metric": "fleet_routing_ab[real mesh->worker->engine path, "
                  "2 replicas, fixed-latency device stub / real debug "
                  "engines]",
        "value": round(ratio, 2),
        "unit": "x p95 request-latency (queue-wait-dominated) growth "
                "under random vs load-aware placement on a 2x-skewed "
                "fleet (mean of 2 interleaved trials per arm)",
        "placement_ratio_bar": PLACEMENT_RATIO_BAR,
        "affinity_floor": AFFINITY_FLOOR,
        "ok": ok,
        "placement": {
            "load_aware": load_trials, "random": random_trials,
        },
        "affinity": {"on": affinity_on, "off": affinity_off},
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also write JSON here")
    ns = parser.parse_args()
    result = asyncio.run(run())
    line = json.dumps(result)
    print(line)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(line + "\n")
    sys.exit(0 if result["ok"] else 1)
