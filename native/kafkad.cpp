// kafkad — a native single-node broker speaking the REAL Kafka wire
// protocol (reference anchor: the reference validates its mesh against a
// Kafka-compatible broker, tests/integration + Makefile test-kafka; this
// image ships neither a broker nor aiokafka, so the TPU build carries its
// own).  The framework's KafkaWireMesh client (calfkit_tpu/mesh/kafka_wire.py)
// speaks the same protocol to THIS binary in-image and to a real
// Kafka/Redpanda cluster in production — one client, one wire format.
//
// Implemented APIs (fixed, non-flexible versions — chosen so both this
// broker and real brokers accept them):
//   ApiVersions v0, Metadata v1, Produce v3, Fetch v4, ListOffsets v1,
//   FindCoordinator v0, JoinGroup v2, SyncGroup v1, Heartbeat v1,
//   LeaveGroup v1, OffsetCommit v2, OffsetFetch v1, CreateTopics v0
// Record format: RecordBatch v2 (magic=2, crc32c, zigzag varints) — the
// only format modern brokers speak.
//
// Scope decisions:
// - one node (node_id 0); all partitions led here; replication factor 1
// - consumer-group coordination is COMPLETE (generations, leader range
//   assignment done client-side per the standard "consumer" embedded
//   protocol, rebalance on join/leave/session-expiry, blocking joins)
// - compacted topics retain all records; compaction is an optimization,
//   not semantics — readers apply tombstones, so views converge the same
// - fetch long-polls up to max_wait_ms on a producer-signalled condvar
//
// Usage: kafkad [port]   (port 0 = OS-assigned, reported as "PORT <n>")

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <errno.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <thread>
#include <vector>

namespace {

// ----------------------------------------------------------------- crc32c
// Castagnoli CRC (poly 0x1EDC6F41, reflected 0x82F63B78) — what
// RecordBatch v2's crc field uses.  Table-based, byte at a time.
uint32_t kCrcTable[256];
void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
    kCrcTable[i] = c;
  }
}
uint32_t crc32c(const uint8_t* data, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = kCrcTable[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------ byte codecs
struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  Reader(const uint8_t* data, size_t n) : p(data), end(data + n) {}
  bool need(size_t n) {
    if (size_t(end - p) < n) { ok = false; return false; }
    return true;
  }
  uint8_t i8() { if (!need(1)) return 0; return *p++; }
  int16_t i16() {
    if (!need(2)) return 0;
    int16_t v = int16_t((p[0] << 8) | p[1]); p += 2; return v;
  }
  int32_t i32() {
    if (!need(4)) return 0;
    int32_t v = int32_t((uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                        (uint32_t(p[2]) << 8) | p[3]);
    p += 4; return v;
  }
  int64_t i64() {
    if (!need(8)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
    p += 8; return int64_t(v);
  }
  // zigzag varint (records)
  int64_t varlong() {
    uint64_t v = 0; int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = *p++;
      v |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) { ok = false; return 0; }
    }
    return int64_t(v >> 1) ^ -int64_t(v & 1);
  }
  std::string str() {  // STRING (i16 length, -1 => null -> "")
    int16_t n = i16();
    if (n < 0) return "";
    if (!need(size_t(n))) return "";
    std::string s(reinterpret_cast<const char*>(p), size_t(n));
    p += n; return s;
  }
  std::optional<std::vector<uint8_t>> bytes() {  // BYTES (i32 length, -1 null)
    int32_t n = i32();
    if (n < 0) return std::nullopt;
    if (!need(size_t(n))) return std::nullopt;
    std::vector<uint8_t> b(p, p + n);
    p += n; return b;
  }
};

struct Writer {
  std::vector<uint8_t> buf;
  void raw(const void* data, size_t n) {
    const uint8_t* d = static_cast<const uint8_t*>(data);
    buf.insert(buf.end(), d, d + n);
  }
  void i8(uint8_t v) { buf.push_back(v); }
  void i16(int16_t v) { buf.push_back(uint8_t(v >> 8)); buf.push_back(uint8_t(v)); }
  void i32(int32_t v) {
    for (int i = 3; i >= 0; i--) buf.push_back(uint8_t(uint32_t(v) >> (8 * i)));
  }
  void i64(int64_t v) {
    for (int i = 7; i >= 0; i--) buf.push_back(uint8_t(uint64_t(v) >> (8 * i)));
  }
  void varlong(int64_t v) {
    uint64_t z = (uint64_t(v) << 1) ^ uint64_t(v >> 63);
    while (z >= 0x80) { buf.push_back(uint8_t(z) | 0x80); z >>= 7; }
    buf.push_back(uint8_t(z));
  }
  void str(const std::string& s) {
    i16(int16_t(s.size()));
    raw(s.data(), s.size());
  }
  void null_str() { i16(-1); }
  void bytes(const std::vector<uint8_t>& b) {
    i32(int32_t(b.size()));
    raw(b.data(), b.size());
  }
  // overwrite a previously-reserved i32 at `at`
  void patch_i32(size_t at, int32_t v) {
    for (int i = 0; i < 4; i++) buf[at + i] = uint8_t(uint32_t(v) >> (8 * (3 - i)));
  }
};

// ------------------------------------------------------------ log storage
struct StoredRecord {
  int64_t offset;
  int64_t timestamp_ms;
  std::optional<std::vector<uint8_t>> key;    // nullopt = null key
  std::optional<std::vector<uint8_t>> value;  // nullopt = tombstone
  std::vector<std::pair<std::string, std::vector<uint8_t>>> headers;
};

struct Partition {
  std::vector<StoredRecord> log;  // offset == index (no truncation)
  int64_t high_watermark() const { return int64_t(log.size()); }
};

struct Topic {
  std::vector<Partition> partitions;
  bool compacted = false;
};

// --------------------------------------------------------- group machinery
struct Member {
  std::string id;
  // protocol name -> metadata, in the member's preference order
  std::vector<std::pair<std::string, std::vector<uint8_t>>> protocols;
  std::vector<uint8_t> assignment;
  int64_t deadline_ms = 0;         // session expiry
  int32_t session_timeout_ms = 30000;
  bool joined_round = false;       // has (re-)joined the current rebalance
};

struct Group {
  enum State { Empty, PreparingRebalance, CompletingRebalance, Stable };
  State state = Empty;
  int32_t generation = 0;
  std::string leader;
  std::string protocol;  // chosen protocol name (e.g. "range")
  std::map<std::string, Member> members;
  std::map<std::pair<std::string, int32_t>, int64_t> offsets;
  int64_t rebalance_deadline_ms = 0;
  int member_counter = 0;
};

// ----------------------------------------------------------- broker state
std::mutex g_mu;
std::condition_variable g_data_cv;   // new produce landed (fetch long-poll)
std::condition_variable g_group_cv;  // group state changed (join/sync blocks)
std::map<std::string, Topic> g_topics;
std::map<std::string, Group> g_groups;
int g_port = 0;
int g_advertise_port = 0;  // advertised.listeners equivalent (defaults to g_port)
constexpr int32_t kDefaultPartitions = 8;

int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return int64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// ----------------------------------------------------------- WAL (opt-in)
// `kafkad <port> --log-dir <dir>` makes the dev broker DURABLE: every
// topic creation, record append, and committed offset is appended to
// <dir>/wal.log (length-prefixed, crc32c-guarded frames) and replayed on
// boot.  Without the flag, retention is memory-only (Tansu-dev-broker
// parity) and a restart is a fresh world — the documented trade.
FILE* g_wal = nullptr;     // non-null = durability on
bool g_replaying = false;  // suppress re-logging during boot replay
uint32_t crc32c(const uint8_t* data, size_t n);

struct WalWriter {
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void u16(uint16_t v) { buf.push_back(uint8_t(v >> 8)); buf.push_back(uint8_t(v)); }
  void i32w(int32_t v) { for (int i = 3; i >= 0; i--) buf.push_back(uint8_t(uint32_t(v) >> (8 * i))); }
  void i64w(int64_t v) { for (int i = 7; i >= 0; i--) buf.push_back(uint8_t(uint64_t(v) >> (8 * i))); }
  void str(const std::string& s) { u16(uint16_t(s.size())); buf.insert(buf.end(), s.begin(), s.end()); }
  void blob(const std::optional<std::vector<uint8_t>>& b) {
    if (!b) { i32w(-1); return; }
    i32w(int32_t(b->size()));
    buf.insert(buf.end(), b->begin(), b->end());
  }
};

bool g_wal_failed = false;  // loud-once latch: never silently memory-only

void wal_io_check(bool ok) {  // caller holds g_mu
  if (ok) {
    if (g_wal_failed)
      fprintf(stderr, "kafkad: WAL writes recovered\n");
    g_wal_failed = false;
    return;
  }
  if (!g_wal_failed)
    fprintf(stderr,
            "kafkad: WAL WRITE FAILED (disk full / fs error?) — durability "
            "is DEGRADED until writes recover: %s\n", strerror(errno));
  g_wal_failed = true;
}

void wal_append(const WalWriter& w) {  // caller holds g_mu; flush deferred
  if (!g_wal || g_replaying) return;
  uint32_t len = uint32_t(w.buf.size());
  uint32_t crc = crc32c(w.buf.data(), w.buf.size());
  uint8_t head[8];
  for (int i = 0; i < 4; i++) head[i] = uint8_t(len >> (8 * (3 - i)));
  for (int i = 0; i < 4; i++) head[4 + i] = uint8_t(crc >> (8 * (3 - i)));
  bool ok = fwrite(head, 1, 8, g_wal) == 8 &&
            fwrite(w.buf.data(), 1, w.buf.size(), g_wal) == w.buf.size();
  wal_io_check(ok);
}

void wal_flush() {  // caller holds g_mu; one flush per handler mutation
  if (!g_wal || g_replaying) return;
  wal_io_check(fflush(g_wal) == 0);
}

void wal_log_topic(const std::string& name, int32_t partitions, bool compacted) {
  WalWriter w;
  w.u8('T'); w.str(name); w.i32w(partitions); w.u8(compacted ? 1 : 0);
  wal_append(w);
}

void wal_log_record(const std::string& topic, int32_t part, const StoredRecord& rec) {
  WalWriter w;
  w.u8('R'); w.str(topic); w.i32w(part); w.i64w(rec.timestamp_ms);
  w.blob(rec.key); w.blob(rec.value);
  w.i32w(int32_t(rec.headers.size()));
  for (const auto& h : rec.headers) { w.str(h.first); w.blob(std::optional<std::vector<uint8_t>>(h.second)); }
  wal_append(w);
}

void wal_log_offset(const std::string& group, const std::string& topic, int32_t part, int64_t off) {
  WalWriter w;
  w.u8('O'); w.str(group); w.str(topic); w.i32w(part); w.i64w(off);
  wal_append(w);
}

Topic& topic_ref_locked(const std::string& name, int32_t partitions = kDefaultPartitions,
                        bool compacted = false) {
  auto it = g_topics.find(name);
  if (it == g_topics.end()) {
    Topic t;
    t.partitions.resize(size_t(partitions));
    t.compacted = compacted;
    it = g_topics.emplace(name, std::move(t)).first;
    wal_log_topic(name, partitions, compacted);
  }
  return it->second;
}

// error codes
constexpr int16_t ERR_NONE = 0;
constexpr int16_t ERR_OFFSET_OUT_OF_RANGE = 1;
constexpr int16_t ERR_UNKNOWN_TOPIC = 3;
constexpr int16_t ERR_ILLEGAL_GENERATION = 22;
constexpr int16_t ERR_UNKNOWN_MEMBER = 25;
constexpr int16_t ERR_INVALID_TOPIC = 17;
constexpr int16_t ERR_REBALANCE_IN_PROGRESS = 27;
constexpr int16_t ERR_UNSUPPORTED_VERSION = 35;
constexpr int16_t ERR_UNSUPPORTED_SASL_MECHANISM = 33;
constexpr int16_t ERR_SASL_AUTHENTICATION_FAILED = 58;

// SASL/PLAIN credentials (empty user = auth disabled).  Set via
// `kafkad <port> --sasl user:pass` — gives the wire client's SASL path a
// real in-image round trip (VERDICT r4 item 2).
std::string g_sasl_user, g_sasl_pass;

// ------------------------------------------------------- record batch v2
// Parse every record of a RecordBatch v2 blob into `out` (timestamps and
// offsets recomputed by the broker — producer deltas are relative).
bool parse_record_batch(const std::vector<uint8_t>& blob,
                        std::vector<StoredRecord>* out) {
  Reader r(blob.data(), blob.size());
  while (r.ok && r.p < r.end) {
    r.i64();                       // baseOffset (producer-side, ignored)
    int32_t batch_len = r.i32();   // bytes after this field
    if (!r.need(size_t(batch_len))) return false;
    const uint8_t* batch_end = r.p + batch_len;
    r.i32();                       // partitionLeaderEpoch
    uint8_t magic = r.i8();
    if (magic != 2) return false;
    r.i32();                       // crc (trusted: same-process tests + TCP)
    int16_t attrs = r.i16();
    if (attrs & 0x07) return false;  // compression unsupported
    r.i32();                       // lastOffsetDelta
    int64_t first_ts = r.i64();
    r.i64();                       // maxTimestamp
    r.i64();                       // producerId
    r.i16();                       // producerEpoch
    r.i32();                       // baseSequence
    int32_t count = r.i32();
    for (int32_t i = 0; i < count && r.ok; i++) {
      int64_t rec_len = r.varlong();
      const uint8_t* rec_end = r.p + rec_len;
      r.i8();                      // record attributes
      int64_t ts_delta = r.varlong();
      r.varlong();                 // offsetDelta
      StoredRecord rec;
      rec.timestamp_ms = first_ts + ts_delta;
      int64_t klen = r.varlong();
      if (klen >= 0) {
        if (!r.need(size_t(klen))) return false;
        rec.key = std::vector<uint8_t>(r.p, r.p + klen);
        r.p += klen;
      }
      int64_t vlen = r.varlong();
      if (vlen >= 0) {
        if (!r.need(size_t(vlen))) return false;
        rec.value = std::vector<uint8_t>(r.p, r.p + vlen);
        r.p += vlen;
      }
      int64_t hcount = r.varlong();
      for (int64_t h = 0; h < hcount && r.ok; h++) {
        int64_t hklen = r.varlong();
        if (!r.need(size_t(hklen))) return false;
        std::string hk(reinterpret_cast<const char*>(r.p), size_t(hklen));
        r.p += hklen;
        int64_t hvlen = r.varlong();
        std::vector<uint8_t> hv;
        if (hvlen >= 0) {
          if (!r.need(size_t(hvlen))) return false;
          hv.assign(r.p, r.p + hvlen);
          r.p += hvlen;
        }
        rec.headers.emplace_back(std::move(hk), std::move(hv));
      }
      if (r.p != rec_end) r.p = rec_end;  // tolerate producer padding
      out->push_back(std::move(rec));
    }
    if (r.p != batch_end) r.p = batch_end;
  }
  return r.ok;
}

// Encode records [first, last) of a partition log as ONE RecordBatch v2.
std::vector<uint8_t> encode_record_batch(const std::vector<StoredRecord>& log,
                                         size_t first, size_t last) {
  Writer records;
  int64_t base_ts = log[first].timestamp_ms;
  for (size_t i = first; i < last; i++) {
    const StoredRecord& rec = log[i];
    Writer body;
    body.i8(0);  // attributes
    body.varlong(rec.timestamp_ms - base_ts);
    body.varlong(int64_t(i - first));  // offsetDelta
    if (rec.key) { body.varlong(int64_t(rec.key->size())); body.raw(rec.key->data(), rec.key->size()); }
    else body.varlong(-1);
    if (rec.value) { body.varlong(int64_t(rec.value->size())); body.raw(rec.value->data(), rec.value->size()); }
    else body.varlong(-1);
    body.varlong(int64_t(rec.headers.size()));
    for (const auto& h : rec.headers) {
      body.varlong(int64_t(h.first.size()));
      body.raw(h.first.data(), h.first.size());
      body.varlong(int64_t(h.second.size()));
      body.raw(h.second.data(), h.second.size());
    }
    records.varlong(int64_t(body.buf.size()));
    records.raw(body.buf.data(), body.buf.size());
  }
  // the crc covers everything from attributes (i16) onward
  Writer crcbody;
  crcbody.i16(0);                          // attributes
  crcbody.i32(int32_t(last - first - 1));  // lastOffsetDelta
  crcbody.i64(base_ts);
  crcbody.i64(log[last - 1].timestamp_ms);
  crcbody.i64(-1);                         // producerId
  crcbody.i16(-1);                         // producerEpoch
  crcbody.i32(-1);                         // baseSequence
  crcbody.i32(int32_t(last - first));
  crcbody.raw(records.buf.data(), records.buf.size());
  uint32_t crc = crc32c(crcbody.buf.data(), crcbody.buf.size());

  Writer out;
  out.i64(int64_t(first));                     // baseOffset
  out.i32(int32_t(4 + 1 + 4 + crcbody.buf.size()));  // batchLength
  out.i32(0);                                  // partitionLeaderEpoch
  out.i8(2);                                   // magic
  out.i32(int32_t(crc));
  out.raw(crcbody.buf.data(), crcbody.buf.size());
  return out.buf;
}

// ----------------------------------------------------------- API handlers
void handle_api_versions(Writer& w) {
  // (api_key, min, max) for everything we speak
  const int16_t table[][3] = {
      {0, 0, 3},  {1, 0, 4},  {2, 0, 1},  {3, 0, 1},  {8, 0, 2},
      {9, 0, 1},  {10, 0, 0}, {11, 0, 2}, {12, 0, 1}, {13, 0, 1},
      {14, 0, 1}, {17, 0, 1}, {18, 0, 0}, {19, 0, 0}, {36, 0, 0},
  };
  w.i16(ERR_NONE);
  w.i32(int32_t(sizeof(table) / sizeof(table[0])));
  for (const auto& row : table) { w.i16(row[0]); w.i16(row[1]); w.i16(row[2]); }
}

void handle_metadata(Reader& r, Writer& w) {
  int32_t n = r.i32();
  std::vector<std::string> names;
  for (int32_t i = 0; i < n; i++) names.push_back(r.str());
  std::lock_guard<std::mutex> lk(g_mu);
  if (n < 0) for (const auto& kv : g_topics) names.push_back(kv.first);
  else { for (const auto& name : names) topic_ref_locked(name); wal_flush(); }  // auto-create
  // brokers
  w.i32(1);
  w.i32(0); w.str("127.0.0.1"); w.i32(g_advertise_port); w.null_str();  // rack
  w.i32(0);  // controller_id
  w.i32(int32_t(names.size()));
  for (const auto& name : names) {
    Topic& t = g_topics.at(name);
    w.i16(ERR_NONE); w.str(name); w.i8(0);  // is_internal
    w.i32(int32_t(t.partitions.size()));
    for (size_t p = 0; p < t.partitions.size(); p++) {
      w.i16(ERR_NONE); w.i32(int32_t(p)); w.i32(0);  // leader
      w.i32(1); w.i32(0);  // replicas [0]
      w.i32(1); w.i32(0);  // isr [0]
    }
  }
}

void handle_produce(Reader& r, Writer& w) {
  r.str();   // transactional_id (v3; nullable)
  r.i16();   // acks — we always ack after append (durability = RAM)
  r.i32();   // timeout
  int32_t ntopics = r.i32();
  struct PartResult { std::string topic; int32_t part; int16_t err; int64_t base; };
  std::vector<PartResult> results;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    for (int32_t t = 0; t < ntopics; t++) {
      std::string name = r.str();
      int32_t nparts = r.i32();
      for (int32_t p = 0; p < nparts; p++) {
        int32_t part = r.i32();
        auto blob = r.bytes();
        PartResult res{name, part, ERR_NONE, -1};
        Topic& topic = topic_ref_locked(name);
        if (part < 0 || size_t(part) >= topic.partitions.size()) {
          res.err = ERR_UNKNOWN_TOPIC;
        } else if (blob) {
          std::vector<StoredRecord> recs;
          if (!parse_record_batch(*blob, &recs)) {
            res.err = ERR_INVALID_TOPIC;
          } else {
            Partition& pa = topic.partitions[size_t(part)];
            res.base = pa.high_watermark();
            int64_t ts = now_ms();
            for (auto& rec : recs) {
              rec.offset = pa.high_watermark();
              if (rec.timestamp_ms <= 0) rec.timestamp_ms = ts;
              wal_log_record(name, part, rec);
              pa.log.push_back(std::move(rec));
            }
          }
        }
        results.push_back(std::move(res));
      }
    }
    wal_flush();
  }
  g_data_cv.notify_all();
  // group results by topic, preserving order
  w.i32(ntopics);
  size_t i = 0;
  while (i < results.size()) {
    const std::string& name = results[i].topic;
    size_t j = i;
    while (j < results.size() && results[j].topic == name) j++;
    w.str(name);
    w.i32(int32_t(j - i));
    for (size_t k = i; k < j; k++) {
      w.i32(results[k].part);
      w.i16(results[k].err);
      w.i64(results[k].base);
      w.i64(-1);  // log_append_time
    }
    i = j;
  }
  w.i32(0);  // throttle_time_ms (LAST for produce)
}

void handle_fetch(Reader& r, Writer& w) {
  r.i32();  // replica_id
  int32_t max_wait = r.i32();
  int32_t min_bytes = r.i32();
  r.i32();  // max_bytes (total)
  r.i8();   // isolation
  int32_t ntopics = r.i32();
  struct Want { std::string topic; int32_t part; int64_t off; int32_t max; };
  std::vector<Want> wants;
  for (int32_t t = 0; t < ntopics; t++) {
    std::string name = r.str();
    int32_t nparts = r.i32();
    for (int32_t p = 0; p < nparts; p++) {
      Want want;
      want.topic = name;
      want.part = r.i32();
      want.off = r.i64();
      want.max = r.i32();
      wants.push_back(std::move(want));
    }
  }
  auto have_data = [&wants]() {
    for (const auto& want : wants) {
      auto it = g_topics.find(want.topic);
      if (it == g_topics.end()) continue;
      if (want.part < 0 || size_t(want.part) >= it->second.partitions.size())
        continue;
      if (it->second.partitions[size_t(want.part)].high_watermark() > want.off)
        return true;
    }
    return false;
  };
  std::unique_lock<std::mutex> lk(g_mu);
  if (max_wait > 0 && min_bytes > 0 && !have_data()) {
    g_data_cv.wait_for(lk, std::chrono::milliseconds(max_wait),
                       [&] { return have_data(); });
  }
  w.i32(0);  // throttle (FIRST for fetch v1+)
  w.i32(ntopics);
  size_t i = 0;
  while (i < wants.size()) {
    const std::string& name = wants[i].topic;
    size_t j = i;
    while (j < wants.size() && wants[j].topic == name) j++;
    w.str(name);
    w.i32(int32_t(j - i));
    for (size_t k = i; k < j; k++) {
      const Want& want = wants[k];
      w.i32(want.part);
      auto it = g_topics.find(want.topic);
      bool known = it != g_topics.end() && want.part >= 0 &&
                   size_t(want.part) < it->second.partitions.size();
      if (!known) {
        w.i16(ERR_UNKNOWN_TOPIC); w.i64(-1); w.i64(-1);
        w.i32(-1);  // aborted_transactions (null)
        w.i32(-1);  // record_set null
        continue;
      }
      Partition& pa = it->second.partitions[size_t(want.part)];
      int64_t hw = pa.high_watermark();
      // a position BEYOND the log end means the consumer knows a world
      // this broker does not (broker restart wiped the memory-only log):
      // answer OFFSET_OUT_OF_RANGE like real Kafka so clients re-resolve
      // loudly instead of long-polling a dead position forever.
      // off == hw is the normal caught-up wait.
      if (want.off > hw || want.off < 0) {
        w.i16(ERR_OFFSET_OUT_OF_RANGE); w.i64(hw); w.i64(hw);
        w.i32(-1);  // aborted_transactions (null)
        w.i32(-1);  // record_set null
        continue;
      }
      w.i16(ERR_NONE); w.i64(hw); w.i64(hw);
      w.i32(-1);  // aborted_transactions (null)
      if (want.off >= hw) { w.i32(-1); continue; }
      // cap records by the partition max_bytes request (approximate:
      // stop before exceeding, always include at least one)
      size_t first = size_t(want.off), last = first;
      int64_t budget = want.max > 0 ? want.max : 1 << 20;
      int64_t used = 0;
      while (last < pa.log.size()) {
        const StoredRecord& rec = pa.log[last];
        int64_t sz = 32 + int64_t(rec.key ? rec.key->size() : 0) +
                     int64_t(rec.value ? rec.value->size() : 0);
        for (const auto& h : rec.headers)
          sz += int64_t(h.first.size() + h.second.size() + 4);
        if (last > first && used + sz > budget) break;
        used += sz;
        last++;
      }
      std::vector<uint8_t> blob = encode_record_batch(pa.log, first, last);
      w.bytes(blob);
    }
    i = j;
  }
}

void handle_list_offsets(Reader& r, Writer& w) {
  r.i32();  // replica
  int32_t ntopics = r.i32();
  std::lock_guard<std::mutex> lk(g_mu);
  w.i32(ntopics);
  for (int32_t t = 0; t < ntopics; t++) {
    std::string name = r.str();
    int32_t nparts = r.i32();
    w.str(name);
    w.i32(nparts);
    for (int32_t p = 0; p < nparts; p++) {
      int32_t part = r.i32();
      int64_t ts = r.i64();
      w.i32(part);
      auto it = g_topics.find(name);
      if (it == g_topics.end() || part < 0 ||
          size_t(part) >= it->second.partitions.size()) {
        w.i16(ERR_UNKNOWN_TOPIC); w.i64(-1); w.i64(-1);
        continue;
      }
      int64_t hw = it->second.partitions[size_t(part)].high_watermark();
      w.i16(ERR_NONE);
      w.i64(-1);  // timestamp
      w.i64(ts == -2 ? 0 : hw);  // -2 earliest, -1 latest
    }
  }
}

void handle_find_coordinator(Reader& r, Writer& w) {
  r.str();  // group id — single node: always us
  w.i16(ERR_NONE);
  w.i32(0); w.str("127.0.0.1"); w.i32(g_advertise_port);
}

// complete a pending rebalance if every current member has rejoined (or
// the deadline passed — stragglers are dropped).  Caller holds g_mu.
void maybe_complete_join_locked(Group& g) {
  if (g.state != Group::PreparingRebalance) return;
  bool all = true;
  for (const auto& kv : g.members) all = all && kv.second.joined_round;
  if (!all && now_ms() < g.rebalance_deadline_ms) return;
  if (!all) {  // drop stragglers
    for (auto it = g.members.begin(); it != g.members.end();) {
      if (!it->second.joined_round) it = g.members.erase(it);
      else ++it;
    }
  }
  if (g.members.empty()) { g.state = Group::Empty; g_group_cv.notify_all(); return; }
  g.generation++;
  g.leader = g.members.begin()->first;
  // protocol selection: first protocol of the leader (all members align
  // on "range" in our client)
  if (!g.members.begin()->second.protocols.empty())
    g.protocol = g.members.begin()->second.protocols[0].first;
  g.state = Group::CompletingRebalance;
  for (auto& kv : g.members) kv.second.assignment.clear();
  g_group_cv.notify_all();
}

void handle_join_group(Reader& r, Writer& w) {
  std::string group_id = r.str();
  int32_t session_timeout = r.i32();
  int32_t rebalance_timeout = r.i32();
  std::string member_id = r.str();
  std::string protocol_type = r.str();
  int32_t nproto = r.i32();
  std::vector<std::pair<std::string, std::vector<uint8_t>>> protocols;
  for (int32_t i = 0; i < nproto; i++) {
    std::string pname = r.str();
    auto meta = r.bytes();
    protocols.emplace_back(pname, meta.value_or(std::vector<uint8_t>{}));
  }
  (void)protocol_type;

  std::unique_lock<std::mutex> lk(g_mu);
  Group& g = g_groups[group_id];
  if (member_id.empty())
    member_id = "m-" + std::to_string(++g.member_counter);
  Member& m = g.members[member_id];
  m.id = member_id;
  m.protocols = std::move(protocols);
  m.session_timeout_ms = session_timeout;
  m.deadline_ms = now_ms() + session_timeout;
  m.joined_round = true;
  if (g.state == Group::Empty || g.state == Group::Stable ||
      g.state == Group::CompletingRebalance) {
    // a (re)join interrupts a stable/completing group: everyone rebalances
    g.state = Group::PreparingRebalance;
    g.rebalance_deadline_ms = now_ms() + std::max(rebalance_timeout, 1000);
    for (auto& kv : g.members) kv.second.joined_round = kv.first == member_id;
  }
  maybe_complete_join_locked(g);
  // block until this round completes (or our straggler deadline drops us)
  g_group_cv.wait_for(
      lk, std::chrono::milliseconds(std::max(rebalance_timeout, 1000) + 2000),
      [&] {
        maybe_complete_join_locked(g);
        return g.state == Group::CompletingRebalance || g.state == Group::Stable ||
               g.members.find(member_id) == g.members.end();
      });
  w.i32(0);  // throttle (JoinGroup v2)
  if (g.members.find(member_id) == g.members.end()) {
    w.i16(ERR_UNKNOWN_MEMBER); w.i32(-1); w.str(""); w.str(""); w.str(member_id);
    w.i32(0);
    return;
  }
  w.i16(ERR_NONE);
  w.i32(g.generation);
  w.str(g.protocol);
  w.str(g.leader);
  w.str(member_id);
  if (member_id == g.leader) {
    w.i32(int32_t(g.members.size()));
    for (const auto& kv : g.members) {
      w.str(kv.first);
      // leader assigns from each member's metadata for the CHOSEN protocol
      const std::vector<uint8_t>* meta = nullptr;
      for (const auto& pr : kv.second.protocols)
        if (pr.first == g.protocol) { meta = &pr.second; break; }
      static const std::vector<uint8_t> kEmpty;
      w.bytes(meta ? *meta : kEmpty);
    }
  } else {
    w.i32(0);
  }
}

void handle_sync_group(Reader& r, Writer& w) {
  std::string group_id = r.str();
  int32_t generation = r.i32();
  std::string member_id = r.str();
  int32_t nassign = r.i32();
  std::vector<std::pair<std::string, std::vector<uint8_t>>> assignments;
  for (int32_t i = 0; i < nassign; i++) {
    std::string mid = r.str();
    auto blob = r.bytes();
    assignments.emplace_back(mid, blob.value_or(std::vector<uint8_t>{}));
  }
  std::unique_lock<std::mutex> lk(g_mu);
  auto git = g_groups.find(group_id);
  w.i32(0);  // throttle (SyncGroup v1)
  if (git == g_groups.end() || !git->second.members.count(member_id)) {
    w.i16(ERR_UNKNOWN_MEMBER); w.i32(-1);
    return;
  }
  Group& g = git->second;
  if (generation != g.generation) {
    w.i16(ERR_ILLEGAL_GENERATION); w.i32(-1);
    return;
  }
  if (member_id == g.leader) {
    for (auto& kv : assignments) {
      auto mit = g.members.find(kv.first);
      if (mit != g.members.end()) mit->second.assignment = std::move(kv.second);
    }
    g.state = Group::Stable;
    g_group_cv.notify_all();
  } else {
    g_group_cv.wait_for(lk, std::chrono::milliseconds(30000), [&] {
      return g.state == Group::Stable || g.state == Group::PreparingRebalance ||
             g.generation != generation || !g.members.count(member_id);
    });
    if (g.generation != generation || !g.members.count(member_id)) {
      w.i16(g.members.count(member_id) ? ERR_ILLEGAL_GENERATION
                                       : ERR_UNKNOWN_MEMBER);
      w.i32(-1);
      return;
    }
    if (g.state != Group::Stable) {
      // the leader never synced (died mid-rebalance, reaper restarted the
      // round) or the 30s wait timed out: an ERR_NONE with the cleared
      // empty assignment would park this member with zero partitions
      // forever — force a rejoin instead
      w.i16(ERR_REBALANCE_IN_PROGRESS);
      w.i32(-1);
      return;
    }
  }
  w.i16(ERR_NONE);
  w.bytes(g.members[member_id].assignment);
}

void handle_heartbeat(Reader& r, Writer& w) {
  std::string group_id = r.str();
  int32_t generation = r.i32();
  std::string member_id = r.str();
  std::lock_guard<std::mutex> lk(g_mu);
  w.i32(0);  // throttle (v1)
  auto git = g_groups.find(group_id);
  if (git == g_groups.end() || !git->second.members.count(member_id)) {
    w.i16(ERR_UNKNOWN_MEMBER);
    return;
  }
  Group& g = git->second;
  Member& m = g.members[member_id];
  m.deadline_ms = now_ms() + m.session_timeout_ms;
  if (g.state == Group::PreparingRebalance) { w.i16(ERR_REBALANCE_IN_PROGRESS); return; }
  if (generation != g.generation) { w.i16(ERR_ILLEGAL_GENERATION); return; }
  w.i16(ERR_NONE);
}

void handle_leave_group(Reader& r, Writer& w) {
  std::string group_id = r.str();
  std::string member_id = r.str();
  std::lock_guard<std::mutex> lk(g_mu);
  w.i32(0);  // throttle (v1)
  auto git = g_groups.find(group_id);
  if (git != g_groups.end() && git->second.members.erase(member_id)) {
    Group& g = git->second;
    if (g.members.empty()) {
      g.state = Group::Empty;
    } else {
      g.state = Group::PreparingRebalance;
      g.rebalance_deadline_ms = now_ms() + 5000;
      for (auto& kv : g.members) kv.second.joined_round = false;
    }
    g_group_cv.notify_all();
  }
  w.i16(ERR_NONE);
}

void handle_offset_commit(Reader& r, Writer& w) {
  std::string group_id = r.str();
  int32_t generation = r.i32();
  std::string member_id = r.str();
  r.i64();  // retention (v2)
  int32_t ntopics = r.i32();
  std::lock_guard<std::mutex> lk(g_mu);
  Group& g = g_groups[group_id];
  // real-Kafka validation: generation -1 commits are simple-consumer
  // writes and always land; generation-tagged commits must come from a
  // KNOWN member of the CURRENT generation.  Without this, a client's
  // commit-on-revoke after a broker restart would poison the fresh
  // (memory-only) world with positions from the lost one, silently
  // stalling every consumer past the new log end.
  int16_t err = ERR_NONE;
  if (generation >= 0) {
    if (g.members.find(member_id) == g.members.end())
      err = ERR_UNKNOWN_MEMBER;
    else if (generation != g.generation)
      err = ERR_ILLEGAL_GENERATION;
  }
  w.i32(ntopics);
  for (int32_t t = 0; t < ntopics; t++) {
    std::string name = r.str();
    int32_t nparts = r.i32();
    w.str(name);
    w.i32(nparts);
    for (int32_t p = 0; p < nparts; p++) {
      int32_t part = r.i32();
      int64_t off = r.i64();
      r.str();  // metadata
      if (err == ERR_NONE) {
        g.offsets[{name, part}] = off;
        wal_log_offset(group_id, name, part, off);
      }
      w.i32(part);
      w.i16(err);
    }
  }
  wal_flush();
}

void handle_offset_fetch(Reader& r, Writer& w) {
  std::string group_id = r.str();
  int32_t ntopics = r.i32();
  std::lock_guard<std::mutex> lk(g_mu);
  auto git = g_groups.find(group_id);
  w.i32(ntopics);
  for (int32_t t = 0; t < ntopics; t++) {
    std::string name = r.str();
    int32_t nparts = r.i32();
    w.str(name);
    w.i32(nparts);
    for (int32_t p = 0; p < nparts; p++) {
      int32_t part = r.i32();
      int64_t off = -1;
      if (git != g_groups.end()) {
        auto oit = git->second.offsets.find({name, part});
        if (oit != git->second.offsets.end()) off = oit->second;
      }
      w.i32(part);
      w.i64(off);
      w.null_str();  // metadata
      w.i16(ERR_NONE);
    }
  }
}

void handle_create_topics(Reader& r, Writer& w) {
  int32_t ntopics = r.i32();
  std::vector<std::pair<std::string, int32_t>> reqs;
  for (int32_t t = 0; t < ntopics; t++) {
    std::string name = r.str();
    int32_t parts = r.i32();
    r.i16();  // replication
    int32_t nassign = r.i32();
    for (int32_t a = 0; a < nassign; a++) {
      r.i32();
      int32_t nrep = r.i32();
      for (int32_t x = 0; x < nrep; x++) r.i32();
    }
    int32_t nconf = r.i32();
    bool compacted = false;
    for (int32_t c = 0; c < nconf; c++) {
      std::string key = r.str();
      std::string value = r.str();
      if (key == "cleanup.policy" && value.find("compact") != std::string::npos)
        compacted = true;
    }
    if (parts <= 0) parts = kDefaultPartitions;
    reqs.emplace_back(name, compacted ? -parts : parts);
  }
  r.i32();  // timeout
  std::lock_guard<std::mutex> lk(g_mu);
  w.i32(int32_t(reqs.size()));
  for (auto& req : reqs) {
    bool compacted = req.second < 0;
    int32_t parts = compacted ? -req.second : req.second;
    bool existed = g_topics.count(req.first) > 0;
    topic_ref_locked(req.first, parts, compacted);
    wal_flush();
    w.str(req.first);
    w.i16(existed ? int16_t(36) : ERR_NONE);  // 36 = TOPIC_ALREADY_EXISTS
  }
}

// ------------------------------------------------------- session reaping
void reaper() {
  while (true) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1000));
    std::lock_guard<std::mutex> lk(g_mu);
    int64_t now = now_ms();
    for (auto& gkv : g_groups) {
      Group& g = gkv.second;
      bool removed = false;
      for (auto it = g.members.begin(); it != g.members.end();) {
        // Stable: heartbeat deadline governs.  CompletingRebalance: a
        // leader that died before SyncGroup would wedge the group forever
        // — its join-time deadline expires it and restarts the round.
        // PreparingRebalance is exempt (joins block without heartbeating;
        // the rebalance deadline drops stragglers instead).
        bool expired = g.state != Group::PreparingRebalance &&
                       now > it->second.deadline_ms;
        if (expired) { it = g.members.erase(it); removed = true; }
        else ++it;
      }
      if (removed) {
        if (g.members.empty()) {
          g.state = Group::Empty;
        } else {
          g.state = Group::PreparingRebalance;
          g.rebalance_deadline_ms = now + 5000;
          for (auto& kv : g.members) kv.second.joined_round = false;
        }
        g_group_cv.notify_all();
      }
    }
  }
}

// ----------------------------------------------------------------- sasl
void handle_sasl_handshake(Reader& r, Writer& w) {
  std::string mech = r.str();
  w.i16(mech == "PLAIN" ? ERR_NONE : ERR_UNSUPPORTED_SASL_MECHANISM);
  w.i32(1);
  w.str("PLAIN");
}

// → true when the connection is now authenticated
bool handle_sasl_authenticate(Reader& r, Writer& w) {
  auto token = r.bytes();
  bool ok = false;
  if (token) {
    // PLAIN token: [authzid] NUL authcid NUL passwd
    const std::vector<uint8_t>& t = *token;
    size_t first = 0;
    while (first < t.size() && t[first] != 0) first++;
    size_t second = first + 1;
    while (second < t.size() && t[second] != 0) second++;
    if (first < t.size() && second < t.size()) {
      std::string user(t.begin() + first + 1, t.begin() + second);
      std::string pass(t.begin() + second + 1, t.end());
      ok = (user == g_sasl_user && pass == g_sasl_pass);
    }
  }
  if (ok) {
    w.i16(ERR_NONE);
    w.null_str();
    w.bytes({});
  } else {
    w.i16(ERR_SASL_AUTHENTICATION_FAILED);
    w.str("SASL/PLAIN authentication failed");
    w.bytes({});
  }
  return ok;
}

// --------------------------------------------------------------- serving
bool read_exact(int fd, uint8_t* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t k = recv(fd, buf + got, n - got, 0);
    if (k <= 0) return false;
    got += size_t(k);
  }
  return true;
}

void serve(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  bool authenticated = g_sasl_user.empty();
  while (true) {
    uint8_t szbuf[4];
    if (!read_exact(fd, szbuf, 4)) break;
    uint32_t size = (uint32_t(szbuf[0]) << 24) | (uint32_t(szbuf[1]) << 16) |
                    (uint32_t(szbuf[2]) << 8) | szbuf[3];
    if (size == 0 || size > (64u << 20)) break;
    std::vector<uint8_t> req(size);
    if (!read_exact(fd, req.data(), size)) break;
    Reader r(req.data(), req.size());
    int16_t api_key = r.i16();
    int16_t api_version = r.i16();
    int32_t correlation = r.i32();
    r.str();  // client_id

    // with SASL enabled, only ApiVersions/SaslHandshake/SaslAuthenticate
    // are legal pre-auth; anything else drops the connection (the same
    // fail-closed posture real brokers take on an illegal SASL state)
    if (!authenticated && api_key != 17 && api_key != 36 && api_key != 18)
      break;

    Writer w;
    w.i32(0);  // size placeholder
    w.i32(correlation);
    bool supported = true;
    switch (api_key) {
      case 17: handle_sasl_handshake(r, w); break;
      case 36:
        if (handle_sasl_authenticate(r, w)) authenticated = true;
        break;
      case 18: handle_api_versions(w); break;
      case 3:  handle_metadata(r, w); break;
      case 0:  handle_produce(r, w); break;
      case 1:  handle_fetch(r, w); break;
      case 2:  handle_list_offsets(r, w); break;
      case 10: handle_find_coordinator(r, w); break;
      case 11: handle_join_group(r, w); break;
      case 14: handle_sync_group(r, w); break;
      case 12: handle_heartbeat(r, w); break;
      case 13: handle_leave_group(r, w); break;
      case 8:  handle_offset_commit(r, w); break;
      case 9:  handle_offset_fetch(r, w); break;
      case 19: handle_create_topics(r, w); break;
      default: supported = false; break;
    }
    (void)api_version;
    if (!supported) {
      w.buf.resize(8);
      w.i16(ERR_UNSUPPORTED_VERSION);
    }
    w.patch_i32(0, int32_t(w.buf.size() - 4));
    size_t sent = 0;
    bool fail = false;
    while (sent < w.buf.size()) {
      ssize_t k = send(fd, w.buf.data() + sent, w.buf.size() - sent, 0);
      if (k <= 0) { fail = true; break; }
      sent += size_t(k);
    }
    if (fail) break;
  }
  close(fd);
}

// WAL boot replay: frames are length+crc prefixed; a torn/corrupt tail
// (crash mid-append) ends replay cleanly at the last good frame.
void wal_replay(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return;
  g_replaying = true;
  std::vector<uint8_t> frame;
  size_t replayed = 0;
  long good_end = 0;  // file offset after the last fully-valid frame
  for (;;) {
    uint8_t head[8];
    if (fread(head, 1, 8, f) != 8) break;
    uint32_t len = (uint32_t(head[0]) << 24) | (uint32_t(head[1]) << 16) |
                   (uint32_t(head[2]) << 8) | head[3];
    uint32_t crc = (uint32_t(head[4]) << 24) | (uint32_t(head[5]) << 16) |
                   (uint32_t(head[6]) << 8) | head[7];
    if (len == 0 || len > (64u << 20)) break;
    frame.resize(len);
    if (fread(frame.data(), 1, len, f) != len) break;
    if (crc32c(frame.data(), len) != crc) break;
    Reader r(frame.data(), frame.size());
    uint8_t kind = r.i8();
    if (kind == 'T') {
      std::string name = r.str();
      int32_t parts = r.i32();
      bool compacted = r.i8() != 0;
      topic_ref_locked(name, parts, compacted);
    } else if (kind == 'R') {
      std::string topic = r.str();
      int32_t part = r.i32();
      StoredRecord rec;
      rec.timestamp_ms = r.i64();
      rec.key = r.bytes();
      rec.value = r.bytes();
      int32_t nheaders = r.i32();
      for (int32_t h = 0; h < nheaders && r.ok; h++) {
        std::string hk = r.str();
        auto hv = r.bytes();
        rec.headers.emplace_back(hk, hv ? *hv : std::vector<uint8_t>());
      }
      if (!r.ok) break;
      Topic& t = topic_ref_locked(topic);
      if (part >= 0 && size_t(part) < t.partitions.size()) {
        Partition& pa = t.partitions[size_t(part)];
        rec.offset = pa.high_watermark();
        pa.log.push_back(std::move(rec));
      }
    } else if (kind == 'O') {
      std::string group = r.str();
      std::string topic = r.str();
      int32_t part = r.i32();
      int64_t off = r.i64();
      if (r.ok) g_groups[group].offsets[{topic, part}] = off;
    } else {
      break;  // unknown frame kind: stop at the last understood state
    }
    if (!r.ok) break;
    replayed++;
    good_end = ftell(f);
  }
  bool torn = ftell(f) != good_end || fgetc(f) != EOF;
  fclose(f);
  if (torn) {
    // a torn/corrupt tail must be CUT, not appended after: replay stops
    // at the tear, so anything written beyond it would be silently lost
    // on the NEXT restart
    if (truncate(path.c_str(), good_end) != 0)
      fprintf(stderr, "kafkad: could not truncate torn WAL tail of %s: %s\n",
              path.c_str(), strerror(errno));
    else
      fprintf(stderr, "kafkad: truncated torn WAL tail of %s at %ld\n",
              path.c_str(), good_end);
  }
  g_replaying = false;
  if (replayed)
    fprintf(stderr, "kafkad: replayed %zu WAL frames from %s\n",
            replayed, path.c_str());
}

}  // namespace

int main(int argc, char** argv) {
  crc_init();
  int port = argc > 1 ? atoi(argv[1]) : 19192;
  std::string log_dir;
  for (int i = 2; i < argc; i++) {
    if (std::string(argv[i]) == "--log-dir") {
      if (i + 1 >= argc) {
        fprintf(stderr, "--log-dir expects a directory\n");
        return 2;
      }
      log_dir = argv[++i];
      continue;
    }
    if (std::string(argv[i]) == "--advertise-port") {
      if (i + 1 >= argc) {
        fprintf(stderr, "--advertise-port expects a port\n");
        return 2;
      }
      g_advertise_port = atoi(argv[++i]);
      continue;
    }
    if (std::string(argv[i]) == "--sasl") {
      if (i + 1 >= argc) {  // fail CLOSED: never start open when auth was asked for
        fprintf(stderr, "--sasl expects user:pass\n");
        return 2;
      }
      std::string cred(argv[++i]);
      size_t colon = cred.find(':');
      if (colon == std::string::npos) {
        fprintf(stderr, "--sasl expects user:pass\n");
        return 2;
      }
      g_sasl_user = cred.substr(0, colon);
      g_sasl_pass = cred.substr(colon + 1);
    }
  }
  signal(SIGPIPE, SIG_IGN);
  if (!log_dir.empty()) {
    std::string wal_path = log_dir + "/wal.log";
    wal_replay(wal_path);
    g_wal = fopen(wal_path.c_str(), "ab");
    if (!g_wal) {
      fprintf(stderr, "kafkad: cannot open %s for append\n", wal_path.c_str());
      return 2;  // durability was asked for: fail closed, don't run volatile
    }
  }
  int server = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(server, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  if (bind(server, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (port == 0) {
    socklen_t len = sizeof(addr);
    if (getsockname(server, (sockaddr*)&addr, &len) == 0)
      port = ntohs(addr.sin_port);
  }
  g_port = port;
  if (g_advertise_port == 0) g_advertise_port = port;
  listen(server, 64);
  printf("PORT %d\n", port);
  fflush(stdout);
  fprintf(stderr, "kafkad listening on 127.0.0.1:%d\n", port);
  std::thread(reaper).detach();
  for (;;) {
    int fd = accept(server, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(serve, fd).detach();
  }
}
