/* crc32c (Castagnoli) as a tiny shared library for the Python wire client.
 *
 * RecordBatch v2's crc field is crc32c over attributes..end; verifying it
 * in pure Python costs ~100 ns/byte, which would stall the asyncio loop on
 * multi-MiB fetches.  This library does it at memory speed: the SSE4.2
 * crc32 instruction when the CPU has it, a slice-by-8 table otherwise.
 *
 * ABI: uint32_t calfkit_crc32c(const uint8_t *data, size_t n)
 * (matches the pure-Python fallback in calfkit_tpu/mesh/kafka_wire.py).
 */
#include <stddef.h>
#include <stdint.h>

#define POLY 0x82F63B78u

static uint32_t table[8][256];
static int table_ready = 0;

static void init_table(void) {
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (POLY ^ (c >> 1)) : (c >> 1);
        table[0][i] = c;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t c = table[0][i];
        for (int s = 1; s < 8; s++) {
            c = table[0][c & 0xFF] ^ (c >> 8);
            table[s][i] = c;
        }
    }
    table_ready = 1;
}

static uint32_t crc_sw(uint32_t c, const uint8_t *p, size_t n) {
    if (!table_ready) init_table();
    while (n && ((uintptr_t)p & 7)) {
        c = table[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
        n--;
    }
    while (n >= 8) {
        uint64_t v;
        __builtin_memcpy(&v, p, 8);
        v ^= c;
        c = table[7][v & 0xFF] ^ table[6][(v >> 8) & 0xFF] ^
            table[5][(v >> 16) & 0xFF] ^ table[4][(v >> 24) & 0xFF] ^
            table[3][(v >> 32) & 0xFF] ^ table[2][(v >> 40) & 0xFF] ^
            table[1][(v >> 48) & 0xFF] ^ table[0][(v >> 56) & 0xFF];
        p += 8;
        n -= 8;
    }
    while (n--) c = table[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
    return c;
}

#if defined(__x86_64__) || defined(__i386__)
__attribute__((target("sse4.2")))
static uint32_t crc_hw(uint32_t c, const uint8_t *p, size_t n) {
    while (n && ((uintptr_t)p & 7)) {
        c = __builtin_ia32_crc32qi(c, *p++);
        n--;
    }
#if defined(__x86_64__)
    while (n >= 8) {
        uint64_t v;
        __builtin_memcpy(&v, p, 8);
        c = (uint32_t)__builtin_ia32_crc32di(c, v);
        p += 8;
        n -= 8;
    }
#endif
    while (n >= 4) {
        uint32_t v;
        __builtin_memcpy(&v, p, 4);
        c = __builtin_ia32_crc32si(c, v);
        p += 4;
        n -= 4;
    }
    while (n--) c = __builtin_ia32_crc32qi(c, *p++);
    return c;
}
#endif

uint32_t calfkit_crc32c(const uint8_t *data, size_t n) {
    uint32_t c = 0xFFFFFFFFu;
#if defined(__x86_64__) || defined(__i386__)
    if (__builtin_cpu_supports("sse4.2"))
        c = crc_hw(c, data, n);
    else
#endif
        c = crc_sw(c, data, n);
    return c ^ 0xFFFFFFFFu;
}
