// meshd — the single-binary dev-mesh broker.
//
// The reference ships a bundled single-binary Kafka-compatible broker for its
// zero-setup dev mesh (Tansu, spawned by `ck dev`; reference
// cli/_dev_broker.py).  This is our native equivalent: a small TCP broker
// implementing the MeshTransport semantics the framework needs —
// partitioned topics, consumer groups with exclusive partition assignment
// (per-key ordering across processes), broadcast taps, and per-partition
// end offsets for client-side table barriers (every publish is acked before
// the response line returns).
//
// Protocol: newline-delimited text, one request -> one response.
//   ENSURE t1,t2            -> OK
//   PUB topic key* value* hdrs*        (* = base64, '-' for empty)
//                           -> OK <offset>
//   SUB topic group|- latest|earliest  -> OK <subid>
//   POLL subid max timeout_ms -> N <k> then k x: REC part off key* value* hdrs*
//   ENDS topic              -> OK n0,n1,...   (per-partition sizes)
//   PING                    -> PONG
// Subscription cleanup is disconnect-driven: closing the TCP connection
// removes the member and rebalances its partitions.
//
// Dev-grade by design: one thread per connection, one global mutex, no
// persistence.  Build: make -C native   (produces native/bin/meshd)

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

constexpr int kPartitions = 16;

// ---------------------------------------------------------------- base64
const char kB64[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string b64encode(const std::string& in) {
  std::string out;
  int val = 0, valb = -6;
  for (unsigned char c : in) {
    val = (val << 8) + c;
    valb += 8;
    while (valb >= 0) {
      out.push_back(kB64[(val >> valb) & 0x3F]);
      valb -= 6;
    }
  }
  if (valb > -6) out.push_back(kB64[((val << 8) >> (valb + 8)) & 0x3F]);
  while (out.size() % 4) out.push_back('=');
  return out;
}

std::string b64decode(const std::string& in) {
  static int table[256];
  static bool init = false;
  if (!init) {
    std::fill(table, table + 256, -1);
    for (int i = 0; i < 64; i++) table[(unsigned char)kB64[i]] = i;
    init = true;
  }
  std::string out;
  int val = 0, valb = -8;
  for (unsigned char c : in) {
    if (table[c] == -1) break;  // '=' padding or garbage ends the payload
    val = (val << 6) + table[c];
    valb += 6;
    if (valb >= 0) {
      out.push_back(char((val >> valb) & 0xFF));
      valb -= 8;
    }
  }
  return out;
}

// ----------------------------------------------------------------- crc32
uint32_t crc32(const std::string& data) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (unsigned char ch : data) c = table[(c ^ ch) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ----------------------------------------------------------------- state
struct Record {
  std::string key, value, headers;  // raw bytes (headers = JSON text)
  int64_t offset;
};

struct Topic {
  std::vector<std::vector<Record>> parts{kPartitions};
  int64_t next_offset = 0;
  int64_t rr = 0;  // round-robin for keyless records
};

struct Sub {
  std::string topic, group;  // group empty = broadcast tap
  std::vector<int64_t> cursors;  // per-partition (taps own these; groups
                                 // use the shared group cursors)
  bool alive = true;
};

struct GroupState {
  std::vector<int64_t> cursors;
  std::vector<int64_t> members;  // subids, assignment = index round-robin
  GroupState() : cursors(kPartitions, 0) {}
};

std::mutex g_mu;
std::condition_variable g_cv;
std::map<std::string, Topic> g_topics;
std::map<int64_t, Sub> g_subs;
std::map<std::pair<std::string, std::string>, GroupState> g_groups;
int64_t g_next_sub = 1;

Topic& topic_of(const std::string& name) { return g_topics[name]; }

std::vector<int> assigned_partitions(const Sub& sub, int64_t subid) {
  if (sub.group.empty()) {
    std::vector<int> all(kPartitions);
    for (int i = 0; i < kPartitions; i++) all[i] = i;
    return all;
  }
  auto& gs = g_groups[{sub.topic, sub.group}];
  auto it = std::find(gs.members.begin(), gs.members.end(), subid);
  if (it == gs.members.end()) return {};
  int idx = int(it - gs.members.begin());
  int n = int(gs.members.size());
  std::vector<int> mine;
  for (int p = idx; p < kPartitions; p += n) mine.push_back(p);
  return mine;
}

// ------------------------------------------------------------- line io
bool read_line(int fd, std::string& buf, std::string& line) {
  for (;;) {
    auto nl = buf.find('\n');
    if (nl != std::string::npos) {
      line = buf.substr(0, nl);
      buf.erase(0, nl + 1);
      return true;
    }
    char chunk[65536];
    ssize_t n = read(fd, chunk, sizeof(chunk));
    if (n <= 0) return false;
    buf.append(chunk, size_t(n));
  }
}

bool write_all(int fd, const std::string& s) {
  size_t off = 0;
  while (off < s.size()) {
    ssize_t n = write(fd, s.data() + off, s.size() - off);
    if (n <= 0) return false;
    off += size_t(n);
  }
  return true;
}

std::string field(const std::string& s) { return s == "-" ? "" : b64decode(s); }
std::string unfield(const std::string& s) {
  return s.empty() ? "-" : b64encode(s);
}

// ------------------------------------------------------------- handlers
void serve(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string buf, line;
  std::vector<int64_t> my_subs;
  while (read_line(fd, buf, line)) {
    std::istringstream in(line);
    std::string op;
    in >> op;
    if (op == "PING") {
      write_all(fd, "PONG\n");
    } else if (op == "ENSURE") {
      std::string csv;
      in >> csv;
      std::lock_guard<std::mutex> lk(g_mu);
      std::stringstream ss(csv);
      std::string t;
      while (std::getline(ss, t, ',')) {
        if (!t.empty()) topic_of(t);
      }
      write_all(fd, "OK\n");
    } else if (op == "PUB") {
      std::string t, k, v, h;
      in >> t >> k >> v >> h;
      int64_t offset;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        Topic& topic = topic_of(t);
        Record rec{field(k), field(v), field(h), topic.next_offset++};
        int part = rec.key.empty() ? int(topic.rr++ % kPartitions)
                                   : int(crc32(rec.key) % kPartitions);
        offset = rec.offset;
        topic.parts[size_t(part)].push_back(std::move(rec));
      }
      g_cv.notify_all();
      write_all(fd, "OK " + std::to_string(offset) + "\n");
    } else if (op == "SUB") {
      std::string t, g, mode;
      in >> t >> g >> mode;
      if (g == "-") g = "";
      int64_t id;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        Topic& topic = topic_of(t);
        id = g_next_sub++;
        Sub sub;
        sub.topic = t;
        sub.group = g;
        sub.cursors.assign(kPartitions, 0);
        if (mode == "latest") {
          for (int p = 0; p < kPartitions; p++)
            sub.cursors[size_t(p)] = int64_t(topic.parts[size_t(p)].size());
        }
        if (!g.empty()) {
          auto& gs = g_groups[{t, g}];
          if (gs.members.empty() && mode == "latest") {
            for (int p = 0; p < kPartitions; p++)
              gs.cursors[size_t(p)] = int64_t(topic.parts[size_t(p)].size());
          }
          gs.members.push_back(id);
        }
        g_subs[id] = std::move(sub);
      }
      my_subs.push_back(id);
      write_all(fd, "OK " + std::to_string(id) + "\n");
    } else if (op == "POLL") {
      int64_t id, maxn, timeout_ms;
      in >> id >> maxn >> timeout_ms;
      std::vector<std::string> lines;
      // per-partition (start, end) cursor ranges this batch committed —
      // kept so a failed response write can UN-commit (a member leaving
      // mid-poll must not swallow records for the whole group)
      std::map<int, std::pair<int64_t, int64_t>> taken;
      {
        std::unique_lock<std::mutex> lk(g_mu);
        auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(timeout_ms);
        for (;;) {
          auto it = g_subs.find(id);
          if (it == g_subs.end()) break;
          Sub& sub = it->second;
          Topic& topic = topic_of(sub.topic);
          bool group_mode = !sub.group.empty();
          auto* cursors = group_mode
                              ? &g_groups[{sub.topic, sub.group}].cursors
                              : &sub.cursors;
          for (int p : assigned_partitions(sub, id)) {
            auto& part = topic.parts[size_t(p)];
            int64_t start = (*cursors)[size_t(p)];
            while ((*cursors)[size_t(p)] < int64_t(part.size()) &&
                   int64_t(lines.size()) < maxn) {
              const Record& r = part[size_t((*cursors)[size_t(p)])];
              (*cursors)[size_t(p)]++;  // ack-first commit
              lines.push_back("REC " + std::to_string(p) + " " +
                              std::to_string(r.offset) + " " + unfield(r.key) +
                              " " + unfield(r.value) + " " +
                              unfield(r.headers) + "\n");
            }
            if ((*cursors)[size_t(p)] > start)
              taken[p] = {start, (*cursors)[size_t(p)]};
            if (int64_t(lines.size()) >= maxn) break;
          }
          if (!lines.empty() || timeout_ms == 0) break;
          if (g_cv.wait_until(lk, deadline) == std::cv_status::timeout) break;
        }
      }
      std::string out = "N " + std::to_string(lines.size()) + "\n";
      for (auto& l : lines) out += l;
      if (!write_all(fd, out) && !taken.empty()) {
        // consumer vanished between commit and delivery: roll each cursor
        // back IF nobody advanced it further meanwhile (otherwise a
        // rollback would re-deliver a peer's records; accept the rare loss)
        std::lock_guard<std::mutex> lk(g_mu);
        auto it = g_subs.find(id);
        if (it != g_subs.end()) {
          Sub& sub = it->second;
          auto* cursors = !sub.group.empty()
                              ? &g_groups[{sub.topic, sub.group}].cursors
                              : &sub.cursors;
          for (auto& [p, range] : taken)
            if ((*cursors)[size_t(p)] == range.second)
              (*cursors)[size_t(p)] = range.first;
          g_cv.notify_all();
        }
      }
    } else if (op == "ENDS") {
      std::string t;
      in >> t;
      std::string csv;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        Topic& topic = topic_of(t);
        for (int p = 0; p < kPartitions; p++) {
          if (p) csv += ",";
          csv += std::to_string(topic.parts[size_t(p)].size());
        }
      }
      write_all(fd, "OK " + csv + "\n");
    } else {
      write_all(fd, "ERR unknown op\n");
    }
  }
  // connection closed: drop this connection's subscriptions (rebalance)
  std::lock_guard<std::mutex> lk(g_mu);
  for (int64_t id : my_subs) {
    auto it = g_subs.find(id);
    if (it == g_subs.end()) continue;
    if (!it->second.group.empty()) {
      auto& gs = g_groups[{it->second.topic, it->second.group}];
      gs.members.erase(std::remove(gs.members.begin(), gs.members.end(), id),
                       gs.members.end());
    }
    g_subs.erase(it);
  }
  g_cv.notify_all();
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 19092;
  signal(SIGPIPE, SIG_IGN);
  int server = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(server, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  if (bind(server, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (port == 0) {
    // OS-assigned port: report it on stdout for the spawning parent
    // (closes the probe-then-spawn TOCTOU race on busy hosts)
    socklen_t len = sizeof(addr);
    if (getsockname(server, (sockaddr*)&addr, &len) == 0)
      port = ntohs(addr.sin_port);
  }
  listen(server, 64);
  printf("PORT %d\n", port);
  fflush(stdout);
  fprintf(stderr, "meshd listening on 127.0.0.1:%d\n", port);
  for (;;) {
    int fd = accept(server, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(serve, fd).detach();
  }
}
