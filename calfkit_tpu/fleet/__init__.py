"""Serving fleet: replicated engines behind load-, drain- and
prefix-aware mesh routing (ISSUE 7; see docs/fleet.md).

Layers:

- :mod:`calfkit_tpu.fleet.selection` — pure hashing/ranking primitives
  shared with the mesh dispatcher's lane law;
- :mod:`calfkit_tpu.fleet.registry` — the per-instance replica view
  over the compacted ``mesh.engine_stats`` heartbeats;
- :mod:`calfkit_tpu.fleet.policy` — the routing-policy seam
  (least-loaded, power-of-two-choices, prefix-affinity, random);
- :mod:`calfkit_tpu.fleet.router` — registry + policy → one topic per
  call, shared-topic fail-open;
- :mod:`calfkit_tpu.fleet.failover` — in-flight failure recovery
  (ISSUE 9): the dead-placement law, the caller's failover/hedge
  policy, and the stream-resume dedupe ledger.

Re-exports are LAZY (mirroring ``calfkit_tpu/__init__``): the mesh
dispatcher imports ``fleet.selection`` for its lane law, and that import
must stay stdlib-only — an eager ``__init__`` would drag pydantic and
the control-plane models into every process that merely dispatches
records.

The whole package is under the real mypy gate (not in the pyproject
allowlist) and its selection path is guarded by
``scripts/lint_hotpath.py``.
"""

from __future__ import annotations

from importlib import import_module
from typing import TYPE_CHECKING, Any

_LAZY: dict[str, str] = {
    "FailoverPolicy": "calfkit_tpu.fleet.failover",
    "StreamLedger": "calfkit_tpu.fleet.failover",
    "placement_verdict": "calfkit_tpu.fleet.failover",
    "FleetRouter": "calfkit_tpu.fleet.router",
    "Route": "calfkit_tpu.fleet.router",
    "LeastLoaded": "calfkit_tpu.fleet.policy",
    "PowerOfTwoChoices": "calfkit_tpu.fleet.policy",
    "PrefixAffinity": "calfkit_tpu.fleet.policy",
    "RandomChoice": "calfkit_tpu.fleet.policy",
    "RouteRequest": "calfkit_tpu.fleet.policy",
    "RoutingPolicy": "calfkit_tpu.fleet.policy",
    "affinity_key_for": "calfkit_tpu.fleet.policy",
    "resolve_policy": "calfkit_tpu.fleet.policy",
    "Replica": "calfkit_tpu.fleet.registry",
    "ReplicaRegistry": "calfkit_tpu.fleet.registry",
    "eligibility_verdict": "calfkit_tpu.fleet.registry",
    "parse_replicas": "calfkit_tpu.fleet.registry",
}

__all__ = sorted(_LAZY)

if TYPE_CHECKING:  # pragma: no cover
    from calfkit_tpu.fleet.failover import (
        FailoverPolicy,
        StreamLedger,
        placement_verdict,
    )
    from calfkit_tpu.fleet.policy import (
        LeastLoaded,
        PowerOfTwoChoices,
        PrefixAffinity,
        RandomChoice,
        RouteRequest,
        RoutingPolicy,
        affinity_key_for,
        resolve_policy,
    )
    from calfkit_tpu.fleet.registry import (
        Replica,
        ReplicaRegistry,
        eligibility_verdict,
        parse_replicas,
    )
    from calfkit_tpu.fleet.router import FleetRouter, Route


def __getattr__(name: str) -> Any:
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(import_module(module), name)


def __dir__() -> "list[str]":
    return sorted(set(globals()) | set(_LAZY))
