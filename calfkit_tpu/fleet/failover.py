"""In-flight failure recovery: the pure laws (ISSUE 9; see
docs/robustness.md "Failure recovery").

The fleet router (ISSUE 7) routes NEW calls around dead replicas; this
module is about the call that was already PLACED when its replica died —
process kill, OOM, a wedged device grant.  Three small, pure pieces the
gateway's failover supervisor (``client/caller.py``) composes:

- :class:`FailoverPolicy` — the caller's knobs: how often to probe an
  outstanding placement's health, how many re-placements one call may
  burn, and (optionally) the ``hedge_after`` latency past which a
  duplicate dispatch races the original.
- :func:`placement_verdict` — THE dead-placement law, shared by the
  gateway supervisor and the ``ck fleet`` table (one copy, or the
  operator tool drifts from what failover actually does).  A placement
  is dead when its replica's advert is *gone* from the directory,
  *stale* past ``stale_after`` (on the ``cancellation.wall_clock``
  seam), or flipped *unready without draining* (boot loss, wedge
  watchdog).  Draining is NOT dead: a draining replica finishes its
  in-flight work by contract.
- :class:`StreamLedger` — the stream-resume dedupe law.  The gateway
  records the token text the caller has already observed; a failover
  re-dispatch replays the call from the start on the surviving replica
  (the identical prompt rides the prefix cache there), and the ledger
  suppresses exactly the already-delivered prefix of the replayed
  stream, so the caller observes ONE contiguous stream — no duplicated,
  no missing tokens (byte-exact for deterministic decode; offset-exact
  otherwise).

Delivery guarantees these pieces add up to (docs/fleet.md):
**at-least-once placement** (a call may be published to more than one
replica across failovers/hedges), **at-most-once terminal delivery**
(the caller consumes exactly one terminal: the old correlation id is
cancel-tombstoned before every re-dispatch, and each attempt runs under
a FRESH correlation id, so a zombie replica that resumes consuming
faults the orphaned call at its admission gate instead of executing it).
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

from dataclasses import dataclass
from typing import TYPE_CHECKING

from calfkit_tpu import cancellation

if TYPE_CHECKING:  # pragma: no cover
    from calfkit_tpu.fleet.registry import Replica

__all__ = ["FailoverPolicy", "StreamLedger", "placement_verdict"]

PLACEMENT_ALIVE = "alive"


@dataclass(frozen=True)
class FailoverPolicy:
    """Caller-side in-flight recovery knobs (ISSUE 9), applied by
    ``AgentGateway.execute``/``AgentGateway.stream`` on fleet-routed
    clients.  Failover re-dispatches carry the REMAINING deadline (the
    mesh deadline is absolute — a recovered call never gets extra
    budget), run under a fresh correlation id with the dead replica
    excluded from placement, and cancel-tombstone the old correlation so
    a zombie replica cannot execute the orphaned run."""

    # how often (real seconds) the supervisor re-checks an outstanding
    # placement against placement_verdict while awaiting its terminal.
    # The stall a probe can detect is bounded below by the registry's
    # stale_after — probing faster than the heartbeat interval buys
    # nothing but wakeups.
    probe_interval: float = 0.25
    # dead-placement re-dispatches one call may burn (the original
    # attempt is not counted).  Retriable FAULTS are governed by the
    # client's RetryPolicy, not this bound — a fault is an answer, a
    # dead placement is silence.
    max_failovers: int = 2
    # optional tail-latency hedge: with no terminal after this many
    # seconds (on the wall_clock seam, measured from dispatch), a
    # duplicate call is placed on a DIFFERENT replica; the first
    # terminal wins and the loser is cancelled through the ordinary
    # cancel propagation.  None = off.  Hedging applies to execute()
    # only — a hedged stream would interleave two token streams.
    hedge_after: "float | None" = None


@hotpath
def placement_verdict(
    replica: "Replica | None", *, stale_after: float,
    now: "float | None" = None,
) -> str:
    """THE dead-placement law: is a run placed on ``replica`` still being
    served?  Returns ``"alive"`` or the first death reason —
    ``"dead:gone"`` (advert vanished from the directory without a drain),
    ``"dead:stale"`` (heartbeat lapsed past ``stale_after``: process
    kill, OOM, wedged heartbeat loop), ``"dead:unready"`` (the advert
    flipped unready WITHOUT draining — the wedge watchdog's signature).

    Draining and merely-busy replicas are alive: drain finishes in-flight
    work by contract, and load is the router's problem, not failover's.
    ``None`` (replica not in the registry view) is ``dead:gone``."""
    if replica is None:
        return "dead:gone"
    if now is None:
        now = cancellation.wall_clock()
    if replica.age(now) >= stale_after:
        return "dead:stale"
    if not replica.stats.ready and not replica.stats.draining:
        return "dead:unready"
    return PLACEMENT_ALIVE


class StreamLedger:
    """The stream-resume dedupe law (one contiguous stream across
    failover attempts).

    ``filter(chunk)`` is fed every TokenStep text chunk of the CURRENT
    attempt, in order, and returns the portion the caller has not yet
    observed (possibly ``""``).  ``begin_attempt()`` resets the replay
    cursor when a failover re-dispatch starts: the new replica replays
    the answer from the start, and exactly ``len(self.text)`` characters
    of it are suppressed before delivery resumes.

    The law is OFFSET-exact: with deterministic decode (the fleet's
    greedy default) the replayed prefix is byte-identical and the caller
    cannot tell a failover happened; with sampled decode the suffix past
    the offset is delivered as generated (documented in
    docs/robustness.md).

    The ledger can only be as contiguous as DELIVERY: the hub's per-run
    step queue drops oldest past its bound, so a consumer lagging far
    enough to lose token events was observing a gapped stream before any
    failover — the ledger records what the caller actually saw, and the
    resumed offset aligns to that, not to the un-dropped generation.
    Keep consuming the stream promptly (the pre-existing contract for
    lossless token telemetry)."""

    def __init__(self) -> None:
        # everything the caller has observed, across all attempts
        self.text = ""
        # characters seen from the current attempt's stream so far
        self._attempt_seen = 0

    @property
    def delivered(self) -> int:
        return len(self.text)

    def begin_attempt(self) -> None:
        self._attempt_seen = 0

    @hotpath
    def filter(self, chunk: str, offset: "int | None" = None) -> str:
        """The not-yet-observed suffix of ``chunk`` (empty while the
        replay is still inside the already-delivered prefix).

        ``offset`` (ISSUE 10) is the chunk's ABSOLUTE char offset within
        the attempt's answer when the emitter stamped it
        (``TokenStep.offset``): a decode-from-offset RESUME stamps its
        first chunk at the delivered-prefix length — the ledger then
        suppresses nothing, because nothing was re-generated — while a
        re-generating attempt stamps from 0 and the replayed prefix is
        trimmed exactly.  ``None`` (pre-ISSUE-10 emitters) falls back to
        the cumulative chars-seen-this-attempt law, which is identical
        for replay-from-zero streams."""
        start = offset if offset is not None else self._attempt_seen
        self._attempt_seen = start + len(chunk)
        overlap = len(self.text) - start  # chars of chunk already observed
        if overlap >= len(chunk):
            return ""
        fresh = chunk[overlap:] if overlap > 0 else chunk
        self.text += fresh
        return fresh
