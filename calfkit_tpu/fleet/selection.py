"""Pure selection primitives: the hashing layer under every "which of N
do I pick" decision in the mesh (ISSUE 7).

Two layers previously each owned their own selection math — the
key-ordered dispatcher's lane choice (``crc32(key) % lanes`` in
``mesh/dispatch.py``) and the client's engine/topic choice (hardcoded to
the shared agent topic in ``client/caller.py``).  The fleet refactor
splits both out here so routing policies, lane assignment, and any later
placement feature (QoS classes, disaggregated prefill/decode) compose
over the same dependency-free primitives:

- :func:`lane_of` — the dispatcher's exact historical lane law (crc32,
  keyless → lane 0).  Moved, not changed: per-key ordering contracts
  hang off this value being stable across releases.
- :func:`stable_hash` — 64-bit blake2b for affinity keys; NOT Python's
  ``hash()`` (randomized per process — a router must agree with itself
  across restarts and with its peers).
- :func:`rendezvous_rank` — highest-random-weight ordering of candidate
  ids for a key.  The prefix-affinity property fleet routing needs falls
  out of HRW directly: the same key always prefers the same replica, and
  when that replica is ineligible (draining, stale, excluded) the
  NEXT-ranked replica is a stable second home instead of a reshuffle of
  the whole fleet.
- :func:`page_aligned_prefix` — quantize a prompt to page-granular
  prefix boundaries so one session's turns (same instructions/history
  prefix, growing tail) map to one affinity key.

This module must stay dependency-free (stdlib only): ``mesh/dispatch``
imports it, and the mesh layer must not pull in control-plane models.
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

import hashlib
import zlib
from typing import Iterable, Sequence

__all__ = [
    "lane_of",
    "stable_hash",
    "rendezvous_rank",
    "page_aligned_prefix",
]


@hotpath
def lane_of(key: "bytes | None", lanes: int) -> int:
    """The key-ordered dispatcher's lane law (unchanged semantics:
    ``crc32(key) % lanes``; keyless records serialize on lane 0)."""
    if key is None:
        return 0
    return zlib.crc32(key) % lanes


@hotpath
def stable_hash(data: bytes, *, salt: bytes = b"") -> int:
    """Process- and host-stable 64-bit hash (blake2b).

    The salt is folded into the hashed stream (length-prefixed so
    ``salt|data`` boundaries cannot alias), NOT passed as blake2b's
    ``key`` parameter: the key is silently capped at 64 bytes, and a
    rendezvous salt built from a long replica key (``agent.<name>@…``)
    would truncate BEFORE the instance id — every replica hashing
    identically turns prefix-affinity into route-everything-to-the-
    lexicographic-max replica."""
    h = hashlib.blake2b(digest_size=8)
    h.update(len(salt).to_bytes(4, "big"))
    h.update(salt)
    h.update(data)
    return int.from_bytes(h.digest(), "big")


@hotpath
def rendezvous_rank(key: bytes, candidates: Iterable[str]) -> "list[str]":
    """Candidate ids ordered by highest-random-weight for ``key``.

    ``rank[0]`` is the key's home; ``rank[1]`` its stable fallback.
    Adding or removing ONE candidate moves only the keys homed on it —
    the minimal-disruption property that makes affinity survive replica
    churn.  Ties (hash collisions) break on the candidate id itself so
    the ordering is total and deterministic.
    """
    return sorted(
        candidates,
        key=lambda c: (stable_hash(key, salt=c.encode("utf-8")), c),
        reverse=True,
    )


@hotpath
def page_aligned_prefix(
    tokens: "Sequence[int] | str", page: int, *, max_pages: int = 4
) -> "bytes | None":
    """The prompt's page-aligned prefix head as hashable bytes, or
    ``None`` when the prompt is shorter than one page (no shared pages
    to chase — affinity would just be a worse-balanced random policy).

    Accepts token ids (aligned to the KV page size: the unit the
    ``PrefixCache`` caches at) or raw text (callers that have not
    tokenized yet quantize on characters; pick ``page`` ≈ page_size
    tokens × ~4 chars/token).  Two alignment properties matter:

    - truncating to whole pages (not raw length) maps prompts that
      share cached pages to the same key;
    - capping at ``max_pages`` keeps the key stable as a SESSION grows:
      turn N's prompt is turn 1's plus appended history, so hashing the
      full aligned prompt would re-home the session every few turns —
      exactly when its earlier pages are hot on the current home."""
    if page <= 0:
        return None
    aligned = (len(tokens) // page) * page
    if aligned <= 0:
        return None
    head = tokens[: min(aligned, max(1, max_pages) * page)]
    if isinstance(head, str):
        return head.encode("utf-8", errors="replace")
    return b"".join(int(t).to_bytes(8, "big", signed=True) for t in head)
