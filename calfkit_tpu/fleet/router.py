"""The fleet router: registry + policy → one topic per call (ISSUE 7).

Sits on the CALLER side of the mesh (the client owns one), replacing the
hardcoded ``agent_input_topic`` in ``client/caller.py`` with an explicit
placement decision:

    eligible = registry.eligible(agent, exclude=…)   # drain/stale gate
    replica  = policy.select(eligible, request)       # ranking seam
    topic    = replica.topic or shared fallback

Design rules:

- **Fail-open to the shared topic.**  No control plane, a cold
  directory, zero live replicas, every replica excluded — all degrade
  to the pre-fleet shared topic, where consumer-group membership still
  load-balances blindly.  Routing is an optimization; it must never be
  a new way for a call to fail.
- **Reads only.**  The per-call path touches the registry's folded
  table snapshot (host memory) — no broker round-trip, no barrier, no
  lock; ``scripts/lint_hotpath.py`` bans blocking constructs in it.
- **Exclusions are per-pick**, supplied by the caller (the shed-retry
  loop in ``AgentGateway.execute`` excludes the replica that shed).
- **Local in-flight accounting.**  Heartbeat depth is fleet-wide truth
  but lags a beat interval; a router ranking on it alone herds every
  pick between two beats onto the momentary minimum.  The router
  therefore folds its OWN not-yet-returned placements into each
  candidate's depth (``Replica.router_inflight`` — the least-request
  technique client-side balancers use).  Entries clear when the run's
  terminal reply lands (the gateway notifies) and are TTL-swept as a
  leak backstop for runs whose terminal never arrives.
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath


import asyncio
import logging
import time
from dataclasses import dataclass, replace

from calfkit_tpu import protocol
from calfkit_tpu.fleet.policy import (
    RouteRequest,
    RoutingPolicy,
    affinity_key_for,
    resolve_policy,
)
from calfkit_tpu.fleet.registry import Replica, ReplicaRegistry
from calfkit_tpu.mesh.transport import MeshTransport

logger = logging.getLogger(__name__)

__all__ = ["Route", "FleetRouter"]


@dataclass(frozen=True)
class Route:
    """One placement decision: where to publish, and to whom."""

    topic: str
    replica: "Replica | None" = None  # None = shared-topic fallback

    @property
    def instance_id(self) -> "str | None":
        return self.replica.instance_id if self.replica else None


class FleetRouter:
    def __init__(
        self,
        transport: MeshTransport,
        policy: "RoutingPolicy | str" = "p2c",
        *,
        stale_after: "float | None" = None,
        catchup_timeout: float = 30.0,
    ):
        kwargs = {"catchup_timeout": catchup_timeout}
        if stale_after is not None:
            kwargs["stale_after"] = stale_after
        self.registry = ReplicaRegistry(transport, **kwargs)
        self.policy = resolve_policy(policy)
        self._started = False
        # monotonic stamp of the last failed registry start: routing
        # degrades to the shared topic, then RE-PROBES after
        # start_retry_interval — a one-blip broker outage at first call
        # must not disable fleet routing for the client's lifetime
        self._start_failed_at: "float | None" = None
        self.start_retry_interval = 30.0
        # created lazily (constructor may run with no event loop): guards
        # the registry start against concurrent first route() calls —
        # N unguarded awaits would each start a table reader, leaking
        # N-1 broker clients and pump tasks on a real transport
        self._start_lock: "asyncio.Lock | None" = None
        # local in-flight placements, keyed by the FULL replica key
        # ("<node_id>@<instance>"): bare instance ids collide across
        # agents when operators pin stable ids ("r0", "r1") for every
        # agent's replicas, and a collision would charge agent A's
        # backlog against agent B's idle replica.  Values are
        # {correlation: placed-at monotonic}; bounded by construction
        # (one entry per in-flight run of THIS client) with a TTL sweep
        # as the leak backstop for runs whose terminal never arrives.
        self._inflight: "dict[str, dict[str, float]]" = {}
        self.inflight_ttl = 600.0

    # ------------------------------------------------ in-flight accounting
    @hotpath
    def note_dispatch(self, replica_key: str, correlation_id: str) -> None:
        """A run was just placed on the replica (gateway-called)."""
        self._inflight.setdefault(replica_key, {})[correlation_id] = (
            time.monotonic()
        )

    @hotpath
    def note_done(self, replica_key: str, correlation_id: str) -> None:
        """The run's terminal reply landed (any outcome)."""
        entries = self._inflight.get(replica_key)
        if entries is not None:
            entries.pop(correlation_id, None)
            if not entries:
                self._inflight.pop(replica_key, None)

    @hotpath
    def _sweep_inflight(self, now_m: float) -> None:
        """Drop TTL-expired entries and emptied per-instance dicts for
        EVERY instance — including replicas that have left the fleet
        (sweeping only current candidates would leak entries charged to
        a departed replica forever, and a non-empty ``_inflight`` forces
        the per-candidate copy pass in :meth:`select` on every pick)."""
        for replica_key, entries in list(self._inflight.items()):
            stale = [
                corr for corr, placed in entries.items()
                if now_m - placed > self.inflight_ttl
            ]
            for corr in stale:
                del entries[corr]
            if not entries:
                self._inflight.pop(replica_key, None)

    @hotpath
    def _outstanding(self, replica_key: str) -> int:
        entries = self._inflight.get(replica_key)
        return len(entries) if entries else 0

    # ---------------------------------------------- dead-placement law
    def placement_verdict(self, replica_key: str) -> str:
        """Is a run placed on ``replica_key`` still being served (ISSUE
        9)?  ``"alive"``, or ``"dead:gone"`` / ``"dead:stale"`` /
        ``"dead:unready"`` per :func:`calfkit_tpu.fleet.failover.
        placement_verdict`.  Fail-SAFE, not fail-open: with no registry
        view (router never started, directory down) the verdict is
        ``"alive"`` — failover must never fire on blindness, only on
        positive evidence of death."""
        if not self._started:
            return "alive"
        from calfkit_tpu.fleet.failover import placement_verdict

        return placement_verdict(
            self.registry.replica(replica_key),
            stale_after=self.registry.stale_after,
        )

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._started:
            return
        if self._start_lock is None:
            self._start_lock = asyncio.Lock()
        async with self._start_lock:  # single-flight across callers
            if self._started:
                return
            await self.registry.start()
            # atomicity-ok: double-checked under _start_lock (re-read
            # inside the lock above)
            self._started = True
            self._start_failed_at = None

    async def stop(self) -> None:
        if self._start_lock is None:
            self._start_lock = asyncio.Lock()
        async with self._start_lock:
            # serialized with start(): stopping while a first route()'s
            # catch-up is in flight must wait for it, or registry.stop()
            # would no-op (registry not yet marked started) and the
            # reader's broker client + pump task would outlive the client
            self._started = False
            await self.registry.stop()

    # --------------------------------------------------------------- route
    async def route(
        self,
        agent: str,
        *,
        prompt_text: str = "",
        correlation_id: str = "",
        exclude: "frozenset[str] | set[str]" = frozenset(),
    ) -> Route:
        """Pick a target topic for one call to ``agent``.  Never raises:
        any trouble (directory unreadable, no live replicas) returns the
        shared-topic fallback."""
        shared = Route(topic=protocol.agent_input_topic(agent))
        if not self._started:
            if (
                self._start_failed_at is not None
                and time.monotonic() - self._start_failed_at
                < self.start_retry_interval
            ):
                return shared  # directory recently failed: don't re-pay yet
            try:
                await self.start()
            except Exception:  # noqa: BLE001 - fail-open to shared topic
                # atomicity-ok: _start_failed_at is a rate-limit stamp —
                # concurrent failed routes both stamping is last-wins and
                # only widens the retry backoff by one interval
                self._start_failed_at = time.monotonic()
                logger.warning(
                    "fleet registry unavailable; routing %s via the "
                    "shared topic (re-probing in %.0fs)",
                    agent, self.start_retry_interval, exc_info=True,
                )
                return shared
        try:
            replica = self.select(
                agent,
                prompt_text=prompt_text,
                correlation_id=correlation_id,
                exclude=exclude,
            )
        except Exception:  # noqa: BLE001 - the never-raises contract
            # covers the whole pick, not just registry start: a custom
            # policy's select() or a broken reader read degrades to the
            # shared topic instead of failing the call
            logger.warning(
                "replica selection failed for %s; using the shared topic",
                agent, exc_info=True,
            )
            return shared
        if replica is None:
            return shared
        return Route(topic=replica.topic, replica=replica)

    @hotpath
    def select(
        self,
        agent: str,
        *,
        prompt_text: str = "",
        correlation_id: str = "",
        exclude: "frozenset[str] | set[str]" = frozenset(),
    ) -> "Replica | None":
        """The synchronous per-dispatch selection path (registry snapshot
        + pure policy; guarded by lint_hotpath): ``None`` = no eligible
        replica, use the shared topic."""
        candidates = self.registry.eligible(agent, exclude=exclude)
        if not candidates:
            return None
        if self._inflight:
            # fold this router's own not-yet-returned placements into
            # the heartbeat depths (least-request accounting)
            self._sweep_inflight(time.monotonic())
        if self._inflight:
            candidates = [
                replace(r, router_inflight=self._outstanding(r.key))
                for r in candidates
            ]
        request = RouteRequest(
            agent=agent,
            affinity_key=affinity_key_for(prompt_text),
            correlation_id=correlation_id,
        )
        return self.policy.select(candidates, request)
