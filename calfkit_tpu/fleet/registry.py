"""The replica registry: per-INSTANCE view over ``mesh.engine_stats``.

``ControlPlaneView`` deliberately collapses instance-keyed records to one
live record per node name (freshest heartbeat wins) — correct for "is
agent X up", fatally wrong for a fleet: N replicas of the same model
serve under ONE node name, and the router needs all of them, each with
its own heartbeat age, queue depth, drain flag, and replica-addressed
topic.  :class:`ReplicaRegistry` therefore reads the same compacted
table but keeps every ``<node_id>@<instance>`` key separate.

Eligibility rules (DeServe's placement/overload-isolation loop,
arXiv:2501.14784 — see docs/fleet.md):

- **stale heartbeat** (``now - heartbeat_at >= stale_after`` on the
  :func:`calfkit_tpu.cancellation.wall_clock` seam) → ineligible until
  the replica re-advertises; a wedged worker must stop receiving
  traffic without anyone deregistering it;
- **draining** (``EngineStatsRecord.draining``) → ineligible for NEW
  runs; in-flight work finishes on the replica untouched;
- **not ready** (boot not finished, readiness probe false) → ineligible;
- **excluded** (caller-supplied instance ids — the shed-retry loop
  excludes the replica that just refused) → ineligible for this pick.

Everything here is a read path: the registry never publishes.
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

import logging
import zlib
from dataclasses import dataclass

from pydantic import ValidationError

from calfkit_tpu import cancellation, protocol
from calfkit_tpu.mesh.tables import TableReader
from calfkit_tpu.mesh.transport import MeshTransport
from calfkit_tpu.models.records import (
    SCHEMA_VERSION,
    ControlPlaneRecord,
    EngineStatsRecord,
)

logger = logging.getLogger(__name__)

__all__ = [
    "Replica",
    "ReplicaRegistry",
    "eligibility_verdict",
    "parse_replicas",
]

DEFAULT_STALE_AFTER = 15.0  # matches ControlPlaneConfig 5s beat × 3


@dataclass(frozen=True)
class Replica:
    """One live engine-backed instance, as the router sees it."""

    key: str  # "<node_id>@<instance_id>" — the control-plane record key
    node_id: str  # e.g. "agent.support"
    instance_id: str
    heartbeat_at: float
    stats: EngineStatsRecord
    # requests THIS router placed on the replica that have not returned
    # yet (FleetRouter's local accounting).  The heartbeat depth is the
    # fleet-wide truth but lags a beat interval; without the local
    # share, every pick between two beats sees the same depths and a
    # least-loaded policy herds the whole gap onto one replica.
    router_inflight: int = 0

    @property
    def agent_name(self) -> str:
        """The node name without its kind prefix ("agent.x" -> "x")."""
        _, _, name = self.node_id.partition(".")
        return name or self.node_id

    @property
    def model_name(self) -> str:
        return self.stats.model_name

    @property
    def topic(self) -> str:
        """The replica-addressed input topic ("" = shared-topic only)."""
        return self.stats.replica_topic

    @property
    def queue_depth(self) -> int:
        """The load signal policies rank on: slots occupied plus requests
        admitted but still queued for a slot (per the last heartbeat),
        plus this router's own not-yet-returned placements."""
        return (
            self.stats.active_requests
            + self.stats.pending_requests
            + self.router_inflight
        )

    @property
    def batch_depth(self) -> int:
        """The batch-class share of the advertised QUEUE (ISSUE 20):
        queued batch requests per the last heartbeat.  The routing
        tiebreak signal — at equal total depth, prefer the replica whose
        backlog is batch-heavy, because its queued work is exactly what
        priority shedding will evict if an interactive arrival needs the
        slot.  Zero everywhere when no batch traffic exists (and on
        pre-QoS adverts), so the tiebreak is exactly neutral for
        single-class fleets — pinned pre-QoS timelines are unchanged."""
        return self.stats.batch_pending

    @property
    def dispatch_ewma(self) -> float:
        """EWMA decode-dispatch latency (ms) from the advert — the
        many-router coherence tiebreak (ISSUE 10): when queue depths tie
        (the normal state between heartbeat beats), policies prefer the
        replica that is actually dispatching faster, so N independent
        routers stop herding onto one lexicographic winner.  0.0 = no
        signal (pre-EWMA advert, never-dispatched engine): the policy
        ranks it LAST among ties — no latency evidence must not read as
        zero latency — and all-unknown ties fall to the stable key."""
        return self.stats.dispatch_ewma_ms

    @property
    def headroom_pages(self) -> "int | None":
        """Pages an admission could obtain on this replica right now
        (ISSUE 19): the advert's pages_total minus live-owner pages —
        free-list pages plus evictable zero-ref cached pages.  None when
        the replica advertises no page pool (dense layout or a
        pre-capacity record): no signal must not read as zero headroom,
        or a density-aware policy would starve every legacy replica."""
        total = self.stats.pages_total
        if total <= 0:
            return None
        return max(0, total - self.stats.pages_in_use)

    def age(self, now: "float | None" = None) -> float:
        if now is None:
            now = cancellation.wall_clock()
        return max(0.0, now - self.heartbeat_at)


@hotpath
def eligibility_verdict(
    replica: Replica, *, stale_after: float, now: "float | None" = None
) -> str:
    """THE eligibility law, shared by the router's filter and the
    ``ck fleet`` ROUTE column (one copy, or the operator tool drifts
    from what the router actually does): ``"yes"`` = routable for a NEW
    run, else the first reason it is skipped — ``"shared-only"`` (not
    individually addressable), ``"stale"`` (wedged heartbeat),
    ``"drain"``, ``"unready"``.  Caller-supplied exclusions are
    per-pick state, not part of the verdict."""
    if now is None:
        now = cancellation.wall_clock()
    if not replica.topic:
        return "shared-only"
    if replica.age(now) >= stale_after:
        return "stale"
    if replica.stats.draining:
        return "drain"
    if not replica.stats.ready:
        return "unready"
    return "yes"


def parse_replicas(items: "dict[str, bytes]") -> "list[Replica]":
    """Fold raw compacted-table items into per-instance replicas.

    Undecodable and foreign-schema records are skipped (same leniency as
    ``ControlPlaneView``); staleness is NOT applied here — callers that
    render (``ck fleet``) want stale rows visible, callers that route
    (:meth:`ReplicaRegistry.eligible`) filter them."""
    out: list[Replica] = []
    for key, raw in items.items():
        try:
            wrapped = ControlPlaneRecord.from_wire(raw)
            if wrapped.schema_version != SCHEMA_VERSION:
                continue
            stats = EngineStatsRecord.model_validate(wrapped.record)
        except (ValidationError, ValueError):
            # blocking-ok: the undecodable-record debug floor — fires only
            # for a CORRUPT advert (never per healthy parse), lazily
            # %-formatted, and _parsed's version fast path means a stable
            # corrupt record is logged once per table change, not per call
            logger.debug("undecodable engine-stats record %s", key)
            continue
        out.append(
            Replica(
                key=key,
                node_id=stats.node_id,
                instance_id=(
                    stats.instance_id or wrapped.stamp.instance_id
                ),
                heartbeat_at=wrapped.stamp.heartbeat_at,
                stats=stats,
            )
        )
    return sorted(out, key=lambda r: r.key)


class ReplicaRegistry:
    def __init__(
        self,
        transport: MeshTransport,
        *,
        stale_after: float = DEFAULT_STALE_AFTER,
        catchup_timeout: float = 30.0,
    ):
        self._reader: TableReader = transport.table_reader(
            protocol.ENGINE_STATS_TOPIC
        )
        self.stale_after = stale_after
        self._catchup_timeout = catchup_timeout
        self._started = False
        # parsed-replica cache keyed on a cheap fingerprint: the table
        # only changes once per heartbeat tick, but routing reads it per
        # CALL — re-running pydantic validation per replica per pick
        # would put JSON decode on the exact path lint_hotpath guards.
        # Readers that maintain a mutation version counter (all in-repo
        # transports do — ISSUE 9 satellite) make the no-change case a
        # single int compare, O(1) in table size; readers without one
        # fall back to the crc32 byte scan (~100x cheaper than the
        # parse, but still O(table bytes) per pick).
        self._cache_fp: "tuple | None" = None
        self._cache: "list[Replica]" = []
        self._cache_by_key: "dict[str, Replica]" = {}

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._started:
            return
        await self._reader.start(timeout=self._catchup_timeout)
        # atomicity-ok: single-flight via FleetRouter.start's lock (the
        # only caller); a double reader catch-up is idempotent regardless
        self._started = True

    async def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        await self._reader.stop()

    async def barrier(self) -> None:
        await self._reader.barrier()

    @property
    def is_caught_up(self) -> bool:
        return self._started and self._reader.is_caught_up

    # --------------------------------------------------------------- reads
    @hotpath
    def _parsed(self) -> "list[Replica]":
        version = self._reader.version
        if version is not None:
            # O(1) no-change fast path: the reader bumps its version on
            # every view mutation, so an unchanged table is one int
            # compare — no byte scan, no items() dict copy
            fp: tuple = ("v", version)
            if fp == self._cache_fp:
                return self._cache
            items = self._reader.items()
        else:
            items = self._reader.items()
            crc = 0
            for key, value in items.items():
                crc = zlib.crc32(value, zlib.crc32(key.encode("utf-8"), crc))
            # empty table ≠ crc seed 0
            fp = ("crc", (crc << 1) | 1 if items else 0)
        if fp != self._cache_fp:
            self._cache = parse_replicas(items)
            self._cache_by_key = {r.key: r for r in self._cache}
            self._cache_fp = fp
        return self._cache

    @hotpath
    def replicas(
        self,
        *,
        agent: "str | None" = None,
        model: "str | None" = None,
    ) -> "list[Replica]":
        """Every advertised replica (stale and draining INCLUDED — this
        is the rendering/debugging read), optionally filtered by agent
        name or model name."""
        out = self._parsed()
        if agent is not None:
            out = [r for r in out if r.agent_name == agent]
        if model is not None:
            out = [r for r in out if r.model_name == model]
        # never hand out the cache list itself: a caller-side sort/append
        # would poison every later read
        return list(out) if out is self._cache else out

    @hotpath
    def replica(self, key: str) -> "Replica | None":
        """One replica by its full ``<node_id>@<instance>`` key, or None
        when its record left the table (tombstoned, compacted away).  The
        failover supervisor's per-probe lookup — O(1) off the parsed
        cache (ISSUE 9)."""
        self._parsed()
        return self._cache_by_key.get(key)

    @hotpath
    def eligible(
        self,
        agent: str,
        *,
        exclude: "frozenset[str] | set[str]" = frozenset(),
        now: "float | None" = None,
    ) -> "list[Replica]":
        """Replicas a NEW run may be routed to: verdict ``"yes"`` under
        :func:`eligibility_verdict` and not in ``exclude``."""
        if now is None:
            now = cancellation.wall_clock()
        return [
            r
            for r in self.replicas(agent=agent)
            if r.instance_id not in exclude
            and eligibility_verdict(
                r, stale_after=self.stale_after, now=now
            ) == "yes"
        ]
