"""Routing policies: the explicit engine-selection seam (ISSUE 7).

A policy is a PURE function over an already-filtered candidate list:
eligibility (staleness, drain, readiness, exclusions) is the registry's
job, ranking is the policy's.  Keeping policies pure — no I/O, no
clocks, no broker reads — is what lets ``tests/test_fleet.py`` pin
their distribution properties in isolation and lets the hot routing
path stay allocation-light (``scripts/lint_hotpath.py`` guards the
``select`` bodies).

Shipped policies:

- :class:`LeastLoaded` — global minimum queue depth.  Best placement
  per pick, but every concurrent router chasing the same minimum herds
  onto one replica between heartbeats.
- :class:`PowerOfTwoChoices` — sample two, take the less loaded
  (Mitzenmacher): near-optimal load spread with O(1) state reads and no
  herd, the fleet default.
- :class:`PrefixAffinity` — rendezvous-hash the request's page-aligned
  prompt prefix over the candidates so repeat agent sessions land on
  the replica whose ``PrefixCache`` already holds their shared-prefix
  pages; requests with no affinity key (short prompts) fall through to
  a load-aware fallback policy, and an ineligible home (draining,
  stale, shed-excluded) falls back to the key's stable next-ranked
  replica — not a fleet-wide reshuffle.

``rng`` knobs follow the :class:`~calfkit_tpu.client.caller.RetryPolicy`
convention: a zero-arg callable returning a float in ``[0, 1)``, so the
chaos harness and the distribution tests inject determinism.
"""

from __future__ import annotations

from calfkit_tpu.effects import hotpath

import random
from dataclasses import dataclass, field
from typing import Callable, Protocol, Sequence

from calfkit_tpu.fleet.registry import Replica
from calfkit_tpu.fleet.selection import page_aligned_prefix, stable_hash

__all__ = [
    "RouteRequest",
    "RoutingPolicy",
    "LeastLoaded",
    "PowerOfTwoChoices",
    "PrefixAffinity",
    "RandomChoice",
    "affinity_key_for",
    "resolve_policy",
    "POLICY_NAMES",
]

# default affinity quantum for UNtokenized prompts: ~page_size (16)
# tokens × ~4 chars/token.  Token-level alignment happens engine-side;
# the router only needs session turns to collapse to one key.
DEFAULT_AFFINITY_PAGE_CHARS = 64


@dataclass(frozen=True)
class RouteRequest:
    """What a policy may rank on for one placement decision."""

    agent: str
    affinity_key: "bytes | None" = None
    correlation_id: str = ""


class RoutingPolicy(Protocol):
    def select(
        self, candidates: Sequence[Replica], request: RouteRequest
    ) -> "Replica | None": ...


@hotpath
def _least(candidates: Sequence[Replica]) -> "Replica | None":
    # ties break FIRST on the advertised BATCH queue share, descending
    # (ISSUE 20: at equal total depth, a batch-heavy backlog is the
    # cheaper home — its queued work is exactly what priority shedding
    # evicts if an interactive arrival needs the slot, so interactive
    # latency there is bounded by sheds, not by the whole queue; with no
    # batch traffic anywhere, and on pre-QoS adverts, every replica
    # reports 0 and the tiebreak is exactly neutral — single-class
    # timelines are unchanged), THEN on the advert's EWMA dispatch
    # latency (ISSUE 10: between heartbeat beats N routers see identical
    # depths — breaking the tie on which replica actually dispatches
    # faster spreads the herd), THEN on the stable replica key, never on
    # list order: two routers looking at the same directory must still
    # agree.  A 0.0 EWMA means NO SIGNAL (pre-EWMA advert in a rolling
    # upgrade, or an engine that never dispatched) and ranks LAST among
    # ties — sorting it first would deterministically herd ALL tied
    # traffic onto the one replica nobody has latency evidence for, the
    # exact failure this tiebreak exists to kill.  All-unknown ties fall
    # through to the stable key, the pre-EWMA law.
    return min(
        candidates,
        key=lambda r: (
            r.queue_depth,
            -r.batch_depth,
            r.dispatch_ewma or float("inf"),
            r.key,
        ),
        default=None,
    )


@dataclass(frozen=True)
class LeastLoaded:
    """Global minimum queue depth (ties → lexicographic replica key)."""

    @hotpath
    def select(
        self, candidates: Sequence[Replica], request: RouteRequest
    ) -> "Replica | None":
        return _least(candidates)


@dataclass(frozen=True)
class RandomChoice:
    """Uniform random placement — the A/B baseline, not a recommendation."""

    rng: "Callable[[], float] | None" = None

    @hotpath
    def select(
        self, candidates: Sequence[Replica], request: RouteRequest
    ) -> "Replica | None":
        if not candidates:
            return None
        draw = (self.rng or random.random)()
        return candidates[min(int(draw * len(candidates)), len(candidates) - 1)]


@dataclass(frozen=True)
class PowerOfTwoChoices:
    """Two uniform samples, keep the less loaded (Mitzenmacher 2001)."""

    rng: "Callable[[], float] | None" = None

    @hotpath
    def select(
        self, candidates: Sequence[Replica], request: RouteRequest
    ) -> "Replica | None":
        n = len(candidates)
        if n <= 2:
            return _least(candidates)
        rng = self.rng or random.random
        i = min(int(rng() * n), n - 1)
        j = min(int(rng() * (n - 1)), n - 2)
        if j >= i:  # second draw over the remaining n-1: distinct by law
            j += 1
        return _least([candidates[i], candidates[j]])


@dataclass(frozen=True)
class PrefixAffinity:
    """Rendezvous-hashed session stickiness over shared-prefix pages.

    ``fallback`` ranks requests that carry no affinity key; it defaults
    to :class:`PowerOfTwoChoices` so a fleet configured for affinity
    degrades to load-aware (not random) placement on cold prompts."""

    fallback: RoutingPolicy = field(default_factory=PowerOfTwoChoices)

    @hotpath
    def select(
        self, candidates: Sequence[Replica], request: RouteRequest
    ) -> "Replica | None":
        if not candidates:
            return None
        affinity_key = request.affinity_key
        if affinity_key is None:
            return self.fallback.select(candidates, request)
        # the highest-random-weight pick — identical ordering law to
        # selection.rendezvous_rank, computed as an O(n) max instead of
        # a full sort (only the top rank is ever consumed: candidates
        # were pre-filtered for eligibility, so the max IS the best
        # still-eligible home, and a draining/stale/excluded home never
        # reaches this list — the key's next-ranked replica takes over
        # with no fleet-wide reshuffle)
        return max(
            candidates,
            key=lambda r: (
                stable_hash(affinity_key, salt=r.key.encode("utf-8")),
                r.key,
            ),
        )


@hotpath
def affinity_key_for(
    prompt: "Sequence[int] | str",
    *,
    page: "int | None" = None,
) -> "bytes | None":
    """The request's affinity key: hashable page-aligned prompt prefix
    (``None`` = no shared pages worth chasing; see selection module)."""
    if page is None:
        page = DEFAULT_AFFINITY_PAGE_CHARS if isinstance(prompt, str) else 16
    return page_aligned_prefix(prompt, page)


# names accepted wherever a policy can be configured (CLI, Client kwarg)
POLICY_NAMES = ("least-loaded", "p2c", "prefix-affinity", "random")


def resolve_policy(policy: "RoutingPolicy | str") -> RoutingPolicy:
    if not isinstance(policy, str):
        return policy
    table: dict[str, Callable[[], RoutingPolicy]] = {
        "least-loaded": LeastLoaded,
        "p2c": PowerOfTwoChoices,
        "power-of-two": PowerOfTwoChoices,
        "prefix-affinity": PrefixAffinity,
        "random": RandomChoice,
    }
    try:
        return table[policy]()
    except KeyError:
        raise ValueError(
            f"unknown routing policy {policy!r} (one of {POLICY_NAMES})"
        ) from None
