"""calfkit_tpu.sim — the deterministic fleet simulator (ISSUE 11).

One package, four layers:

- **seams** (`clock`, `ids`, `chaos`, `transport`): the virtual clock +
  event heap, deterministic id minting, scripted fault injectors, and
  the per-replica death/partition transport — promoted from
  ``tests/_chaos.py`` so the simulator and the chaos tests share one
  implementation (``tests/_chaos.py`` remains as an import shim).
- **fleet shape** (`topology`, `stubs`): N real Workers of one agent
  name on a shared mesh, engines replaced by virtual-latency stubs.
- **scenarios** (`scenario`, `runner`, `report`): the declarative DSL
  (arrival curves, tenants, scripted death/partition/heal, lease
  churn), the discrete-event runner over the REAL
  mesh→worker→router path, and the SIM.json report shape.
- **the pinned suite** (`suite`): the scenarios ``scripts/perf_gate.py``
  runs and gates against SIM_BASELINE.json on every PR.

See docs/simulation.md for the scenario DSL, the metric definitions,
the determinism contract, and the tolerance policy.
"""

from calfkit_tpu.sim.chaos import (
    BrokerChaos,
    ChaosScript,
    assert_engine_drained,
    settle,
)
from calfkit_tpu.sim.clock import DEFAULT_EPOCH, VirtualClock, virtual_clock
from calfkit_tpu.sim.ids import deterministic_ids
from calfkit_tpu.sim.report import (
    CheckResult,
    ScenarioReport,
    SimReport,
    strip_capture,
)
from calfkit_tpu.sim.runner import SimRunner, run_scenario
from calfkit_tpu.sim.scenario import (
    Check,
    LeaseChurn,
    LoadPhase,
    ReplicaEvent,
    Scenario,
    ServiceSpec,
    TenantSpec,
    diurnal_phases,
)
from calfkit_tpu.sim.stubs import (
    BijectiveTokenizer,
    ServingStubModel,
    SimEngineModel,
    StreamingStubModel,
)
from calfkit_tpu.sim.topology import FleetTopology
from calfkit_tpu.sim.transport import ReplicaTransport

__all__ = [
    "BrokerChaos",
    "ChaosScript",
    "assert_engine_drained",
    "settle",
    "DEFAULT_EPOCH",
    "VirtualClock",
    "virtual_clock",
    "deterministic_ids",
    "CheckResult",
    "ScenarioReport",
    "SimReport",
    "strip_capture",
    "SimRunner",
    "run_scenario",
    "Check",
    "LeaseChurn",
    "LoadPhase",
    "ReplicaEvent",
    "Scenario",
    "ServiceSpec",
    "TenantSpec",
    "diurnal_phases",
    "BijectiveTokenizer",
    "ServingStubModel",
    "SimEngineModel",
    "StreamingStubModel",
    "FleetTopology",
    "ReplicaTransport",
]
