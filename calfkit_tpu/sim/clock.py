"""The simulator's time authority: a virtual clock with an event heap.

:class:`VirtualClock` (promoted from ``tests/_chaos.py`` — ISSUE 11) is a
controllable stand-in for :data:`calfkit_tpu.cancellation.wall_clock`,
THE deadline/staleness clock every layer reads.  Installing one via
:func:`virtual_clock` moves client deadline mint, hop expiry, engine
admission/reap, heartbeat stamps, lease lapse, and placement verdicts in
lockstep; scenarios advance time explicitly and nothing sleeps to make a
deadline pass.

ISSUE 11 adds the **event heap**: ``schedule(at, fn)`` registers a
callback at an absolute virtual time, and every ``advance``/
``advance_to``/``advance_to_next`` fires due callbacks IN ORDER, with
``now`` set to each event's own timestamp while it runs — so a callback
that schedules relative work (``clock.now + service_s``) composes
correctly even when one advance crosses many events.  Ties fire in
scheduling order (a monotonic sequence number breaks them), which is
what makes the fleet simulator's discrete-event loop reproducible.

No wall-clock reads anywhere in this module — ``scripts/lint_hotpath.py``
bans ``time.time``/``time.monotonic``/``time.perf_counter`` across the
whole sim package (the ``wall_clock`` seam is the one clock).
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
from typing import Callable, Iterator

from calfkit_tpu import cancellation

__all__ = ["VirtualClock", "virtual_clock", "DEFAULT_EPOCH"]

# an arbitrary fixed epoch well inside "plausible wall clock" so absolute
# deadlines/stamps look realistic in dumps, far from zero-is-falsy bugs
DEFAULT_EPOCH = 1_700_000_000.0


class VirtualClock:
    """A controllable stand-in for ``cancellation.wall_clock`` with an
    ordered virtual-event heap (the fleet simulator's timeline)."""

    def __init__(self, start: float = DEFAULT_EPOCH):
        self.now = float(start)
        self._heap: "list[tuple[float, int, Callable[[], object]]]" = []
        self._seq = itertools.count()
        self.fired = 0  # lifetime events fired (runner progress metric)

    def __call__(self) -> float:
        return self.now

    # ------------------------------------------------------------- events
    def schedule(self, at: float, fn: "Callable[[], object]") -> None:
        """Register ``fn`` to fire when the clock reaches virtual time
        ``at`` (clamped to ``now`` — the past is not schedulable).  Fire
        order is (time, registration order); callbacks run synchronously
        inside the advance that crosses them."""
        heapq.heappush(self._heap, (max(float(at), self.now), next(self._seq), fn))

    @property
    def next_event_at(self) -> "float | None":
        """Virtual timestamp of the earliest pending event (None = no
        pending events)."""
        return self._heap[0][0] if self._heap else None

    @property
    def pending_events(self) -> int:
        return len(self._heap)

    def fire_due(self) -> int:
        """Fire every event scheduled at or before ``now``; returns the
        count fired.  Callbacks may schedule further events (fired in the
        same pass when due)."""
        fired = 0
        while self._heap and self._heap[0][0] <= self.now:
            _, _, fn = heapq.heappop(self._heap)
            fn()
            fired += 1
            self.fired += 1
        return fired

    # ----------------------------------------------------------- advances
    def advance(self, seconds: float) -> float:
        """Advance by ``seconds``, firing every event the jump crosses
        (each with ``now`` at its own timestamp).  Returns the new now."""
        return self.advance_to(self.now + seconds)

    def advance_to(self, target: float) -> float:
        target = max(float(target), self.now)
        while self._heap and self._heap[0][0] <= target:
            at = self._heap[0][0]
            if at > self.now:
                self.now = at
            self.fire_due()
        self.now = target
        return self.now

    def advance_to_next(self) -> bool:
        """Jump to the earliest pending event and fire everything due at
        that instant.  False when the heap is empty (time holds still)."""
        if not self._heap:
            return False
        self.advance_to(self._heap[0][0])
        return True


@contextlib.contextmanager
def virtual_clock(start: float = DEFAULT_EPOCH) -> "Iterator[VirtualClock]":
    """Install a :class:`VirtualClock` as THE package deadline clock for
    the duration of the block (every caller reads it through the module
    attribute, so one swap moves all layers in lockstep)."""
    clock = VirtualClock(start)
    previous = cancellation.wall_clock
    cancellation.wall_clock = clock
    try:
        yield clock
    finally:
        cancellation.wall_clock = previous
