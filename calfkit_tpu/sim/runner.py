"""The fleet simulator's discrete-event loop (ISSUE 11).

:class:`SimRunner` executes one :class:`~calfkit_tpu.sim.scenario.
Scenario` against the REAL serving stack — an ``InMemoryMesh``, N real
Workers (own dispatch lanes, own control-plane publishers), the real
node kernel and agent turn, the real ``FleetRouter``/``ReplicaRegistry``
over the real compacted ``mesh.engine_stats`` table, a real ``Client``
with the real shed-retry and failover supervision — with exactly ONE
substitution: the inference engine is a
:class:`~calfkit_tpu.sim.stubs.SimEngineModel`, whose service times are
virtual.  Simulated hours cost seconds of host time, and the whole
timeline is a pure function of the scenario seed.

How determinism is achieved (docs/simulation.md "Determinism"):

- every layer's clock reads ride the ``cancellation.wall_clock`` seam,
  swapped for a :class:`~calfkit_tpu.sim.clock.VirtualClock`;
- every id mint (instance ids, correlation ids, lease ids) rides
  :func:`~calfkit_tpu.sim.ids.deterministic_ids`;
- every stochastic choice (arrivals, tenants, policy sampling, retry
  jitter) rides an injected ``random.Random(seed)`` stream;
- NOTHING in the loop waits on host time: heartbeats are virtual
  events (``FleetTopology.beat_all``), the caller's retry backoff is
  zero-delay, the failover supervisor's probe interval is zero (a
  yield, not a timer), and the runner's drain is pure ``sleep(0)``
  ticks — so the asyncio ready queue, which IS deterministic, is the
  only scheduler.

The event loop advances in macro-steps: fire every virtual event in the
next window (arrivals, completions, beats, scripted faults), then drain
the mesh at a frozen clock until quiescent, then jump the clock again.
Wall-clock reads are banned across this package by
``scripts/lint_hotpath.py`` — host time must never leak into a report.
"""

from __future__ import annotations

import asyncio
import contextlib
import random
from typing import Any, Iterator

from calfkit_tpu import leases, protocol
from calfkit_tpu.client import Client
from calfkit_tpu.client.caller import RetryPolicy
from calfkit_tpu.fleet import FleetRouter
from calfkit_tpu.fleet.failover import FailoverPolicy
from calfkit_tpu.fleet.policy import (
    LeastLoaded,
    PowerOfTwoChoices,
    PrefixAffinity,
    RandomChoice,
    RoutingPolicy,
)
from calfkit_tpu.mesh.memory import InMemoryMesh
from calfkit_tpu.sim.chaos import settle
from calfkit_tpu.sim.clock import VirtualClock, virtual_clock
from calfkit_tpu.sim.ids import deterministic_ids
from calfkit_tpu.sim.report import CheckResult, ScenarioReport, percentile
from calfkit_tpu.sim.scenario import Scenario, TenantSpec
from calfkit_tpu.sim.stubs import SimEngineModel
from calfkit_tpu.sim.topology import FleetTopology

__all__ = ["SimRunner", "run_scenario", "fresh_lease_store"]

# sleep(0) yields per drain round: enough for a full client→worker→stub
# round trip (~30 awaits) plus supervisor/retry churn riding on top
DRAIN_TICKS = 80
# minimum virtual jump per macro-step: events closer together than this
# fire as one batch (each still at its own timestamp) — bounds the
# number of drain rounds for dense arrival bursts
QUANTUM_S = 0.25
# bounded idle advances once the heap is dry but calls are outstanding
# (blackholed placements waiting out stale_after); then we fail loudly
MAX_IDLE_ADVANCES = 64
# prompts carry a 4-page session preamble so the affinity key (and the
# stub's prefix model) sees a stable head across a session's turns
SESSION_PREFIX_CHARS = 256


@contextlib.contextmanager
def fresh_lease_store() -> "Iterator[None]":
    """Run with an empty process-wide caller-lease store, restoring the
    previous contents after — repeat suite runs in one process (the
    determinism test) must not see each other's leases."""
    with leases._LOCK:
        saved = dict(leases._beats)
        saved_gen = leases._release_gen
        saved_countdown = leases._scan_countdown
        leases._beats.clear()
        leases._scan_countdown = 0
    try:
        yield
    finally:
        with leases._LOCK:
            leases._beats.clear()
            leases._beats.update(saved)
            leases._release_gen = saved_gen
            leases._scan_countdown = saved_countdown


def _resolve_policy(
    name: "str | RoutingPolicy", seed: int
) -> RoutingPolicy:
    """The scenario's policy with every random stream injected from the
    seed (the ``RetryPolicy`` convention) — a bare ``resolve_policy``
    would fall back to the global ``random`` module and break repeat-run
    determinism."""
    if not isinstance(name, str):
        return name
    if name in ("least-loaded",):
        return LeastLoaded()
    if name in ("p2c", "power-of-two"):
        return PowerOfTwoChoices(rng=random.Random(seed ^ 0x9C2).random)
    if name in ("prefix-affinity",):
        return PrefixAffinity(
            fallback=PowerOfTwoChoices(rng=random.Random(seed ^ 0x9C2).random)
        )
    if name in ("random",):
        return RandomChoice(rng=random.Random(seed ^ 0x9C2).random)
    raise ValueError(f"unknown scenario policy {name!r}")


class SimRunner:
    """Execute one scenario; see the module docstring.  ``policy`` (when
    given) OVERRIDES the scenario's routing policy — the perf gate's
    seeded-regression seam (``scripts/perf_gate.py --degrade``)."""

    def __init__(
        self,
        scenario: Scenario,
        *,
        policy: "RoutingPolicy | None" = None,
    ):
        self.scenario = scenario
        self._policy_override = policy

    # ------------------------------------------------------------ helpers
    def _pick_tenant(
        self, rng: random.Random, tenants: "tuple[TenantSpec, ...]"
    ) -> TenantSpec:
        weights = [t.weight for t in tenants]
        return rng.choices(tenants, weights=weights, k=1)[0]

    def _prompt(
        self, rng: random.Random, tenant: TenantSpec, index: int
    ) -> str:
        session = rng.randrange(max(1, tenant.sessions))
        head = f"[{tenant.name}#s{session:04d}] simulated agent session "
        head = (head + "context " * 32)[:SESSION_PREFIX_CHARS]
        return f"{head} turn {index}: do the next step"

    async def _drain(self, ticks: int = DRAIN_TICKS) -> None:
        for _ in range(ticks):
            await asyncio.sleep(0)

    # ---------------------------------------------------------------- run
    async def run(self) -> ScenarioReport:
        scenario = self.scenario
        with contextlib.ExitStack() as stack:
            stack.enter_context(deterministic_ids(scenario.seed))
            stack.enter_context(fresh_lease_store())
            clock = stack.enter_context(virtual_clock())
            return await self._run_inside(clock)
        raise AssertionError("unreachable")  # pragma: no cover

    async def _run_inside(self, clock: VirtualClock) -> ScenarioReport:
        scenario = self.scenario
        arrivals_rng = random.Random(scenario.seed ^ 0xA221)
        tenant_rng = random.Random(scenario.seed ^ 0x7E4A)
        lease_rng = random.Random(scenario.seed ^ 0x1EA5)

        mesh = InMemoryMesh()
        models = [
            SimEngineModel(clock, index=i, service=scenario.service)
            for i in range(scenario.replicas)
        ]
        shed_above = scenario.service.shed_above
        max_workers = max(
            8,
            2 * scenario.service.slots,
            (shed_above + 4) if shed_above is not None else 0,
        )
        topo = FleetTopology(
            mesh,
            models,
            # the REAL heartbeat tick loop must never fire: beats are
            # virtual-clock events (beat_all below), so the control
            # plane is part of the deterministic timeline
            heartbeat_interval=1e6,
            stale_multiplier=1.0,
            max_workers=max_workers,
        )
        policy = self._policy_override or _resolve_policy(
            scenario.policy, scenario.seed
        )
        start_at = clock.now
        kill_ledger: "list[dict[str, Any]]" = []
        depth_samples: "list[int]" = []
        beats_fired = 0

        async with topo:
            router = FleetRouter(
                mesh, policy, stale_after=scenario.stale_after_s
            )
            client = Client.connect(mesh, router=router)
            await router.start()
            await topo.beat_all()
            await settle(
                lambda: len(router.registry.eligible(topo.name))
                == scenario.replicas,
                interval=0,
                ticks=20_000,
                message="fleet never became fully eligible",
            )
            gateway = client.agent(topo.name)

            retry = (
                RetryPolicy(
                    attempts=scenario.retry_attempts,
                    base_delay=0.0,
                    jitter=0.0,
                    rng=random.Random(scenario.seed ^ 0xE77).random,
                )
                if scenario.retry_attempts > 1
                else None
            )
            failover = (
                FailoverPolicy(
                    probe_interval=0.0,
                    max_failovers=scenario.max_failovers,
                )
                if scenario.failover
                else None
            )

            futures: "list[asyncio.Task[Any]]" = []
            faults: "dict[str, int]" = {}
            completed = [0]

            def launch(index: int) -> None:
                # tenant pick then session pick: the SAME rng consumption
                # order as before the QoS split — pre-QoS timelines are
                # byte-identical
                tenant = self._pick_tenant(tenant_rng, scenario.tenants)
                prompt = self._prompt(tenant_rng, tenant, index)

                async def one() -> None:
                    try:
                        await gateway.execute(
                            prompt,
                            timeout=scenario.timeout_s,
                            retry=retry,
                            failover=failover,
                            priority=tenant.priority,
                        )
                        completed[0] += 1
                    except Exception as exc:  # noqa: BLE001 - harvested
                        kind = type(exc).__name__
                        faults[kind] = faults.get(kind, 0) + 1

                futures.append(asyncio.ensure_future(one()))

            # ---- the timeline: arrivals, beats, scripted faults, leases
            offered = 0
            for t in scenario.arrival_times(arrivals_rng):
                index = offered
                offered += 1
                clock.schedule(start_at + t, lambda i=index: launch(i))

            horizon = start_at + scenario.expected_arrival_horizon_s()

            def beat() -> None:
                nonlocal beats_fired
                beats_fired += 1
                for model in models:
                    depth_samples.append(model.active)
                asyncio.ensure_future(topo.beat_all())
                if clock.now + scenario.heartbeat_every_s <= horizon:
                    clock.schedule(
                        clock.now + scenario.heartbeat_every_s, beat
                    )

            clock.schedule(
                start_at + scenario.heartbeat_every_s, beat
            )
            # keep time flowing to the horizon even with no arrivals
            # pending (stale-out windows after a kill, lease tails)
            clock.schedule(horizon, lambda: None)

            def fire_event(action: str, replica: int) -> None:
                if action == "kill":
                    kill_ledger.append(
                        {
                            "replica": replica,
                            "at_s": clock.now - start_at,
                            "delivered_at_kill": topo.calls_delivered(
                                replica
                            ),
                        }
                    )
                    topo.kill(replica)
                elif action == "resume":
                    for entry in kill_ledger:
                        if entry["replica"] == replica and (
                            "delivered_at_heal" not in entry
                        ):
                            entry["delivered_at_heal"] = (
                                topo.calls_delivered(replica)
                            )
                    asyncio.ensure_future(topo.resume(replica))
                elif action == "drain":
                    topo.drain(replica)
                elif action == "wedge_heartbeat":
                    topo.wedge_heartbeat(replica)

            for event in scenario.events:
                clock.schedule(
                    start_at + event.at_s,
                    lambda a=event.action, r=event.replica: fire_event(a, r),
                )

            leases_minted = [0]
            leases_released = [0]
            if scenario.leases is not None:
                self._schedule_leases(
                    clock, mesh, lease_rng, start_at,
                    leases_minted, leases_released,
                )

            # --------------------------------- the discrete-event loop
            idle_advances = 0
            while True:
                await self._drain()
                next_at = clock.next_event_at
                if next_at is not None:
                    idle_advances = 0  # real events = progress
                    clock.advance_to(max(next_at, clock.now + QUANTUM_S))
                    continue
                if all(f.done() for f in futures):
                    break
                # heap dry, calls outstanding: blackholed placements
                # waiting out stale_after — advance deterministically.
                # The budget bounds CONSECUTIVE dry advances only: a
                # long scenario legitimately goes briefly dry many times
                # (a completion frees a dispatcher permit, the next
                # delivery schedules its event one drain later).
                idle_advances += 1
                if idle_advances > MAX_IDLE_ADVANCES:
                    raise RuntimeError(
                        f"scenario {scenario.name!r} did not settle: "
                        f"{sum(not f.done() for f in futures)} call(s) "
                        "still outstanding after the idle-advance budget"
                    )
                clock.advance(max(scenario.stale_after_s / 2.0, 1.0))
            await self._drain()

            # one closing beat so the registry snapshot reflects final
            # counters (failover arrivals, prefix hits) for the harvest
            await topo.beat_all()
            await self._drain()
            report = self._harvest(
                clock=clock,
                start_at=start_at,
                topo=topo,
                models=models,
                router=router,
                mesh=mesh,
                offered=offered,
                completed=completed[0],
                faults=faults,
                depth_samples=depth_samples,
                beats_fired=beats_fired,
                kill_ledger=kill_ledger,
                leases_minted=leases_minted[0],
                leases_released=leases_released[0],
                run_records=client.run_ledger.finished_records(),
            )
            await client.close()
            await router.stop()
        await mesh.stop()
        return report

    # ------------------------------------------------------------- leases
    def _schedule_leases(
        self,
        clock: VirtualClock,
        mesh: InMemoryMesh,
        rng: random.Random,
        start_at: float,
        minted: "list[int]",
        released: "list[int]",
    ) -> None:
        """Synthetic caller-liveness churn: beats and tombstones on the
        real compacted table (every worker folds them — the production
        path), scheduled as virtual events."""
        churn = self.scenario.leases
        assert churn is not None
        writer = mesh.table_writer(protocol.CALLER_LIVENESS_TOPIC)
        duration = max(self.scenario.duration_s, 1.0)

        def put_beat(lease_id: str, ttl: float) -> None:
            asyncio.ensure_future(
                writer.put(lease_id, leases.beat_payload(lease_id, ttl))
            )

        def put_release(lease_id: str) -> None:
            released[0] += 1
            asyncio.ensure_future(writer.tombstone(lease_id))

        for k in range(churn.callers):
            lease_id = f"simlease-{k:06d}"
            born = rng.uniform(0.0, duration)
            life = rng.uniform(churn.min_life_s, churn.max_life_s)
            clean = rng.random() < churn.clean_release_ratio
            minted[0] += 1
            t = 0.0
            while t <= life:
                clock.schedule(
                    start_at + born + t,
                    lambda lid=lease_id, ttl=churn.ttl_s: put_beat(lid, ttl),
                )
                t += churn.beat_every_s
            if clean:
                clock.schedule(
                    start_at + born + life,
                    lambda lid=lease_id: put_release(lid),
                )

    # ------------------------------------------------------------ harvest
    def _harvest(
        self,
        *,
        clock: VirtualClock,
        start_at: float,
        topo: FleetTopology,
        models: "list[SimEngineModel]",
        router: FleetRouter,
        mesh: InMemoryMesh,
        offered: int,
        completed: int,
        faults: "dict[str, int]",
        depth_samples: "list[int]",
        beats_fired: int,
        kill_ledger: "list[dict[str, Any]]",
        leases_minted: int,
        leases_released: int,
        run_records: "list[Any] | None" = None,
    ) -> ScenarioReport:
        scenario = self.scenario
        served = [m.replies for m in models]
        served_total = sum(served)
        mean_served = served_total / max(1, len(served))
        sheds = sum(m.sheds for m in models)
        prefix_lookups = sum(m.prefix_lookups for m in models)
        prefix_hits = sum(m.prefix_hits for m in models)
        decode_tokens = sum(m.decode_tokens for m in models)
        dispatches = sum(m.decode_dispatches for m in models)
        replicas = router.registry.replicas(agent=topo.name)
        failover_arrivals = sum(r.stats.failover_requests for r in replicas)

        delivered_while_dead = 0
        delivered_after_heal = 0
        healed = False
        for entry in kill_ledger:
            final = topo.calls_delivered(entry["replica"])
            end = entry.get("delivered_at_heal", final)
            delivered_while_dead += end - entry["delivered_at_kill"]
            if "delivered_at_heal" in entry:
                healed = True
                delivered_after_heal += final - entry["delivered_at_heal"]

        metrics: "dict[str, Any]" = {
            "requests": {
                "offered": offered,
                "completed": completed,
                "failed": offered - completed,
                "completion_ratio": (
                    round(completed / offered, 6) if offered else 1.0
                ),
                "faults": dict(sorted(faults.items())),
            },
            "shed": {
                "sheds": sheds,
            },
            "routing": {
                "served_total": served_total,
                "fleet": len(models),
                "delivered_while_dead": delivered_while_dead,
                "failover_arrivals": failover_arrivals,
            },
        }
        if healed:
            metrics["routing"]["delivered_after_heal"] = delivered_after_heal
        if run_records is not None:
            # run-level metrics off the client's run ledger (ISSUE 17),
            # computed through the SAME pure rollup fold the SLO adverts
            # use — the sim gates what callers experienced per RUN
            # (virtual seconds end-to-end across every failover/retry),
            # not per attempt.  Window = the whole scenario.
            from calfkit_tpu.observability.runledger import rollup_window

            entries = [
                {
                    "started_at": r.started_at,
                    "finished_at": r.finished_at,
                    "outcome": r.outcome,
                    "error_type": r.error_type,
                    "attempts": len(r.attempts),
                    "sheds": r.sheds,
                    "failovers": r.failovers,
                    "priority": r.priority,
                }
                for r in run_records
            ]
            rollup = rollup_window(
                entries,
                agent=topo.name,
                window_end=clock.now,
                window_s=max(clock.now - start_at, 1.0) + 1.0,
            )
            metrics["runs"] = {
                "finished": rollup.runs,
                "completed": rollup.completed,
                "completion_ratio": round(rollup.completion_ratio, 6),
                "e2e_p50_s": round(rollup.e2e_p50_s, 6),
                "e2e_p95_s": round(rollup.e2e_p95_s, 6),
                "e2e_p99_s": round(rollup.e2e_p99_s, 6),
                "attempts": rollup.attempts,
                "attempt_amplification": round(
                    rollup.attempt_amplification, 6
                ),
                "shed_rate": round(rollup.shed_rate, 6),
                "failover_rate": round(rollup.failover_rate, 6),
                "orphan_rate": round(rollup.orphan_rate, 6),
                "error_budget_burn": round(rollup.error_budget_burn, 6),
            }
            if any(t.priority == "batch" for t in scenario.tenants):
                # multi-tenant QoS metrics (ISSUE 20), emitted ONLY when
                # the scenario actually runs mixed classes — single-class
                # scenario reports stay byte-identical to their pre-QoS
                # baselines.  Per-run numbers come off the same rollup
                # fold as metrics["runs"]; shed counts come off the stub
                # engines, split by the VICTIM's class — the fairness
                # ratio (batch share of all sheds) is the gate input.
                interactive_sheds = sum(m.interactive_sheds for m in models)
                batch_sheds = sum(m.batch_sheds for m in models)
                total_sheds = interactive_sheds + batch_sheds
                metrics["qos"] = {
                    "interactive": {
                        "runs": rollup.interactive_runs,
                        "completed": rollup.interactive_completed,
                        "completion_ratio": round(
                            rollup.interactive_completed
                            / rollup.interactive_runs,
                            6,
                        ) if rollup.interactive_runs else 1.0,
                        "e2e_p95_s": round(rollup.interactive_p95_s, 6),
                        "sheds": interactive_sheds,
                        "replies": sum(
                            m.interactive_replies for m in models
                        ),
                    },
                    "batch": {
                        "runs": rollup.batch_runs,
                        "completed": rollup.batch_completed,
                        "completion_ratio": round(
                            rollup.batch_completed / rollup.batch_runs, 6
                        ) if rollup.batch_runs else 1.0,
                        "e2e_p95_s": round(rollup.batch_p95_s, 6),
                        "sheds": batch_sheds,
                        "replies": sum(m.batch_replies for m in models),
                    },
                    "shed_fairness_ratio": round(
                        batch_sheds / total_sheds, 6
                    ) if total_sheds else 1.0,
                }
        metrics.update({
            "prefix": {
                "lookups": prefix_lookups,
                "hits": prefix_hits,
                "hit_rate": (
                    round(prefix_hits / prefix_lookups, 6)
                    if prefix_lookups
                    else 0.0
                ),
                "reused_tokens": sum(
                    m.prefix_reused_tokens for m in models
                ),
            },
            "tokens": {
                "decode_tokens": decode_tokens,
                "dispatches": dispatches,
                "tokens_per_dispatch": (
                    round(decode_tokens / dispatches, 6)
                    if dispatches
                    else 0.0
                ),
            },
            "time": {
                "virtual_duration_s": round(scenario.duration_s, 6),
                # last completion, not the final clock position — the
                # horizon no-op event must not inflate the makespan
                "makespan_s": round(
                    max(
                        (m.last_done_at for m in models if m.last_done_at),
                        default=start_at,
                    )
                    - start_at,
                    6,
                ),
                "events_fired": clock.fired,
                "heartbeats": beats_fired,
            },
        })
        if scenario.service.pool_pages > 0:
            # capacity observatory metrics (ISSUE 19), summed off the
            # same PageLedger/CapacitySampler a paged engine drives.
            # residual_pages_in_use is the leak oracle at fleet scale:
            # a drained fleet must attribute every page to no owner.
            ledgers = [m.ledger for m in models if m.ledger is not None]
            metrics["capacity"] = {
                "pages_total": sum(led.pages_total for led in ledgers),
                "evicted_pages": sum(led.evicted_pages for led in ledgers),
                "alloc_stalls": sum(led.alloc_stalls for led in ledgers),
                "prefix_resident_pages": sum(
                    led.prefix_resident_pages for led in ledgers
                ),
                "headroom_pages": sum(
                    led.headroom_pages for led in ledgers
                ),
                "residual_pages_in_use": sum(
                    led.pages_in_use for led in ledgers
                ),
                "peak_pages_in_use": max(
                    (m.peak_pages_in_use for m in models), default=0
                ),
                "samples": sum(
                    m.sampler.counts()["appended"]
                    for m in models
                    if m.sampler is not None
                ),
            }
        if scenario.per_replica_report:
            metrics["routing"].update(
                {
                    "per_replica": served,
                    "skew_max_over_mean": (
                        round(max(served) / mean_served, 6)
                        if served_total
                        else 0.0
                    ),
                    "skew_p95_over_mean": (
                        round(
                            percentile([float(s) for s in served], 0.95)
                            / mean_served,
                            6,
                        )
                        if served_total
                        else 0.0
                    ),
                }
            )
            metrics["depth"] = {
                "samples": len(depth_samples),
                "p50": percentile([float(d) for d in depth_samples], 0.50),
                "p95": percentile([float(d) for d in depth_samples], 0.95),
                "max": float(max(depth_samples)) if depth_samples else 0.0,
            }
            ewmas = [
                m.dispatch_ewma_ms for m in models if m.dispatch_ewma_ms > 0
            ]
            metrics["latency"] = {
                "dispatch_ewma_ms_mean": (
                    round(sum(ewmas) / len(ewmas), 6) if ewmas else 0.0
                ),
                "busy_virtual_s_total": round(
                    sum(m.busy_virtual_s for m in models), 6
                ),
            }
        if scenario.leases is not None:
            store = leases.active_leases()
            now = clock.now
            lapsed = sum(
                1
                for beat_at, ttl in store.values()
                if beat_at == float("-inf") or now - beat_at > ttl
            )
            table = mesh.table_reader(protocol.CALLER_LIVENESS_TOPIC)
            metrics["leases"] = {
                "minted": leases_minted,
                "released": leases_released,
                "store_size": len(store),
                "lapsed": lapsed,
                "table_records": len(table.items()),
            }

        checks = [
            CheckResult(
                name=check.name,
                metric=check.metric,
                op=check.op,
                bound=check.bound,
                value=(value := scenario_metric(metrics, check.metric)),
                passed=check.evaluate(value),
            )
            for check in scenario.checks
        ]
        return ScenarioReport(
            name=scenario.name,
            seed=scenario.seed,
            replicas=scenario.replicas,
            metrics=metrics,
            checks=checks,
            gated=scenario.gated,
        )


def scenario_metric(
    metrics: "dict[str, Any]", path: str
) -> "float | None":
    from calfkit_tpu.sim.report import metric_at

    return metric_at(metrics, path)


async def run_scenario(
    scenario: Scenario, *, policy: "RoutingPolicy | None" = None
) -> ScenarioReport:
    """One-shot convenience: build a runner and execute the scenario."""
    return await SimRunner(scenario, policy=policy).run()
