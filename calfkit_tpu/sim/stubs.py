"""Engine stand-ins for fleet scenarios (promoted from ``tests/_chaos.py``
plus the simulator's virtual-latency model — ISSUE 11).

Everything above the model-client seam is REAL in a simulated fleet: the
mesh, the workers, the node kernels, the control-plane heartbeats, the
router, the client.  Only the inference engine is replaced, because a
real engine's decode thread makes completion ordering a property of the
host scheduler — and the simulator promises byte-identical reports.

- :class:`ServingStubModel` — instant replies; LOOKS engine-backed to
  the fleet machinery (``stats_snapshot`` makes its agent advertise on
  ``mesh.engine_stats`` and subscribe its replica-addressed topic).
- :class:`StreamingStubModel` — word-sized deltas with a deterministic
  mid-stream pause seam (the kill-mid-stream scenarios).
- :class:`BijectiveTokenizer` — token id ↔ character bijection for
  byte-exact resume tests.
- :class:`SimEngineModel` — the simulator's fixed-latency device stub:
  requests occupy one of ``slots`` virtual servers for a service time
  computed purely from the scenario's :class:`~calfkit_tpu.sim.scenario.
  ServiceSpec` and complete on virtual-clock events — hours of fleet
  time cost no host time, and identical seeds replay identical
  timelines.  It sheds with the real typed ``EngineOverloadedError``,
  models a page-aligned prefix cache (hits skip the prefill term), and
  advertises the same counters a real engine heartbeats
  (depth, EWMA dispatch latency, prefix hits, tokens/dispatch).  With
  ``ServiceSpec.pool_pages`` set it also models a bounded KV page pool,
  driving the REAL :class:`~calfkit_tpu.observability.capacity.
  PageLedger` / ``CapacitySampler`` through the engine's ownership
  transitions — so capacity attribution, occupancy timelines, and the
  headroom advert are provable at fleet scale on virtual time
  (ISSUE 19).
"""

from __future__ import annotations

import asyncio
from typing import Any

from calfkit_tpu import qos
from calfkit_tpu.exceptions import EngineOverloadedError
from calfkit_tpu.fleet.selection import page_aligned_prefix
from calfkit_tpu.observability import capacity
from calfkit_tpu.sim.clock import VirtualClock
from calfkit_tpu.sim.scenario import ServiceSpec

__all__ = [
    "ServingStubModel",
    "StreamingStubModel",
    "BijectiveTokenizer",
    "SimEngineModel",
]


def _estimate_tokens(messages: Any) -> int:
    return sum(len(str(m)) // 4 for m in messages)


# the sim's virtual KV page geometry (ISSUE 19): tokens per page for the
# stub's deterministic page math — fixed, like the debug preset's
# page_size, so scenario page counts are a pure function of the prompts
SIM_PAGE_TOKENS = 16


def _pages_for(tokens: int) -> int:
    return max(1, -(-int(tokens) // SIM_PAGE_TOKENS))


def _prompt_text(messages: Any) -> str:
    """The latest user-authored text in the turn — what the router's
    affinity key and the stub's prefix model both derive from."""
    for message in reversed(list(messages)):
        for part in reversed(getattr(message, "parts", []) or []):
            content = getattr(part, "content", None)
            if isinstance(content, str) and content:
                return content
    return ""


class ServingStubModel:
    """A scripted model that LOOKS engine-backed to the fleet machinery:
    ``stats_snapshot`` makes its agent advertise on ``mesh.engine_stats``
    (and subscribe its replica-addressed topic) without paying for a real
    inference engine.  ``load`` feeds the queue-depth signal policies
    rank on; ``replies`` counts turns served by THIS replica."""

    def __init__(self, *, text: str = "ok", load: int = 0):
        self.text = text
        self.load = load
        self.replies = 0

    @property
    def model_name(self) -> str:
        return "serving-stub"

    def stats_snapshot(self, *, window: bool = False) -> dict:
        return {
            "model_name": self.model_name,
            "active_requests": self.load,
            "pending_requests": 0,
        }

    async def request(
        self, messages: Any, settings: Any = None, params: Any = None
    ) -> Any:
        from calfkit_tpu.models.messages import (
            ModelResponse,
            TextOutput,
            Usage,
        )

        self.replies += 1
        return ModelResponse(
            parts=[TextOutput(text=self.text)],
            usage=Usage(
                input_tokens=_estimate_tokens(messages), output_tokens=1
            ),
            model_name=self.model_name,
        )


class BijectiveTokenizer:
    """Token id ↔ character bijection for byte-exact resume tests
    (ISSUE 10): generated id ``i`` decodes to ``chr(0x100 + i)`` and
    encodes back to exactly ``i`` — so re-encoding a delivered prefix
    reproduces the original token ids and greedy decode-from-offset
    parity is literal byte equality (ByteTokenizer's UTF-8 replacement
    chars break the round trip for arbitrary model outputs).  Prompt
    characters below U+0100 encode to their ordinal, within the debug
    preset's 512-token vocab."""

    pad_id = 0
    bos_id = 1
    eos_id = 2

    def encode(self, text: str) -> "list[int]":
        return [
            ord(c) - 0x100 if ord(c) >= 0x100 else ord(c) for c in text
        ]

    def decode(self, ids: "list[int]") -> str:
        return "".join(chr(0x100 + i) for i in ids if i >= 0)


class StreamingStubModel(ServingStubModel):
    """A ServingStubModel whose ``request_stream`` yields word-sized
    deltas and PAUSES after ``pause_after`` of them until ``release`` is
    set — the deterministic mid-stream seam: a scenario observes the
    first delivered tokens, kills the replica, and knows exactly how
    much text the caller saw.  The stream keeps yielding after the kill
    (a dead replica's compute keeps burning); the transport seam drops
    the output."""

    def __init__(
        self,
        *,
        text: str = "alpha beta gamma delta",
        pause_after: int = 1,
        load: int = 0,
    ):
        super().__init__(text=text, load=load)
        self.pause_after = pause_after
        self.release = asyncio.Event()
        self.streamed: list[str] = []

    async def request_stream(
        self, messages: Any, settings: Any = None, params: Any = None
    ) -> Any:
        from calfkit_tpu.engine.model_client import ResponseDone, TextDelta

        words = self.text.split(" ")
        deltas = [
            w + (" " if i < len(words) - 1 else "")
            for i, w in enumerate(words)
        ]
        for i, delta in enumerate(deltas):
            if i == self.pause_after:
                await self.release.wait()
            self.streamed.append(delta)
            yield TextDelta(delta)
            await asyncio.sleep(0)
        response = await super().request(messages, settings, params)
        yield ResponseDone(response)


class SimEngineModel:
    """The simulator's deterministic fixed-latency engine (see module
    docstring).  All time below is VIRTUAL: a request reserves the
    earliest-free of ``service.slots`` virtual servers, computes its
    service span from the :class:`ServiceSpec`, and awaits a completion
    event the clock fires when an advance crosses it.  The host never
    sleeps; the scenario's discrete-event loop is the only scheduler."""

    def __init__(
        self,
        clock: VirtualClock,
        *,
        index: int = 0,
        service: "ServiceSpec | None" = None,
        prefix_page_chars: int = 64,
    ):
        self.clock = clock
        self.index = index
        self.service = service or ServiceSpec()
        self.prefix_page_chars = prefix_page_chars
        self._mult = self.service.multiplier(index)
        # per-virtual-server busy-until horizon (absolute virtual time)
        self._busy: "list[float]" = [0.0] * max(1, self.service.slots)
        # admitted-unfinished requests: run_id -> {"start", "done",
        # "slot", "service_s", "priority", "event", "shed"} — the
        # pending-vs-active split the heartbeat advertises, and the
        # priority-shed victim pool (ISSUE 20)
        self._inflight: "dict[int, dict[str, Any]]" = {}
        self._next_run = 0
        # prefix model: page-aligned prefixes this replica has served
        self._prefix_seen: "set[bytes]" = set()
        # counters (everything the heartbeat / report harvests)
        self.replies = 0
        self.sheds = 0
        # per-class splits (ISSUE 20): sheds by the VICTIM's class,
        # completions by the finisher's class — the fairness-gate inputs
        self.interactive_sheds = 0
        self.batch_sheds = 0
        self.interactive_replies = 0
        self.batch_replies = 0
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_reused_tokens = 0
        self.decode_tokens = 0
        self.decode_dispatches = 0
        self.busy_virtual_s = 0.0
        self.dispatch_ewma_ms = 0.0
        # virtual timestamp of the last completion this replica served
        # (the report's makespan reads the fleet max — the horizon
        # no-op event must not inflate it)
        self.last_done_at = 0.0
        # page-pool model (ISSUE 19): when the scenario gives replicas a
        # virtual KV pool, the stub drives the REAL PageLedger and
        # CapacitySampler — the same attribution/occupancy code a paged
        # engine runs — through the same transitions (alloc at admission,
        # transfer at first prefix registration, acquire/release around
        # reuse, evict under pressure).  wall_anchor=False keeps sampler
        # timestamps virtual; every append passes t=clock.now.
        if self.service.pool_pages > 0:
            self.ledger: "capacity.PageLedger | None" = (
                capacity.PageLedger(self.service.pool_pages)
            )
            self.sampler: "capacity.CapacitySampler | None" = (
                capacity.CapacitySampler(
                    self.service.capacity_samples,
                    label=f"sim-r{index}",
                    ledger=self.ledger,
                    wall_anchor=False,
                )
            )
        else:
            self.ledger = None
            self.sampler = None
        self._free_pool = max(0, self.service.pool_pages)
        self._next_page = 0
        # chain key -> resident page ids; insertion order IS the LRU
        # order (zero-ref chains re-append on release), so eviction pops
        # from the front exactly like PrefixCache's LRU
        self._chain_pages: "dict[bytes, tuple[int, ...]]" = {}
        # chain key -> in-flight reference count (a referenced chain is
        # never evictable, mirroring the zero-ref eviction law)
        self._chain_held: "dict[bytes, int]" = {}
        self.peak_pages_in_use = 0

    @property
    def model_name(self) -> str:
        return "sim-engine"

    # ------------------------------------------------------------ signals
    @property
    def active(self) -> int:
        """Admitted-but-unfinished depth (the shed law's input)."""
        return len(self._inflight)

    def _in_service(self) -> int:
        now = self.clock.now
        return sum(
            1 for r in self._inflight.values() if r["start"] <= now
        )

    def stats_snapshot(self, *, window: bool = False) -> dict:
        in_service = self._in_service()
        now = self.clock.now
        queued_batch = sum(
            1
            for r in self._inflight.values()
            if r["start"] > now and r["priority"] == "batch"
        )
        snapshot = {
            "model_name": self.model_name,
            "platform": "sim",
            "active_requests": in_service,
            "pending_requests": len(self._inflight) - in_service,
            "max_batch_size": self.service.slots,
            "decode_tokens": self.decode_tokens,
            "decode_dispatches": self.decode_dispatches,
            "shed_requests": self.sheds,
            # per-class advert keys (ISSUE 20): the same split a real
            # engine heartbeats, so the router's interactive-depth
            # tiebreak works against sim adverts too
            "interactive_shed": self.interactive_sheds,
            "batch_shed": self.batch_sheds,
            "interactive_pending": (
                len(self._inflight) - in_service - queued_batch
            ),
            "batch_pending": queued_batch,
            "dispatch_ewma_ms": round(self.dispatch_ewma_ms, 6),
            "prefix_hits": self.prefix_hits,
            "prefix_reused_tokens": self.prefix_reused_tokens,
            "prefix_cached_pages": len(self._prefix_seen),
            "tokens_per_dispatch": (
                round(self.decode_tokens / self.decode_dispatches, 6)
                if self.decode_dispatches
                else 0.0
            ),
        }
        if self.ledger is not None:
            # the capacity scalars a paged engine heartbeats (ISSUE 19),
            # read off the same ledger — sim adverts carry real headroom
            snapshot["pages_total"] = self.ledger.pages_total
            snapshot["pages_in_use"] = self.ledger.pages_in_use
            snapshot["prefix_resident_pages"] = (
                self.ledger.prefix_resident_pages
            )
            snapshot["evictions_window"] = self.ledger.evicted_pages
            snapshot["alloc_stalls"] = self.ledger.alloc_stalls
        return snapshot

    # -------------------------------------------------------------- pages
    def _reserve_pages(self, need: int) -> int:
        """Deterministic pool pressure: take ``need`` pages from the free
        pool, evicting zero-ref LRU chains through the REAL ledger hook
        when short.  A still-short reservation counts a stall and clamps
        — page accounting is telemetry; virtual service proceeds
        regardless, exactly the never-fault-serving contract."""
        assert self.ledger is not None
        if need > self._free_pool:
            for chain in list(self._chain_pages):
                if need <= self._free_pool:
                    break
                if self._chain_held.get(chain):
                    continue  # referenced — not evictable
                pages = self._chain_pages.pop(chain)
                self._chain_held.pop(chain, None)
                # an evicted chain must re-miss (and re-prefill) later:
                # churn is allowed to cost hit rate, and the scenario
                # measures exactly that
                self._prefix_seen.discard(chain)
                for page in pages:
                    self.ledger.evicted(page)
                self._free_pool += len(pages)
            if need > self._free_pool:
                self.ledger.note_stall()
                need = self._free_pool
        self._free_pool -= need
        return need

    # ---------------------------------------------------------- qos shed
    def _preempt_victim(self) -> "int | None":
        """The queued batch request whose eviction reclaims slot horizon
        EXACTLY: it must not have started (``start > now`` — active work
        is never preempted) and must be the tail of its slot
        (``done == busy[slot]``) so subtracting its service time leaves
        no stale downstream reservation.  Among candidates pick the one
        finishing latest (most horizon reclaimed); ties break to the
        earliest-admitted via dict insertion order — deterministic."""
        now = self.clock.now
        best: "int | None" = None
        best_done = -1.0
        for run_id, record in self._inflight.items():
            if record["priority"] != "batch":
                continue
            if record["start"] <= now:
                continue
            if record["done"] != self._busy[record["slot"]]:
                continue
            if record["done"] > best_done:
                best = run_id
                best_done = record["done"]
        return best

    def _shed_inflight(self, run_id: int) -> None:
        """Evict a queued batch victim: reclaim its slot horizon, count
        the shed against the VICTIM's class, and wake its coroutine —
        which observes the flag, undoes its page accounting, and raises
        the retriable shed fault (the caller's RetryPolicy re-drives)."""
        record = self._inflight.pop(run_id)
        record["shed"] = True
        self._busy[record["slot"]] -= record["service_s"]
        self.sheds += 1
        self.batch_sheds += 1
        record["event"].set()

    # ------------------------------------------------------------ serving
    async def request(
        self, messages: Any, settings: Any = None, params: Any = None
    ) -> Any:
        from calfkit_tpu.models.messages import (
            ModelResponse,
            TextOutput,
            Usage,
        )

        spec = self.service
        # priority class (ISSUE 20): the node kernel set the contextvar
        # from x-mesh-priority before calling the model — the sim runs
        # the REAL delivery path, so the one degradation law applies
        priority = qos.resolve_priority()
        if (
            spec.shed_above is not None
            and len(self._inflight) >= spec.shed_above
        ):
            victim_id = (
                self._preempt_victim() if priority != "batch" else None
            )
            if victim_id is None:
                # shed the ARRIVAL: batch always; interactive only when
                # no queued batch victim exists — the shed-order law
                self.sheds += 1
                if priority == "batch":
                    self.batch_sheds += 1
                else:
                    self.interactive_sheds += 1
                raise EngineOverloadedError(
                    "sim engine overloaded",
                    lane="sim",
                    pending=len(self._inflight),
                    limit=spec.shed_above,
                )
            self._shed_inflight(victim_id)

        prompt = _prompt_text(messages)
        input_tokens = max(1, len(prompt) // 4)
        prefix_hit = False
        key = page_aligned_prefix(prompt, self.prefix_page_chars)
        if key is not None:
            self.prefix_lookups += 1
            if key in self._prefix_seen:
                prefix_hit = True
                self.prefix_hits += 1
                self.prefix_reused_tokens += len(key) // 4
            else:
                self._prefix_seen.add(key)

        prefill_s = (
            0.0 if prefix_hit else spec.prefill_per_token_s * input_tokens
        )
        service_s = (
            spec.base_s + prefill_s + spec.per_token_s * spec.new_tokens
        ) * self._mult
        now = self.clock.now
        slot = min(range(len(self._busy)), key=lambda i: (self._busy[i], i))
        start_at = max(now, self._busy[slot])
        done_at = start_at + service_s
        self._busy[slot] = done_at
        run_id = self._next_run
        self._next_run += 1
        done = asyncio.Event()
        self._inflight[run_id] = {
            "start": start_at,
            "done": done_at,
            "slot": slot,
            "service_s": service_s,
            "priority": priority,
            "event": done,
            "shed": False,
        }
        record = self._inflight[run_id]

        shared: "tuple[int, ...]" = ()
        granted = 0
        if self.ledger is not None:
            if prefix_hit and key is not None and key in self._chain_pages:
                # reuse granted: reference the chain's resident pages
                # (registration may still be in flight on a racing first
                # request — then there is nothing to reference yet)
                shared = self._chain_pages[key]
                self.ledger.acquire(list(shared))
                self._chain_held[key] = self._chain_held.get(key, 0) + 1
            granted = self._reserve_pages(
                _pages_for(
                    spec.new_tokens + (0 if prefix_hit else input_tokens)
                )
            )
            self.ledger.alloc(
                run_id,
                granted,
                f"sim-r{self.index}-{run_id}",
                # the REAL run-identity seam: the node kernel set this
                # from the x-mesh-run header before calling the model
                capacity.current_run.get(),
                "decode",
            )
            self.peak_pages_in_use = max(
                self.peak_pages_in_use, self.ledger.pages_in_use
            )

        self.clock.schedule(done_at, done.set)
        await done.wait()

        if record["shed"]:
            # victim path: a later interactive arrival preempted this
            # queued batch request (``_shed_inflight`` already removed
            # it, reclaimed the slot horizon, and counted the shed).
            # Undo the page accounting this request never consummated,
            # then surface the REAL retriable shed fault so the caller's
            # RetryPolicy re-drives the work.
            if self.ledger is not None:
                if shared:
                    self.ledger.release(list(shared))
                    held = self._chain_held.get(key, 1) - 1
                    if held <= 0:
                        self._chain_held.pop(key, None)
                        if key in self._chain_pages:
                            self._chain_pages[key] = self._chain_pages.pop(
                                key
                            )
                    else:
                        self._chain_held[key] = held
                self.ledger.free(run_id)
                self._free_pool += granted
            if key is not None and not prefix_hit:
                # this request introduced the prefix but never prefilled
                # it to completion — it must re-miss (and re-prefill)
                self._prefix_seen.discard(key)
            raise EngineOverloadedError(
                "sim engine preempted batch request",
                lane="sim",
                pending=len(self._inflight),
                limit=spec.shed_above,
            )

        self._inflight.pop(run_id, None)
        self.replies += 1
        if priority == "batch":
            self.batch_replies += 1
        else:
            self.interactive_replies += 1
        self.last_done_at = max(self.last_done_at, done_at)
        dispatches = max(
            1,
            -(-spec.new_tokens // max(1, spec.steps_per_dispatch)),
        )
        self.decode_tokens += spec.new_tokens
        self.decode_dispatches += dispatches
        self.busy_virtual_s += service_s
        per_dispatch_ms = service_s * 1000.0 / dispatches
        self.dispatch_ewma_ms = (
            per_dispatch_ms
            if self.dispatch_ewma_ms == 0.0
            else 0.8 * self.dispatch_ewma_ms + 0.2 * per_dispatch_ms
        )
        if self.ledger is not None:
            # retirement: drop the shared reference, register the chain
            # off the first finisher's private pages (transfer at
            # refcount 1, then this request's own release — leaving the
            # chain zero-ref resident, evictable), free the rest
            if shared:
                self.ledger.release(list(shared))
                held = self._chain_held.get(key, 1) - 1
                if held <= 0:
                    self._chain_held.pop(key, None)
                    if key in self._chain_pages:
                        # zero-ref again: re-append = move to LRU tail
                        self._chain_pages[key] = self._chain_pages.pop(key)
                else:
                    self._chain_held[key] = held
            elif key is not None and key not in self._chain_pages and granted:
                moved = min(_pages_for(len(key) // 4), granted)
                pages = tuple(
                    range(self._next_page, self._next_page + moved)
                )
                self._next_page += moved
                self.ledger.transfer(run_id, list(pages), [key] * moved)
                self.ledger.release(list(pages))
                self._chain_pages[key] = pages
                granted -= moved
            self.ledger.free(run_id)
            self._free_pool += granted
            if self.sampler is not None:
                in_service = self._in_service()
                self.sampler.append(
                    self.ledger.pages_in_use,
                    self._free_pool,
                    self.ledger.prefix_resident_pages,
                    in_service,
                    len(self._inflight) - in_service,
                    round(spec.new_tokens / dispatches, 6),
                    0.0,  # no analytic HBM model for the virtual device
                    t=self.clock.now,
                )
        return ModelResponse(
            parts=[TextOutput(text=f"sim:r{self.index}:{self.replies}")],
            usage=Usage(
                input_tokens=input_tokens,
                output_tokens=spec.new_tokens,
            ),
            model_name=self.model_name,
        )
