"""The fleet-scenario DSL (ISSUE 11).

A :class:`Scenario` is a declarative description of one simulated fleet
run: how many replicas, what the arrival process looks like over virtual
time, who the tenants are, how the (stubbed) engines behave, which
faults are scripted when, and which :class:`Check` verdicts the run must
satisfy.  ``sim/runner.py`` executes it against the REAL
mesh → worker → node-kernel → fleet-router path; everything random rides
an injected seeded rng (the ``RetryPolicy`` convention), so one seed
pins the whole timeline.

Scale knobs (``Scenario.scaled``) exist so the SAME scenario definition
runs full-size in ``scripts/perf_gate.py`` (hundreds of replicas,
simulated hours) and small in the tier-1 determinism tests.

Time in this module is VIRTUAL seconds unless a name says otherwise;
nothing here reads a clock at all.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Iterator, Protocol

__all__ = [
    "LoadPhase",
    "TenantSpec",
    "ServiceSpec",
    "ReplicaEvent",
    "LeaseChurn",
    "Check",
    "Scenario",
    "diurnal_phases",
    "CHECK_OPS",
]


@dataclass(frozen=True)
class LoadPhase:
    """One segment of the arrival curve: Poisson arrivals at ``rate_rps``
    (mean requests per VIRTUAL second, exponential interarrivals from the
    scenario rng) for ``duration_s`` virtual seconds.  ``rate_rps=0`` is
    a silent gap (the diurnal trough, a maintenance window)."""

    duration_s: float
    rate_rps: float


def diurnal_phases(
    *,
    hours: float = 24.0,
    trough_rps: float,
    peak_rps: float,
    steps: int = 24,
) -> "tuple[LoadPhase, ...]":
    """A smooth day curve: ``steps`` equal phases tracing a raised cosine
    from trough (t=0) up to peak (t=hours/2) and back — the classic
    diurnal load shape, deterministic by construction."""
    phases = []
    for i in range(steps):
        # phase midpoint position in the day, 0..1
        x = (i + 0.5) / steps
        level = 0.5 - 0.5 * math.cos(2.0 * math.pi * x)
        phases.append(
            LoadPhase(
                duration_s=hours * 3600.0 / steps,
                rate_rps=trough_rps + (peak_rps - trough_rps) * level,
            )
        )
    return tuple(phases)


@dataclass(frozen=True)
class TenantSpec:
    """One traffic class: ``weight`` is its share of arrivals (relative
    to the other tenants' weights); ``sessions`` is how many distinct
    long-lived sessions its traffic collapses into — each session keeps
    one page-aligned prompt prefix, which is what prefix-affinity
    routing keys on.  A hotspot tenant is just a tenant whose weight
    dwarfs the rest.

    ``priority`` (ISSUE 20) is the class the tenant's calls carry on
    ``x-mesh-priority``: ``"interactive"`` (default) or ``"batch"``.
    Under overload the stub engines shed batch-class arrivals first —
    the mixed_priority_storm scenario gates exactly that ordering."""

    name: str
    weight: float = 1.0
    sessions: int = 4
    priority: str = "interactive"

    def __post_init__(self) -> None:
        if self.priority not in ("interactive", "batch"):
            raise ValueError(
                f"unknown tenant priority {self.priority!r} "
                "(one of: interactive, batch)"
            )


@dataclass(frozen=True)
class ServiceSpec:
    """The stubbed engine's deterministic service model, in VIRTUAL time.

    One request occupies one of ``slots`` virtual servers for
    ``(base_s + prefill_per_token_s × input_tokens × (0 if prefix hit)
    + per_token_s × new_tokens) × skew[replica]`` seconds; requests past
    every busy slot queue in virtual time.  ``shed_above`` is the
    admitted-but-unfinished depth past which the stub sheds with the
    REAL typed ``EngineOverloadedError`` (None = never shed).  ``skew``
    multiplies per replica (cycled), modeling a slow host in the fleet.

    ``pool_pages`` (ISSUE 19) gives every replica a virtual KV page pool
    of that size: the stub then maintains the REAL
    :class:`~calfkit_tpu.observability.capacity.PageLedger` and
    :class:`~calfkit_tpu.observability.capacity.CapacitySampler` (ring
    capacity ``capacity_samples``) through the same alloc / transfer /
    acquire / release / evict transitions a paged engine drives, with
    page counts derived deterministically from the prompt and prefix
    model.  ``0`` (the default) models no pool — pre-capacity scenarios
    are untouched.  Pool size is per replica and intentionally NOT
    scaled by :meth:`Scenario.scaled`: per-replica page pressure is the
    thing the capacity scenario pins."""

    base_s: float = 0.2
    per_token_s: float = 0.01
    prefill_per_token_s: float = 0.002
    new_tokens: int = 32
    steps_per_dispatch: int = 8
    slots: int = 4
    shed_above: "int | None" = None
    skew: "tuple[float, ...]" = ()
    pool_pages: int = 0
    capacity_samples: int = 0

    def multiplier(self, replica_index: int) -> float:
        if not self.skew:
            return 1.0
        return self.skew[replica_index % len(self.skew)]


@dataclass(frozen=True)
class ReplicaEvent:
    """A scripted fault on the fleet timeline, fired at virtual offset
    ``at_s`` from scenario start.  Actions:

    - ``"kill"`` — hard kill / partition away (``ReplicaTransport.kill``):
      publishes vanish, heartbeat stamp freezes, backlog buffers;
    - ``"resume"`` — the heal: backlog replays (cancels first), the next
      heartbeat re-stamps the advert;
    - ``"drain"`` — clean drain (``Worker.drain()``): the advert flips
      ``draining`` on the next beat and the router stops placing here;
    - ``"wedge_heartbeat"`` — the heartbeat loop dies but serving
      continues (the stale-not-dead geometry).
    """

    at_s: float
    action: str  # kill | resume | drain | wedge_heartbeat
    replica: int

    def __post_init__(self) -> None:
        if self.action not in ("kill", "resume", "drain", "wedge_heartbeat"):
            raise ValueError(f"unknown replica event action {self.action!r}")


@dataclass(frozen=True)
class LeaseChurn:
    """Synthetic caller-liveness churn: ``callers`` distinct lease ids
    beat on the compacted ``mesh.caller_liveness`` table (every worker
    folds them into the process lease store, exactly the production
    path).  Each caller beats every ``beat_every_s`` virtual seconds for
    a lifetime drawn uniformly from ``[min_life_s, max_life_s]`` (the
    scenario rng), then goes silent — except a ``clean_release_ratio``
    fraction, which release cleanly (tombstone) at end of life instead.
    Tens of thousands of callers is the intended scale: the point is
    proving the store's lapse law and cap behavior under fleet-sized
    churn."""

    callers: int = 1000
    ttl_s: float = 15.0
    beat_every_s: float = 5.0
    min_life_s: float = 30.0
    max_life_s: float = 300.0
    clean_release_ratio: float = 0.25


CHECK_OPS = ("<=", ">=", "==", "<", ">", "!=")


@dataclass(frozen=True)
class Check:
    """One pass/fail verdict over the scenario's harvested metrics:
    ``metric`` is a dotted path into the scenario report dict (e.g.
    ``"requests.completed"`` or ``"routing.skew_p95_over_mean"``),
    compared against ``bound`` with ``op``.  Missing metric = failed
    check (a silently absent number must not read as a pass)."""

    name: str
    metric: str
    op: str
    bound: float

    def __post_init__(self) -> None:
        if self.op not in CHECK_OPS:
            raise ValueError(f"unknown check op {self.op!r}")

    def evaluate(self, value: "float | None") -> bool:
        if value is None:
            return False
        if self.op == "<=":
            return value <= self.bound
        if self.op == ">=":
            return value >= self.bound
        if self.op == "==":
            return value == self.bound
        if self.op == "<":
            return value < self.bound
        if self.op == ">":
            return value > self.bound
        return value != self.bound


@dataclass(frozen=True)
class Scenario:
    """One declarative fleet-simulation run.  See the module docstring;
    ``docs/simulation.md`` documents every knob and the tolerance
    policy for the gated metrics."""

    name: str
    replicas: int
    phases: "tuple[LoadPhase, ...]"
    policy: str = "p2c"
    seed: int = 0
    tenants: "tuple[TenantSpec, ...]" = (TenantSpec("t0"),)
    service: ServiceSpec = field(default_factory=ServiceSpec)
    events: "tuple[ReplicaEvent, ...]" = ()
    leases: "LeaseChurn | None" = None
    # caller posture: bounded shed-retry attempts (0 = no retry policy),
    # and whether the failover supervisor runs (cascading-failure /
    # partition scenarios need it; steady-state does not)
    retry_attempts: int = 3
    failover: bool = False
    max_failovers: int = 3
    # control-plane cadence, virtual seconds (production shape: 5s beat,
    # 3 beats to stale)
    heartbeat_every_s: float = 5.0
    stale_after_s: float = 15.0
    # per-call budget; generous by default — scenario checks, not
    # timeouts, are the verdict mechanism
    timeout_s: float = 3600.0
    # racing-failover scenarios make per-replica placement counts
    # order-sensitive; with this False the report carries only
    # order-invariant aggregates (see docs/simulation.md "Determinism")
    per_replica_report: bool = True
    checks: "tuple[Check, ...]" = ()
    # dotted metric paths compared against SIM_BASELINE.json by the perf
    # gate (in addition to the pass/fail checks above)
    gated: "tuple[str, ...]" = ()

    @property
    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    def expected_arrival_horizon_s(self) -> float:
        """Virtual horizon the runner must keep time flowing past even
        when no arrivals are pending: scripted events and lease churn may
        outlive the load curve."""
        horizon = self.duration_s
        for event in self.events:
            horizon = max(horizon, event.at_s)
        return horizon + 2.0 * self.stale_after_s

    def scaled(self, factor: float) -> "Scenario":
        """A proportionally smaller (or larger) copy: replica count,
        arrival rates, session counts, and lease-churn population scale
        together so per-replica load and per-session turn counts are
        preserved; scripted event indices clamp to the new fleet size,
        and bounds of checks over population-absolute metrics
        (``leases.minted``) scale with the population.

        Verdicts are APPROXIMATELY preserved, not guaranteed: a
        two-replica fleet has almost no sibling headroom to absorb
        Poisson bursts that a twelve-replica fleet statistically
        smooths, so shed-retry checks get tighter as fleets shrink.
        The tier-1 determinism tests pin factor 0.15, where every
        pinned verdict holds; verify before leaning on other factors."""
        replicas = max(2, int(round(self.replicas * factor)))
        phases = tuple(
            replace(p, rate_rps=p.rate_rps * factor) for p in self.phases
        )
        events = tuple(
            replace(e, replica=min(e.replica, replicas - 1))
            for e in self.events
        )
        tenants = tuple(
            replace(t, sessions=max(1, int(round(t.sessions * factor))))
            for t in self.tenants
        )
        leases = self.leases
        checks = self.checks
        if leases is not None:
            scaled_callers = max(8, int(round(leases.callers * factor)))
            leases = replace(leases, callers=scaled_callers)
            checks = tuple(
                replace(c, bound=c.bound * factor)
                if c.metric == "leases.minted"
                else c
                for c in checks
            )
        return replace(
            self, replicas=replicas, phases=phases, events=events,
            tenants=tenants, leases=leases, checks=checks,
        )

    def arrival_times(self, rng: "RandomLike") -> "Iterator[float]":
        """Poisson arrival offsets (virtual seconds from scenario start)
        across every phase, in order, from the injected rng."""
        t = 0.0
        phase_start = 0.0
        for phase in self.phases:
            phase_end = phase_start + phase.duration_s
            if phase.rate_rps > 0.0:
                t = max(t, phase_start)
                while True:
                    t += rng.expovariate(phase.rate_rps)
                    if t >= phase_end:
                        break
                    yield t
            phase_start = phase_end


class RandomLike(Protocol):
    """The slice of ``random.Random`` the DSL consumes (typing seam)."""

    def expovariate(self, lambd: float) -> float: ...

    def uniform(self, a: float, b: float) -> float: ...

    def random(self) -> float: ...
