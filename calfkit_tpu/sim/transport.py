"""The process-death / partition seam: one replica's I/O boundary.

Promoted from ``tests/_chaos.py`` (ISSUE 11).  :class:`ReplicaTransport`
wraps a shared mesh transport per replica so scenarios can script the
two failure geometries fleets actually see:

- **death** — ``kill()`` with no ``resume()``: publishes vanish, the
  heartbeat stamp freezes on the table (no tombstone — that would be a
  clean shutdown), deliveries buffer like a dead consumer's partition
  backlog, and in-flight compute keeps burning (the zombie the
  cancel-tombstone law exists for);
- **partition + heal** — ``kill()`` then ``resume()``: the SAME seam.
  A partitioned replica is indistinguishable from a dead one to the
  rest of the fleet (that is the whole point of failure detectors);
  ``resume()`` is the heal — buffered deliveries replay with cancel
  records FIRST (mirroring the dispatcher's express intake, where a
  cancel skips the ordered lanes), publishes flow again, and the next
  heartbeat re-stamps the advert fresh.
"""

from __future__ import annotations

from typing import Any

from calfkit_tpu import protocol
from calfkit_tpu.mesh.tables import TableReader, TableWriter
from calfkit_tpu.mesh.transport import MeshTransport

__all__ = ["ReplicaTransport"]


class _GatedTableWriter(TableWriter):
    """A dead replica's heartbeat puts/tombstones never reach the table —
    its last stamp stays frozen there, exactly what a killed process
    leaves behind (no tombstone: that would be a CLEAN shutdown)."""

    def __init__(self, owner: "ReplicaTransport", inner: TableWriter):
        self._owner = owner
        self._inner = inner

    async def put(self, key: str, value: bytes) -> None:
        if self._owner.dead:
            self._owner.dropped.append(("<table-put>", key))
            return
        await self._inner.put(key, value)

    async def tombstone(self, key: str) -> None:
        if self._owner.dead:
            self._owner.dropped.append(("<table-tombstone>", key))
            return
        await self._inner.tombstone(key)


class _DeliveryGate:
    """The consumption half of a process death: while dead, deliveries
    buffer (the dead process's partition backlog) instead of reaching
    the node handler; ``replay()`` on resume drains the backlog with
    cancel records FIRST — mirroring the dispatcher's express intake,
    where a cancel skips the ordered lanes and therefore lands before
    the queued work it abandons gets to execute."""

    def __init__(self, owner: "ReplicaTransport", inner: Any):
        self._owner = owner
        self._inner = inner
        self.buffered: list[Any] = []

    async def __call__(self, record: Any) -> None:
        if self._owner.dead:
            self.buffered.append(record)
            return
        await self._inner(record)

    async def replay(self) -> None:
        backlog, self.buffered = self.buffered, []
        cancels = [
            r for r in backlog
            if r.headers.get(protocol.HDR_KIND) == "cancel"
        ]
        rest = [
            r for r in backlog
            if r.headers.get(protocol.HDR_KIND) != "cancel"
        ]
        for record in cancels + rest:
            await self._inner(record)


class ReplicaTransport(MeshTransport):
    """One replica's I/O boundary over the (shared) mesh — the
    process-death seam (ISSUE 9), doubling as the partition seam
    (ISSUE 11; see module docstring).

    ``kill()`` models a hard kill OR a network partition: NOTHING the
    replica publishes reaches the mesh (heartbeats stop landing with the
    last stamp frozen on the table, a half-delivered stream just stops,
    terminal replies vanish) and nothing is consumed (deliveries buffer
    like the dead consumer's backlog).  Compute the replica had in
    flight keeps burning — exactly the zombie the cancel-tombstone law
    exists for.  ``resume()`` models that zombie coming back (the heal):
    publishes flow again, the backlog replays (cancels first, per the
    dispatcher's express law), and the next heartbeat re-stamps the
    advert."""

    def __init__(self, inner: MeshTransport):
        self.inner = inner
        self.dead = False
        self.dropped: list[tuple[str, str]] = []  # publishes lost while dead
        self._gates: list[_DeliveryGate] = []

    def kill(self) -> None:
        self.dead = True

    async def resume(self) -> None:
        self.dead = False
        for gate in self._gates:
            await gate.replay()

    # ------------------------------------------------------- transport
    async def start(self) -> None:
        await self.inner.start()

    async def stop(self) -> None:
        await self.inner.stop()

    @property
    def max_message_bytes(self) -> int:
        return self.inner.max_message_bytes

    async def publish(
        self,
        topic: str,
        value: bytes,
        *,
        key: "bytes | None" = None,
        headers: "dict[str, str] | None" = None,
    ) -> None:
        if self.dead:
            self.dropped.append(
                (topic, (headers or {}).get(protocol.HDR_KIND, ""))
            )
            return
        await self.inner.publish(topic, value, key=key, headers=headers)

    async def subscribe(self, topics: Any, handler: Any, **kwargs: Any) -> Any:
        gate = _DeliveryGate(self, handler)
        self._gates.append(gate)
        return await self.inner.subscribe(topics, gate, **kwargs)

    async def ensure_topics(
        self, names: Any, *, compacted: bool = False
    ) -> None:
        await self.inner.ensure_topics(names, compacted=compacted)

    def table_reader(self, topic: str) -> TableReader:
        return self.inner.table_reader(topic)

    def table_writer(self, topic: str) -> TableWriter:
        return _GatedTableWriter(self, self.inner.table_writer(topic))
