"""Scenario metrics: harvesting, verdicts, and the SIM.json shape
(ISSUE 11).

A :class:`ScenarioReport` is one scenario's deterministic outcome — the
counters the mesh/fleet stack already keeps (delivery ledgers, heartbeat
adverts, shed/failover counts, prefix-cache hits, lease-store state)
folded into one structured dict, plus the scenario's :class:`Check`
verdicts evaluated over it.  A :class:`SimReport` is the whole suite;
``to_json()`` is the SIM.json artifact.

Determinism contract: every value inside ``scenarios`` is a pure
function of (scenario definition, seed) — byte-identical across repeat
runs and across hosts.  Host-varying facts (wall-clock runtime, capture
timestamp, git sha) live ONLY under the top-level ``capture`` key, which
the determinism test strips before comparing (and which the perf gate
never reads).
"""

from __future__ import annotations

from calfkit_tpu.effects import no_wallclock
import json
from dataclasses import dataclass, field
from typing import Any

__all__ = [
    "CheckResult",
    "ScenarioReport",
    "SimReport",
    "metric_at",
    "flatten_metrics",
    "percentile",
]

SIM_SCHEMA_VERSION = 1


@no_wallclock
def percentile(values: "list[float]", q: float) -> float:
    """Deterministic nearest-rank percentile (no interpolation jitter);
    0.0 on empty input."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = min(len(ordered) - 1, max(0, int(q * len(ordered))))
    return float(ordered[rank])


@no_wallclock
def metric_at(tree: "dict[str, Any]", path: str) -> "float | None":
    """Resolve a dotted metric path (``"requests.completed"``) to a
    number; None when the path is missing or non-numeric — callers treat
    that as a failed check, never as zero."""
    node: Any = tree
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


@no_wallclock
def flatten_metrics(
    tree: "dict[str, Any]", prefix: str = ""
) -> "dict[str, float]":
    """Every numeric leaf as ``dotted.path -> value`` (the perf gate's
    comparison surface)."""
    out: dict[str, float] = {}
    for key, value in tree.items():
        path = f"{prefix}{key}"
        if isinstance(value, dict):
            out.update(flatten_metrics(value, f"{path}."))
        elif isinstance(value, bool):
            continue
        elif isinstance(value, (int, float)):
            out[path] = float(value)
    return out


@dataclass(frozen=True)
class CheckResult:
    name: str
    metric: str
    op: str
    bound: float
    value: "float | None"
    passed: bool

    def to_dict(self) -> "dict[str, Any]":
        return {
            "name": self.name,
            "metric": self.metric,
            "op": self.op,
            "bound": self.bound,
            "value": self.value,
            "passed": self.passed,
        }


@dataclass
class ScenarioReport:
    """One scenario's outcome.  ``metrics`` is the deterministic tree the
    checks and the perf gate read; ``checks`` are the evaluated
    verdicts; ``passed`` is their conjunction."""

    name: str
    seed: int
    replicas: int
    metrics: "dict[str, Any]" = field(default_factory=dict)
    checks: "list[CheckResult]" = field(default_factory=list)
    gated: "tuple[str, ...]" = ()

    @property
    def passed(self) -> bool:
        return all(c.passed for c in self.checks)

    def metric(self, path: str) -> "float | None":
        return metric_at(self.metrics, path)

    def gated_metrics(self) -> "dict[str, float]":
        """The baseline-compared subset, resolved (missing gated path =
        absent from the result; the gate treats absence as regression)."""
        out: dict[str, float] = {}
        for path in self.gated:
            value = self.metric(path)
            if value is not None:
                out[path] = value
        return out

    def to_dict(self) -> "dict[str, Any]":
        return {
            "name": self.name,
            "seed": self.seed,
            "replicas": self.replicas,
            "passed": self.passed,
            "metrics": self.metrics,
            "checks": [c.to_dict() for c in self.checks],
            "gated": list(self.gated),
        }


@dataclass
class SimReport:
    """The whole suite run → SIM.json."""

    suite: str
    scenarios: "list[ScenarioReport]" = field(default_factory=list)

    @property
    def passed(self) -> bool:
        return bool(self.scenarios) and all(s.passed for s in self.scenarios)

    def scenario(self, name: str) -> "ScenarioReport | None":
        for report in self.scenarios:
            if report.name == name:
                return report
        return None

    def to_dict(
        self, *, capture: "dict[str, Any] | None" = None
    ) -> "dict[str, Any]":
        return {
            "schema": SIM_SCHEMA_VERSION,
            "suite": self.suite,
            "passed": self.passed,
            "scenarios": [s.to_dict() for s in self.scenarios],
            # host-varying provenance ONLY — stripped by the determinism
            # comparison, never read by the perf gate
            "capture": dict(capture or {}),
        }

    def to_json(
        self, *, capture: "dict[str, Any] | None" = None
    ) -> str:
        return json.dumps(self.to_dict(capture=capture), sort_keys=True)


@no_wallclock
def strip_capture(document: "dict[str, Any]") -> "dict[str, Any]":
    """The determinism-comparable view of a SIM.json document (drops the
    host-varying ``capture`` block)."""
    out = dict(document)
    out.pop("capture", None)
    return out


__all__.append("strip_capture")
__all__.append("SIM_SCHEMA_VERSION")
