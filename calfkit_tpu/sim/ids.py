"""Deterministic identity for simulation runs (ISSUE 11).

Correlation ids, client ids, lease ids, and node instance ids are all
minted through :func:`uuid.uuid4`.  None of them are *semantically* load
bearing, but several are *mechanically* load bearing for reproducibility:

- the **instance id** is half of the replica key — lexicographic
  tie-breaks in the routing policies and every rendezvous-hash rank are
  functions of it;
- the **correlation id** keys the mesh dispatcher's lane assignment
  (``crc32(key) % lanes``), so which calls serialize behind each other
  on a shared worker depends on it;
- the **lease id** keys the caller-liveness table.

A simulator that promises byte-identical reports across runs therefore
needs the id mint to be part of the seed.  :func:`deterministic_ids`
swaps ``uuid.uuid4`` for a seeded generator for the duration of a run —
RFC-4122-shaped (version/variant bits set) so nothing downstream can
tell, but fully reproducible.  It composes with the virtual clock the
same way: one seam, every layer moves together.
"""

from __future__ import annotations

import contextlib
import random
import uuid
from typing import Iterator

__all__ = ["deterministic_ids"]


@contextlib.contextmanager
def deterministic_ids(seed: int) -> "Iterator[None]":
    """Patch :func:`uuid.uuid4` with a generator seeded from ``seed`` for
    the duration of the block.  Never nest two of these with the same
    seed around concurrent mints from different logical actors — the
    draw ORDER is part of the determinism contract (the simulator mints
    everything from one event loop, where order is reproducible)."""
    rng = random.Random(seed ^ 0x51D_5EED)
    original = uuid.uuid4

    def seeded_uuid4() -> uuid.UUID:
        return uuid.UUID(int=rng.getrandbits(128), version=4)

    uuid.uuid4 = seeded_uuid4  # type: ignore[assignment]
    try:
        yield
    finally:
        uuid.uuid4 = original  # type: ignore[assignment]
