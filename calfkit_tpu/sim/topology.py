"""Multi-replica fleet topologies on one event loop (promoted from
``tests/_chaos.py`` — ISSUE 11).

:class:`FleetTopology` spawns N workers hosting replicas of ONE agent
name on a shared mesh — exactly the multi-process fleet shape, collapsed
into one event loop so scenarios stay deterministic.  Each replica rides
its own :class:`~calfkit_tpu.sim.transport.ReplicaTransport` (the
death/partition seam) and its own control-plane publisher.

Heartbeat cadence comes in two modes:

- **chaos tests** (the historical shape): heartbeats tick fast on the
  REAL event loop while liveness stamps ride the virtual clock, so
  staleness is driven by ``clock.advance``, never by sleeping;
- **simulator** (ISSUE 11): ``heartbeat_interval`` is set far beyond the
  run's real duration and :meth:`beat`/:meth:`beat_all` publish adverts
  as virtual-clock events — the control plane becomes part of the
  deterministic timeline (a killed replica's beat is dropped by its
  gated transport, freezing its stamp exactly like a dead process).
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from typing import Any, Callable

from calfkit_tpu.sim.transport import ReplicaTransport

__all__ = ["FleetTopology"]


class FleetTopology:
    """N workers hosting replicas of ONE agent name on a shared mesh.

    Each replica is its own :class:`~calfkit_tpu.worker.Worker` (own
    dispatch lanes, own control-plane publisher, own drain state) —
    exactly the multi-process fleet shape, collapsed into one event loop
    so scenarios stay deterministic.  ``delivered[i]`` ledgers the
    correlation ids whose CALLS were admitted by replica ``i`` (the
    drain/stale scenarios' "zero new calls" oracle).
    """

    def __init__(
        self,
        mesh: Any,
        models: "list[Any]",
        *,
        name: str = "svc",
        heartbeat_interval: float = 0.05,
        stale_multiplier: float = 100.0,
        agent_kwargs: "dict | None" = None,
        meshes: "list[Any] | None" = None,
        max_workers: int = 8,
    ):
        from calfkit_tpu.controlplane import ControlPlaneConfig
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        self.mesh = mesh
        self.name = name
        self.config = ControlPlaneConfig(
            heartbeat_interval=heartbeat_interval,
            stale_multiplier=stale_multiplier,
        )
        self.delivered: "list[list[str]]" = [[] for _ in models]
        self.agents: "list[Any]" = []
        self.workers: "list[Any]" = []
        # replicas whose heartbeat is wedged: the tick loop is cancelled
        # AND the simulator's manual beat skips them (a wedged publisher
        # re-stamping through beat_all would un-wedge it silently)
        self._wedged: "set[int]" = set()
        # every replica's I/O rides its own ReplicaTransport proxy — the
        # process-death seam (kill/resume).  ``meshes`` supplies a
        # per-replica INNER transport (e.g. one KafkaWireMesh connection
        # each, the real multi-process shape); default = the shared mesh.
        self.transports = [
            ReplicaTransport(inner)
            for inner in (meshes if meshes is not None else [mesh] * len(models))
        ]
        for i, model in enumerate(models):
            agent = Agent(
                name,
                model=model,
                before_node=[self._ledger(i)],
                **(agent_kwargs or {}),
            )
            self.agents.append(agent)
            self.workers.append(
                Worker(
                    [agent],
                    mesh=self.transports[i],
                    control_plane=self.config,
                    owns_transport=meshes is not None,
                    max_workers=max_workers,
                )
            )

    def _ledger(self, i: int) -> Callable[[Any], None]:
        def note(ctx: Any) -> None:
            if ctx.delivery_kind == "call":
                self.delivered[i].append(ctx.correlation_id or "")
            return None

        return note

    # ------------------------------------------------------------ lifecycle
    async def __aenter__(self) -> "FleetTopology":
        for worker in self.workers:
            await worker.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        for worker in self.workers:
            with contextlib.suppress(Exception):
                await worker.stop()

    # ------------------------------------------------------------- identity
    def instance_id(self, i: int) -> str:
        return self.agents[i].instance_id

    def replica_key(self, i: int) -> str:
        return f"{self.agents[i].node_id}@{self.instance_id(i)}"

    def index_of_lowest_key(self) -> int:
        """The replica a depth-tied least-loaded pick lands on (policies
        tie-break on the lexicographic replica key)."""
        return min(range(len(self.agents)), key=self.replica_key)

    def calls_delivered(self, i: int) -> int:
        return len(self.delivered[i])

    # ------------------------------------------------------ process death
    def kill(self, i: int) -> None:
        """Hard-kill replica ``i`` (ISSUE 9): stop consuming AND stop
        heartbeating, without drain — its advert stays on the table with
        the last stamp (staleness is then driven by ``clock.advance``),
        its in-flight output vanishes, its backlog buffers."""
        self.transports[i].kill()

    async def resume(self, i: int) -> None:
        """The killed replica returns as a ZOMBIE: backlog replays
        (cancels first, the express law), publishes flow, the next
        heartbeat re-stamps the advert fresh."""
        await self.transports[i].resume()

    def drain(self, i: int) -> None:
        """Clean drain: the worker refuses NEW calls, finishes in-flight
        work, and its next advert flips ``draining`` so routers stop
        picking it (the scale-down / deploy geometry)."""
        self.workers[i].drain()

    # ---------------------------------------------------- heartbeat chaos
    def _publisher(self, i: int) -> Any:
        attached = self.workers[i]._advertiser
        assert attached is not None, "control plane not attached"
        return attached._publisher

    async def beat(self, i: int) -> None:
        """Publish replica ``i``'s adverts ONCE, stamped at the current
        virtual clock — the simulator's heartbeat primitive (the tick
        loop never fires when ``heartbeat_interval`` is set beyond the
        run).  A killed/partitioned replica's beat is dropped by its
        gated transport, so its table stamp freezes exactly like a dead
        process's."""
        if i in self._wedged:
            return
        publisher = self._publisher(i)
        for advert in publisher._adverts:
            await publisher._writers[advert.topic].put(
                advert.key, publisher._record(advert).to_wire()
            )

    async def beat_all(self) -> None:
        for i in range(len(self.workers)):
            await self.beat(i)

    def wedge_heartbeat(self, i: int) -> None:
        """Simulate a wedged worker: the heartbeat loop dies, the record
        stays on the table with its last stamp (no tombstone — that
        would be a clean shutdown, a DIFFERENT scenario), and serving
        continues.  Advancing the virtual clock past ``stale_after``
        then makes the replica ineligible."""
        publisher = self._publisher(i)
        if publisher._task is not None:
            publisher._task.cancel()
            publisher._task = None
        # simulator mode drives beats manually: mark the replica so
        # beat()/beat_all() stop re-stamping it too
        self._wedged.add(i)

    async def resume_heartbeat(self, i: int) -> None:
        """The wedged worker recovers: one immediate re-advert (fresh
        stamp on the current virtual clock) and the tick loop restarts."""
        self._wedged.discard(i)
        publisher = self._publisher(i)
        await self.beat(i)
        # wallclock-ok: real-time chaos-test helper predating the
        # simulator — re-arms the REAL tick loop's monotonic stamp; never
        # runs inside a scenario's virtual event loop
        publisher._last_beat_at = time.monotonic()
        publisher._task = asyncio.get_running_loop().create_task(
            publisher._beat(), name=f"chaos-resumed-heartbeat-{i}"
        )
