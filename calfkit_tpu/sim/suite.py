"""The pinned scenario suite (ISSUE 11): what ``scripts/perf_gate.py``
runs on every PR and what SIM.json / SIM_BASELINE.json are captured
from.

Nine geometries, each exercising a different fleet claim through the
real mesh → worker → router path (see docs/simulation.md for the full
metric definitions and the reasoning behind every bound):

- **steady_state_120** — 120 replicas under uniform Poisson load: the
  width claim.  Gates routing skew and completion.
- **diurnal_ramp** — a compressed two-hour day curve over 12 replicas
  with bounded admission: peak traffic sheds and retries onto siblings
  instead of failing.  Gates sheds, completion, and peak depth.
- **hotspot_tenant** — one tenant dwarfs the rest under
  prefix-affinity routing: repeat sessions stay home.  Gates the
  prefix-cache hit rate.
- **cascading_failure** — three replicas die in sequence mid-traffic
  with failover supervision on: every blackholed call recovers.
  Order-invariant aggregates only (``per_replica_report=False`` —
  racing supervisors make per-replica counts order-sensitive; see
  docs/simulation.md "Determinism").
- **partition_heal** — two replicas partition away and heal: traffic
  completes throughout, the healed replicas serve again.
- **run_ledger_failover** — two replicas die mid-traffic under failover
  supervision, and the gate moves to the RUN level (ISSUE 17): every
  run the caller launched closes ``ok`` in the client's run ledger
  (``runs.completion_ratio`` exactly 1.0 — same numbers the SLO rollup
  publishes, same pure fold), run-level end-to-end p95 stays bounded
  across failover stale-out waits, attempt amplification stays sane.
- **lease_churn** — 20k synthetic caller leases churn against the real
  compacted liveness table while traffic flows: the lapse law and the
  store cap hold at fleet scale.
- **mixed_priority_storm** — the diurnal geometry pushed to ~2×
  oversubscription with a 50/50 interactive/batch tenant mix
  (ISSUE 20): overload is guaranteed, and the gates pin WHO degrades —
  interactive completion and end-to-end p95 hold while batch absorbs
  (almost) every shed, with a completion floor proving preempted batch
  work is re-driven, never silently lost.
- **capacity_churn** — the hotspot geometry with every replica given a
  page pool SMALLER than its session working set (ISSUE 19): the real
  :class:`~calfkit_tpu.observability.capacity.PageLedger` must show
  eviction churn under pressure, the occupancy timeline must sample,
  and a drained fleet must attribute every page to no owner
  (``capacity.residual_pages_in_use == 0`` — the leak oracle at fleet
  scale).  Gates eviction volume, alloc stalls, peak occupancy, and
  the churn-degraded prefix hit rate.

Scenario *definitions* are data: the tier-1 tests run
``scaled_suite(0.1)`` for speed; the perf gate runs ``PINNED_SUITE``
full-size.  Changing anything here invalidates SIM_BASELINE.json —
regenerate with ``python scripts/perf_gate.py --write-baseline``.
"""

from __future__ import annotations

from calfkit_tpu.sim.scenario import (
    Check,
    LeaseChurn,
    LoadPhase,
    ReplicaEvent,
    Scenario,
    ServiceSpec,
    TenantSpec,
    diurnal_phases,
)

__all__ = ["PINNED_SUITE", "SUITE_NAME", "scaled_suite", "scenario_named"]

SUITE_NAME = "fleet-pinned-v1"


STEADY_STATE = Scenario(
    name="steady_state_120",
    replicas=120,
    seed=11,
    phases=(LoadPhase(duration_s=300.0, rate_rps=16.0),),
    policy="p2c",
    service=ServiceSpec(base_s=0.6, per_token_s=0.04, slots=1),
    heartbeat_every_s=5.0,
    stale_after_s=15.0,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        Check("skew_bounded", "routing.skew_p95_over_mean", "<=", 1.9),
        Check("fleet_used", "routing.skew_max_over_mean", ">", 0.0),
    ),
    gated=(
        "requests.completed",
        "routing.skew_p95_over_mean",
        "tokens.tokens_per_dispatch",
        "time.makespan_s",
    ),
)


DIURNAL = Scenario(
    name="diurnal_ramp",
    replicas=12,
    seed=23,
    phases=diurnal_phases(
        hours=2.0, trough_rps=0.1, peak_rps=2.2, steps=16
    ),
    policy="p2c",
    # peak sits just under fleet capacity (12×2 slots / ~10s service =
    # 2.4 rps) with a shed cap LOW enough that Poisson clumps at peak
    # actually trip bounded admission — the retry-onto-siblings path is
    # part of what this scenario proves
    service=ServiceSpec(
        base_s=4.0, per_token_s=0.19, slots=2, shed_above=5
    ),
    retry_attempts=4,
    heartbeat_every_s=15.0,
    stale_after_s=45.0,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        Check("peak_depth_visible", "depth.p95", ">=", 2.0),
        Check("depth_bounded", "depth.max", "<=", 24.0),
        Check("admission_exercised", "shed.sheds", ">=", 1.0),
    ),
    gated=(
        "requests.completed",
        "shed.sheds",
        "depth.p95",
        "time.makespan_s",
    ),
)


HOTSPOT = Scenario(
    name="hotspot_tenant",
    replicas=16,
    seed=37,
    phases=(LoadPhase(duration_s=600.0, rate_rps=4.0),),
    policy="prefix-affinity",
    tenants=(
        TenantSpec("hot", weight=6.0, sessions=24),
        TenantSpec("t1", weight=1.0, sessions=16),
        TenantSpec("t2", weight=1.0, sessions=16),
        TenantSpec("t3", weight=1.0, sessions=16),
    ),
    service=ServiceSpec(
        base_s=0.4, per_token_s=0.02, prefill_per_token_s=0.01, slots=2
    ),
    heartbeat_every_s=5.0,
    stale_after_s=15.0,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        Check("sessions_stay_home", "prefix.hit_rate", ">=", 0.9),
    ),
    gated=(
        "requests.completed",
        "prefix.hit_rate",
        "prefix.reused_tokens",
        "time.makespan_s",
    ),
)


CASCADE = Scenario(
    name="cascading_failure",
    replicas=12,
    seed=41,
    phases=(LoadPhase(duration_s=240.0, rate_rps=3.0),),
    policy="least-loaded",
    service=ServiceSpec(base_s=1.5, per_token_s=0.05, slots=2),
    failover=True,
    max_failovers=4,
    retry_attempts=4,
    heartbeat_every_s=5.0,
    stale_after_s=15.0,
    events=(
        ReplicaEvent(at_s=60.0, action="kill", replica=2),
        ReplicaEvent(at_s=90.0, action="kill", replica=5),
        ReplicaEvent(at_s=120.0, action="kill", replica=8),
    ),
    per_replica_report=False,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        Check("corpses_get_nothing", "routing.delivered_while_dead", "==", 0.0),
        Check("failover_fired", "routing.failover_arrivals", ">=", 1.0),
    ),
    gated=(
        "requests.completed",
        "routing.delivered_while_dead",
    ),
)


PARTITION_HEAL = Scenario(
    name="partition_heal",
    replicas=10,
    seed=53,
    phases=(LoadPhase(duration_s=300.0, rate_rps=3.0),),
    policy="least-loaded",
    service=ServiceSpec(base_s=1.0, per_token_s=0.03, slots=2),
    failover=True,
    max_failovers=4,
    retry_attempts=4,
    heartbeat_every_s=5.0,
    stale_after_s=15.0,
    events=(
        ReplicaEvent(at_s=60.0, action="kill", replica=3),
        ReplicaEvent(at_s=60.0, action="kill", replica=4),
        ReplicaEvent(at_s=180.0, action="resume", replica=3),
        ReplicaEvent(at_s=180.0, action="resume", replica=4),
    ),
    per_replica_report=False,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        Check("partitioned_get_nothing", "routing.delivered_while_dead", "==", 0.0),
        Check("healed_serve_again", "routing.delivered_after_heal", ">=", 1.0),
    ),
    gated=(
        "requests.completed",
        "routing.delivered_after_heal",
    ),
)


RUN_LEDGER = Scenario(
    name="run_ledger_failover",
    replicas=10,
    seed=79,
    phases=(LoadPhase(duration_s=180.0, rate_rps=2.5),),
    policy="least-loaded",
    service=ServiceSpec(base_s=1.2, per_token_s=0.04, slots=2),
    failover=True,
    max_failovers=4,
    retry_attempts=4,
    heartbeat_every_s=5.0,
    stale_after_s=15.0,
    events=(
        ReplicaEvent(at_s=45.0, action="kill", replica=3),
        ReplicaEvent(at_s=100.0, action="kill", replica=7),
    ),
    per_replica_report=False,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        # the run-ledger claim (ISSUE 17): every RUN the caller
        # launched — including the ones whose first placement died
        # mid-flight — closes "ok" in the ledger, and the run-level
        # end-to-end p95 (virtual seconds, ACROSS failover stale-out
        # waits) stays bounded.  These are the same numbers the SLO
        # rollup publishes, computed through the same pure fold.
        Check("every_run_ok", "runs.completion_ratio", "==", 1.0),
        Check("ledger_closed_runs", "runs.finished", ">=", 30.0),
        Check("failover_in_ledger", "runs.failover_rate", ">", 0.0),
        Check("run_p95_bounded", "runs.e2e_p95_s", "<=", 20.0),
        Check(
            "amplification_bounded",
            "runs.attempt_amplification", "<=", 3.0,
        ),
    ),
    gated=(
        "requests.completed",
        "runs.completion_ratio",
        "runs.finished",
        "runs.e2e_p95_s",
        "runs.attempt_amplification",
    ),
)


LEASE_CHURN = Scenario(
    name="lease_churn",
    replicas=6,
    seed=67,
    phases=(LoadPhase(duration_s=600.0, rate_rps=1.0),),
    policy="p2c",
    service=ServiceSpec(base_s=0.5, per_token_s=0.02, slots=2),
    leases=LeaseChurn(
        callers=20_000,
        ttl_s=90.0,
        beat_every_s=45.0,
        min_life_s=60.0,
        max_life_s=240.0,
        clean_release_ratio=0.25,
    ),
    heartbeat_every_s=15.0,
    stale_after_s=45.0,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        Check("fleet_scale_leases", "leases.minted", ">=", 10_000.0),
        Check("lapse_law_bites", "leases.lapsed", ">=", 1.0),
        # the store cap must hold no matter how many callers churned
        Check("store_capped", "leases.store_size", "<=", 4096.0),
    ),
    gated=(
        "requests.completed",
        "leases.lapsed",
        "leases.store_size",
    ),
)


CAPACITY_CHURN = Scenario(
    name="capacity_churn",
    replicas=16,
    seed=97,
    phases=(LoadPhase(duration_s=600.0, rate_rps=4.0),),
    policy="prefix-affinity",
    tenants=(
        TenantSpec("hot", weight=6.0, sessions=24),
        TenantSpec("t1", weight=1.0, sessions=16),
        TenantSpec("t2", weight=1.0, sessions=16),
        TenantSpec("t3", weight=1.0, sessions=16),
    ),
    # the hotspot service shape, with a per-replica page pool sized just
    # UNDER the steady-state session working set (~4-5 resident chains x
    # 4 pages each, plus in-flight private pages): prefix registration
    # and fresh admissions must fight for pages, so the zero-ref LRU
    # eviction path — and its hit-rate cost — actually runs.  pool_pages
    # is per replica and survives Scenario.scaled untouched, so the
    # tier-1 scaled run sees the same per-replica pressure.
    service=ServiceSpec(
        base_s=0.4, per_token_s=0.02, prefill_per_token_s=0.01, slots=2,
        pool_pages=24, capacity_samples=256,
    ),
    heartbeat_every_s=5.0,
    stale_after_s=15.0,
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
        # the pool is undersized by construction — if nothing evicts,
        # the pressure model is broken, not the fleet healthy
        Check("pool_bites", "capacity.evicted_pages", ">=", 1.0),
        Check("pool_pressured", "capacity.peak_pages_in_use", ">=", 6.0),
        Check("timeline_sampled", "capacity.samples", ">=", 1.0),
        # the leak oracle at fleet scale: after the fleet drains, every
        # page is attributed to no owner
        Check("no_page_leak", "capacity.residual_pages_in_use", "==", 0.0),
    ),
    gated=(
        "requests.completed",
        "capacity.evicted_pages",
        "capacity.alloc_stalls",
        "capacity.peak_pages_in_use",
        "capacity.prefix_resident_pages",
        "prefix.hit_rate",
    ),
)


MIXED_PRIORITY_STORM = Scenario(
    name="mixed_priority_storm",
    replicas=12,
    seed=131,
    # the diurnal geometry pushed past saturation: fleet capacity is
    # 12 replicas × 2 slots / ~10s service ≈ 2.4 rps, and the peak
    # offers ≈1.5× that — overload is GUARANTEED, so the verdicts are
    # about WHO degrades, not whether anyone does.  The peak is chosen
    # so the interactive HALF of the mix (≈1.8 rps) stays under
    # capacity on its own: that is the regime the shed-order law
    # protects (batch absorbs the overload); past 2× the interactive
    # class alone saturates the fleet and sheds against itself, which
    # no priority ordering can fix.  One compressed hour (not
    # diurnal_ramp's two): sustained oversubscription churns retries
    # hard enough that a longer window only costs gate wall time
    # without sharpening any verdict
    phases=diurnal_phases(
        hours=1.0, trough_rps=0.2, peak_rps=3.6, steps=8
    ),
    policy="p2c",
    tenants=(
        TenantSpec("chat", weight=1.0, sessions=12, priority="interactive"),
        TenantSpec("bulk", weight=1.0, sessions=8, priority="batch"),
    ),
    service=ServiceSpec(
        base_s=4.0, per_token_s=0.19, slots=2, shed_above=5
    ),
    retry_attempts=4,
    heartbeat_every_s=15.0,
    stale_after_s=45.0,
    checks=(
        # the QoS claims (ISSUE 20): past saturation the fleet CANNOT
        # complete everything — the gate is that degradation lands on
        # the batch class.  Interactive keeps near-total completion
        # (0.987 in the committed run; with classless shedding both
        # classes would sit at the blended ~0.91) with an end-to-end
        # p95 bounded BELOW where batch sits (363s vs 501s committed —
        # sheds preempt queued batch work instead of queueing behind
        # it); batch keeps a completion FLOOR (retries re-drive
        # preempted work — shed never silently loses it); and the
        # shed-fairness ratio pins the shed-order law: batch absorbs
        # ~4× its traffic share of sheds (0.79 committed vs the 0.5 a
        # classless shed would land), with the interactive remainder
        # being retry-amplified arrivals at lanes whose whole queue
        # was interactive (nothing sheddable — the structural escape
        # hatch, not a fairness bug).
        Check("overload_real", "shed.sheds", ">=", 1.0),
        Check(
            "interactive_completes",
            "qos.interactive.completion_ratio", ">=", 0.97,
        ),
        Check(
            "interactive_p95_bounded",
            "qos.interactive.e2e_p95_s", "<=", 450.0,
        ),
        Check(
            "batch_floor_holds",
            "qos.batch.completion_ratio", ">=", 0.5,
        ),
        Check(
            "sheds_land_on_batch",
            "qos.shed_fairness_ratio", ">=", 0.7,
        ),
    ),
    gated=(
        "requests.completed",
        "qos.interactive.completion_ratio",
        "qos.interactive.e2e_p95_s",
        "qos.batch.completion_ratio",
        "qos.shed_fairness_ratio",
    ),
)


PINNED_SUITE: "tuple[Scenario, ...]" = (
    STEADY_STATE,
    DIURNAL,
    HOTSPOT,
    CASCADE,
    PARTITION_HEAL,
    RUN_LEDGER,
    LEASE_CHURN,
    CAPACITY_CHURN,
    MIXED_PRIORITY_STORM,
)



def scaled_suite(factor: float) -> "tuple[Scenario, ...]":
    """The same nine geometries, proportionally smaller — the tier-1
    determinism tests' fast path (arrival rates scale with the fleet so
    per-replica load, and therefore every verdict, is preserved)."""
    return tuple(s.scaled(factor) for s in PINNED_SUITE)


def scenario_named(name: str) -> Scenario:
    for scenario in PINNED_SUITE:
        if scenario.name == name:
            return scenario
    raise KeyError(name)
