"""Scripted fault injectors and the bounded settling primitive.

Promoted from ``tests/_chaos.py`` (ISSUE 11) so the fleet simulator and
the chaos test suite share ONE set of deterministic failure seams:

- :class:`ChaosScript` — the engine's ``_chaos`` seam: fires a scripted
  exception (or blocks on a gate — the wedged-device simulator) at the
  Nth visit of a named point, so a mid-stream engine fault lands on an
  exact, reproducible dispatch.
- :class:`BrokerChaos` — the in-memory mesh's publish hook
  (``InMemoryMesh.chaos``): drops the Nth record matching a topic/kind
  predicate, counts everything it sees, and can run scripted side
  effects at publish time (e.g. advance the virtual clock between a
  client's deadline mint and the node's delivery).
- :func:`settle` — await a condition within a BOUNDED number of
  event-loop ticks; the harness's only waiting primitive.
- :func:`assert_engine_drained` — the no-leak oracle: no active slots,
  no in-flight dispatch, every slot on the free list, every page back
  in the pool.

Everything here is plain deterministic state — no randomness, no
wall-clock reads (lint-enforced across the sim package).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable

from calfkit_tpu import protocol

__all__ = [
    "ChaosScript",
    "BrokerChaos",
    "settle",
    "assert_engine_drained",
]


class ChaosScript:
    """Scripted failure points for the engine's ``_chaos`` seam.

    >>> engine._chaos = ChaosScript().fail_at("dispatch", 3, RuntimeError("x"))

    raises on the 3rd decode tick exactly; every other visit is a no-op.
    ``calls`` keeps per-point visit counts for assertions.
    """

    def __init__(self) -> None:
        self.calls: dict[str, int] = {}
        self._plan: dict[tuple[str, int], BaseException] = {}
        self._blocks: dict[tuple[str, int], "threading.Event"] = {}

    def fail_at(
        self, point: str, nth: int, exc: BaseException
    ) -> "ChaosScript":
        self._plan[(point, nth)] = exc
        return self

    def block_at(
        self, point: str, nth: int, gate: "threading.Event"
    ) -> "ChaosScript":
        """On the Nth visit of ``point``, BLOCK until ``gate`` is set —
        the wedged-device-grant simulator (ISSUE 9): the decode thread
        (and with it the whole serve loop, stuck in its to_thread) hangs
        exactly like a hung device sync, and only the watchdog's own
        task can observe it.  ``gate.set()`` releases the dispatch, which
        then lands normally (the recovery path)."""
        self._blocks[(point, nth)] = gate
        return self

    def __call__(self, point: str) -> None:
        count = self.calls.get(point, 0) + 1
        self.calls[point] = count
        gate = self._blocks.pop((point, count), None)
        if gate is not None:
            gate.wait()
        exc = self._plan.pop((point, count), None)
        if exc is not None:
            raise exc


class BrokerChaos:
    """Scripted broker misbehavior for ``InMemoryMesh.chaos``.

    Rules match on message kind (the ``x-mesh-kind`` header) and/or a
    topic substring; each drops up to ``count`` matching records.  All
    publishes are recorded in ``seen`` as ``(topic, kind)`` so scenarios
    can assert what crossed the broker (e.g. "a cancel record WAS
    published after the timeout").  ``on_publish`` hooks run for every
    record — the deterministic place to advance a virtual clock between
    a client's deadline mint and the node's delivery.
    """

    def __init__(self) -> None:
        self.seen: list[tuple[str, str]] = []
        self.dropped: list[tuple[str, str]] = []
        self._rules: list[dict[str, Any]] = []
        self.on_publish: "Callable[[str, dict[str, str]], None] | None" = None

    def drop(
        self,
        *,
        kind: "str | None" = None,
        topic_contains: "str | None" = None,
        count: int = 1,
    ) -> "BrokerChaos":
        self._rules.append(
            {"kind": kind, "topic": topic_contains, "count": count}
        )
        return self

    def kinds_seen(self, kind: str) -> int:
        return sum(1 for _, k in self.seen if k == kind)

    def __call__(self, topic: str, headers: dict[str, str]) -> "str | None":
        kind = headers.get(protocol.HDR_KIND, "")
        self.seen.append((topic, kind))
        if self.on_publish is not None:
            self.on_publish(topic, headers)
        for rule in self._rules:
            if rule["count"] <= 0:
                continue
            if rule["kind"] is not None and kind != rule["kind"]:
                continue
            if rule["topic"] is not None and rule["topic"] not in topic:
                continue
            rule["count"] -= 1
            self.dropped.append((topic, kind))
            return "drop"
        return None


async def settle(
    condition: Callable[[], bool],
    *,
    ticks: int = 400,
    interval: float = 0.01,
    message: str = "",
) -> int:
    """Await ``condition`` within a bounded number of event-loop ticks;
    returns the tick count it took.  The ONLY waiting primitive chaos
    scenarios use — an unmet condition is a bounded, attributable
    failure, never a hang.  ``interval=0`` degrades to pure
    ``sleep(0)`` yields (the simulator's frozen-clock drain: no real
    timer may interleave, so the tick at which the condition flips is
    reproducible)."""
    for tick in range(ticks):
        if condition():
            return tick
        await asyncio.sleep(interval)
    raise AssertionError(
        message or f"condition not met within {ticks} bounded ticks"
    )


def assert_engine_drained(
    engine: Any, total_free_pages: "int | None" = None
) -> None:
    """The no-leak oracle: every slot free, no in-flight dispatch, no
    queued entries, and (paged) every page back in the pool."""
    assert not engine._active, f"leaked active slots: {dict(engine._active)}"
    assert engine._pend is None, "a dispatch is still marked in flight"
    assert engine._inflight is None, "a chunked admission wave leaked"
    assert not engine._admitting, "an admission prefill is still in flight"
    assert not engine._pending and not engine._carry, "queued entries leaked"
    assert not engine._long_pending and engine._long is None
    assert len(engine._free) == engine.runtime.max_batch_size, (
        f"free list has {len(engine._free)} of "
        f"{engine.runtime.max_batch_size} slots"
    )
    if total_free_pages is not None and engine._page_alloc is not None:
        assert engine._page_alloc.free_pages == total_free_pages, (
            f"leaked pages: {engine._page_alloc.free_pages} free of "
            f"{total_free_pages}"
        )
    # the attribution oracle (ISSUE 19): a drained engine's page ledger
    # attributes every page to NO owner — a nonzero count here is an
    # attribution leak (a missed free/release mirror), even if the
    # allocator itself balanced
    ledger = getattr(engine, "_ledger", None)
    if ledger is not None:
        assert ledger.pages_in_use == 0, (
            f"ledger attributes {ledger.pages_in_use} page(s) to live "
            f"owners on a drained engine: {ledger.breakdown(top=4)}"
        )
