"""The partition-keying seam: task_id -> Kafka partition key.

THE CONTRACT (reference: calfkit/keying.py:1-34 — the single most load-bearing
invariant in the framework):

    Every publish that participates in a workflow run MUST be keyed by
    ``partition_key(task_id)``.  Combined with key-ordered consumption
    (parallel across keys, strictly serial per key), this makes every run a
    single-writer system: per-run state mutation is race-free *by
    construction*, with no locks anywhere.

A new keying scheme would change which runs serialize against each other on a
shared partition; route every producer through this function so the decision
stays in one place.
"""

from __future__ import annotations


def partition_key(task_id: str) -> bytes:
    """The one authority for workflow partition keys."""
    if not task_id:
        raise ValueError("task_id must be non-empty")
    return task_id.encode("utf-8")
