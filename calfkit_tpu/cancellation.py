"""Mesh-wide cancellation and deadline plumbing (ISSUE 5).

Three small, dependency-free pieces that let a caller's death actually
reach the TPU:

- :func:`wall_clock` — THE wall-clock seam every deadline comparison in
  the package goes through (client mint, hop expiry check, engine
  admission/reap).  One patch point means the chaos harness can drive
  every layer off one deterministic virtual clock, with no sleeps.
- :data:`current_deadline` — a contextvar the node kernel sets from the
  delivery's ``x-mesh-deadline`` header, mirroring how the trace context
  propagates.  In-process work started under the delivery (the inference
  engine, via :class:`~calfkit_tpu.inference.client.JaxLocalModelClient`)
  reads it and enforces the SAME absolute deadline — no per-layer budget
  arithmetic, no drift.
- the **cancel-target registry** — a process-wide weak set of objects
  exposing ``cancel_correlation(corr) -> int`` (the inference engine
  registers itself).  A ``cancel``-kind record arriving at any node fans
  out through :func:`propagate_cancel`, so a timed-out caller's publish
  reaches request abandonment inside every engine that still burns
  dispatches for that correlation id.
- **cancel tombstones** — a cancel can arrive BEFORE the work it abandons
  is anywhere a registry target can see it: the call record may still be
  queued in a dispatch lane behind earlier work (cancel records ride
  EXPRESS past the lanes), or the hop may not have submitted to the
  engine yet.  :func:`propagate_cancel` therefore records the correlation
  id in a small bounded store, and late-starting work asks
  :func:`was_cancelled` to fault fast instead of executing a full
  prefill+decode for a caller that already left.

Everything here is fail-open telemetry-grade plumbing: a broken target
never faults the delivery that tried to cancel it.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import OrderedDict
from contextvars import ContextVar
from typing import Any

logger = logging.getLogger(__name__)

__all__ = [
    "wall_clock",
    "current_deadline",
    "register_cancel_target",
    "propagate_cancel",
    "cancel_targets",
    "was_cancelled",
]
# NOTE: deliberately NO per-layer "remaining budget" helper — every layer
# compares against the ABSOLUTE deadline on the shared clock; budget
# arithmetic per hop is the drift-prone design this module replaces.

# THE deadline clock: module attribute so tests/chaos patch ONE name and
# every layer (client mint, hop expiry, engine admission/reap) moves in
# lockstep.  Always call through the module (``cancellation.wall_clock()``)
# so the patch is visible.
wall_clock = time.time

# the delivery's absolute deadline (epoch seconds), set by the node kernel
# for the duration of one delivery — None outside any deadlined delivery
current_deadline: "ContextVar[float | None]" = ContextVar(
    "calfkit_mesh_deadline", default=None
)


# --------------------------------------------------------------- registry
# WeakSet: an abandoned engine must be collectable; a stopped one simply
# reports zero matches.  The lock only guards set mutation/iteration —
# targets' cancel_correlation runs outside it (a slow target must not
# serialize other registrations).
_TARGETS: "weakref.WeakSet[Any]" = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()


def register_cancel_target(target: Any) -> None:
    """Register an object exposing ``cancel_correlation(corr: str) -> int``
    (idempotent; weakly held)."""
    with _REGISTRY_LOCK:
        _TARGETS.add(target)


def cancel_targets() -> "list[Any]":
    with _REGISTRY_LOCK:
        return list(_TARGETS)


# ------------------------------------------------------ cancel tombstones
# LRU + TTL bounded: tombstones are advisory best-effort state — evicting
# an old entry only costs wasted work for an already-dead caller, never
# correctness — so a fixed cap is safe and keeps a cancel storm from
# growing the map without bound.  Retries are immune by construction:
# every retry attempt runs under a FRESH correlation id (RetryPolicy
# contract in client/caller.py).
_TOMBSTONE_CAP = 4096
_TOMBSTONE_TTL_S = 600.0
_tombstones: "OrderedDict[str, float]" = OrderedDict()


def _record_tombstone(correlation_id: str) -> None:
    with _REGISTRY_LOCK:
        _tombstones[correlation_id] = wall_clock()
        _tombstones.move_to_end(correlation_id)
        while len(_tombstones) > _TOMBSTONE_CAP:
            _tombstones.popitem(last=False)


def was_cancelled(correlation_id: "str | None") -> bool:
    """True if a mesh ``cancel`` for this correlation id already passed
    through this process — work that has not started yet should fault
    fast (``mesh.cancelled``) instead of executing for a dead caller."""
    if not correlation_id:
        return False
    with _REGISTRY_LOCK:
        stamp = _tombstones.get(correlation_id)
        if stamp is None:
            return False
        if wall_clock() - stamp > _TOMBSTONE_TTL_S:
            del _tombstones[correlation_id]
            return False
        return True


def propagate_cancel(correlation_id: str) -> int:
    """Fan a cancel out to every registered target; returns how many
    in-flight requests were abandoned.  Also records the correlation id's
    tombstone so work the registry cannot see yet (queued behind a busy
    dispatch lane, pre-submit) still dies at its admission gate.
    Fail-open per target."""
    if not correlation_id:
        return 0
    _record_tombstone(correlation_id)
    total = 0
    for target in cancel_targets():
        try:
            total += int(target.cancel_correlation(correlation_id) or 0)
        except Exception:  # noqa: BLE001 - a broken target never blocks the rest
            logger.debug(
                "cancel target %r failed for %s",
                target, correlation_id[:8], exc_info=True,
            )
    return total
