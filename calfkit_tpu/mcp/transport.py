"""Minimal MCP client: JSON-RPC 2.0 over stdio (newline-delimited) or
streamable HTTP.

Implements exactly the subset the toolbox node needs (reference:
calfkit/mcp/mcp_transport.py:79 wraps the official SDK; we own the protocol
instead — the wire format is plain JSON-RPC):

- ``initialize`` handshake + ``notifications/initialized``
- ``tools/list`` (paginated) and ``tools/call``
- ``notifications/tools/list_changed`` surfaces via a callback
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

logger = logging.getLogger(__name__)

PROTOCOL_VERSION = "2024-11-05"


@dataclass(frozen=True)
class MCPServerSpec:
    """How to reach one MCP server: a command (stdio) XOR a url (HTTP)."""

    name: str
    command: list[str] | None = None
    url: str | None = None
    env: dict[str, str] = field(default_factory=dict)
    headers: dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if bool(self.command) == bool(self.url):
            raise ValueError(
                f"MCP server {self.name!r}: exactly one of command/url required"
            )


class MCPError(RuntimeError):
    pass


class MCPSession:
    def __init__(
        self,
        spec: MCPServerSpec,
        *,
        on_tools_changed: Callable[[], Awaitable[None] | None] | None = None,
        request_timeout: float = 30.0,
    ):
        self.spec = spec
        self._on_tools_changed = on_tools_changed
        self._timeout = request_timeout
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future[Any]] = {}
        self._dead: str | None = None  # set when the reader can't recover
        self._proc: asyncio.subprocess.Process | None = None
        self._reader_task: asyncio.Task[None] | None = None
        self._http: Any = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self.spec.command:
            self._proc = await asyncio.create_subprocess_exec(
                *self.spec.command,
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
                env={**__import__("os").environ, **self.spec.env} or None,
                # asyncio's default 64 KiB stream limit would KILL the
                # read loop on any large tool result; 32 MiB covers real
                # MCP payloads
                limit=32 * 1024 * 1024,
            )
            self._reader_task = asyncio.get_running_loop().create_task(
                self._read_stdio(), name=f"mcp-{self.spec.name}-reader"
            )
        else:
            import httpx

            self._http = httpx.AsyncClient(
                base_url="", headers=self.spec.headers, timeout=self._timeout
            )
        result = await self.request(
            "initialize",
            {
                "protocolVersion": PROTOCOL_VERSION,
                "capabilities": {},
                "clientInfo": {"name": "calfkit-tpu", "version": "0.1.0"},
            },
        )
        logger.info(
            "mcp %s initialized (server: %s)",
            self.spec.name,
            result.get("serverInfo", {}).get("name", "?"),
        )
        await self.notify("notifications/initialized", {})

    async def stop(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self._proc is not None:
            try:
                self._proc.terminate()
                await asyncio.wait_for(self._proc.wait(), timeout=5)
            except (ProcessLookupError, asyncio.TimeoutError):
                with __import__("contextlib").suppress(ProcessLookupError):
                    self._proc.kill()
            self._proc = None
        if self._http is not None:
            await self._http.aclose()
            self._http = None
        self._fail_pending("session closed")
        self._pending.clear()

    # -------------------------------------------------------------- rpc
    async def request(self, method: str, params: dict[str, Any]) -> dict[str, Any]:
        if self._dead is not None:
            # fail FAST and typed: a dead reader can never resolve a
            # future, so parking one would hang to the raw timeout
            raise MCPError(f"session dead: {self._dead}")
        rpc_id = next(self._ids)
        message = {"jsonrpc": "2.0", "id": rpc_id, "method": method, "params": params}
        if self._proc is not None:
            future: asyncio.Future[Any] = asyncio.get_running_loop().create_future()
            self._pending[rpc_id] = future
            await self._write_stdio(message)
            try:
                return await asyncio.wait_for(future, timeout=self._timeout)
            finally:
                self._pending.pop(rpc_id, None)
        # streamable HTTP: one POST per request
        response = await self._http.post(
            self.spec.url,
            json=message,
            headers={"Accept": "application/json, text/event-stream"},
        )
        response.raise_for_status()
        content_type = response.headers.get("content-type", "")
        if content_type.startswith("text/event-stream"):
            for line in response.text.splitlines():
                if line.startswith("data:"):
                    payload = json.loads(line[5:].strip())
                    if payload.get("id") == rpc_id:
                        return self._unwrap(payload)
            raise MCPError(f"no response for id {rpc_id} in event stream")
        return self._unwrap(response.json())

    async def notify(self, method: str, params: dict[str, Any]) -> None:
        message = {"jsonrpc": "2.0", "method": method, "params": params}
        if self._proc is not None:
            await self._write_stdio(message)
        elif self._http is not None:
            try:
                await self._http.post(self.spec.url, json=message)
            except Exception:  # noqa: BLE001 - notifications are best-effort
                logger.debug("mcp notify failed", exc_info=True)

    @staticmethod
    def _unwrap(payload: dict[str, Any]) -> dict[str, Any]:
        if "error" in payload:
            error = payload["error"]
            if isinstance(error, dict):  # hostile servers send anything
                raise MCPError(
                    f"[{error.get('code')}] {error.get('message')}"
                )
            raise MCPError(str(error)[:500])
        result = payload.get("result", {})
        if not isinstance(result, dict):
            raise MCPError(
                f"server returned non-object result: {str(result)[:200]}"
            )
        return result

    # ------------------------------------------------------------- stdio
    async def _write_stdio(self, message: dict[str, Any]) -> None:
        assert self._proc is not None and self._proc.stdin is not None
        self._proc.stdin.write(json.dumps(message).encode() + b"\n")
        await self._proc.stdin.drain()

    def _fail_pending(self, message: str) -> None:
        self._dead = message
        for future in self._pending.values():
            if not future.done():
                future.set_exception(MCPError(message))

    async def _read_stdio(self) -> None:
        assert self._proc is not None and self._proc.stdout is not None
        while True:
            try:
                line = await self._proc.stdout.readline()
            except ValueError:
                # line beyond even the raised stream limit: the framing is
                # lost mid-line — the session cannot recover, fail LOUDLY
                # instead of leaving callers to time out
                logger.error(
                    "mcp %s: oversized line broke stream framing",
                    self.spec.name,
                )
                self._fail_pending("server line exceeded the stream limit")
                return
            if not line:
                logger.warning("mcp %s: server closed stdout", self.spec.name)
                self._fail_pending("server exited")
                return
            try:
                payload = json.loads(line)
            except ValueError:
                logger.debug("mcp %s: non-JSON line ignored", self.spec.name)
                continue
            if not isinstance(payload, dict):
                # a list/str/number frame must not kill the read loop (it
                # used to: .get on a list) — every in-flight AND future
                # request would silently hang to timeout
                logger.debug("mcp %s: non-object frame ignored", self.spec.name)
                continue
            try:
                self._handle_frame(payload)
            except Exception:  # noqa: BLE001 — one hostile frame must not
                logger.exception(  # take down the whole session's reader
                    "mcp %s: frame handling failed", self.spec.name
                )

    def _handle_frame(self, payload: dict[str, Any]) -> None:
        rpc_id = payload.get("id")
        if rpc_id is not None and rpc_id in self._pending:
            future = self._pending[rpc_id]
            if not future.done():
                try:
                    future.set_result(self._unwrap(payload))
                except MCPError as exc:
                    future.set_exception(exc)
        elif payload.get("method") == "notifications/tools/list_changed":
            if self._on_tools_changed is not None:
                result = self._on_tools_changed()
                if asyncio.iscoroutine(result):
                    # offload: never block the receive loop (reference:
                    # mcp_toolbox re-list offload)
                    asyncio.get_running_loop().create_task(result)

    # ------------------------------------------------------------- tools
    async def list_tools(self) -> list[dict[str, Any]]:
        tools: list[dict[str, Any]] = []
        cursor: str | None = None
        seen_cursors: set[str] = set()
        while True:
            params: dict[str, Any] = {"cursor": cursor} if cursor else {}
            result = await self.request("tools/list", params)
            page = result.get("tools", [])
            if isinstance(page, list):
                tools.extend(t for t in page if isinstance(t, dict))
            cursor = result.get("nextCursor")
            if not cursor:
                return tools
            if not isinstance(cursor, str):
                raise MCPError(
                    f"non-string nextCursor: {str(cursor)[:100]}"
                )
            if cursor in seen_cursors or len(seen_cursors) >= 1000:
                # a repeating/unbounded cursor would spin this loop forever
                raise MCPError(
                    f"tools/list pagination did not terminate "
                    f"(cursor {str(cursor)[:60]!r} repeated or >1000 pages)"
                )
            seen_cursors.add(cursor)

    async def call_tool(self, name: str, args: dict[str, Any]) -> Any:
        result = await self.request(
            "tools/call", {"name": name, "arguments": args}
        )
        if result.get("isError"):
            content = result.get("content", [])
            raise MCPError(
                _content_text(content)
                or (str(content)[:200] if content else "tool error")
            )
        content = result.get("content", [])
        structured = result.get("structuredContent")
        if structured is not None:
            return structured
        return _content_text(content)


def _content_text(content: Any) -> str:
    if not isinstance(content, list):
        return ""
    return "\n".join(
        str(c.get("text", "")) for c in content
        if isinstance(c, dict) and c.get("type") == "text"
    )
