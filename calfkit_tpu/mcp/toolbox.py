"""MCPToolboxNode: host an MCP server connection as a node on the mesh.

Reference: calfkit/mcp/mcp_toolbox.py:39-211 + nodes/toolbox.py:62.  The node
lives on ``mcp_server.{name}``; it advertises a CapabilityRecord whose tool
names carry the ``{node_id}__`` namespace prefix (so two toolboxes exposing
the same upstream tool never collide), caches ``tools/list`` (re-listing on
``tools/list_changed`` off the receive loop), and executes incoming
ToolCallRefs by stripping the prefix.

Call-side: ``Toolbox("name")`` / ``Toolboxes(...)`` selectors resolve the
capability view to toolbox records, with ``include=`` as the trust boundary
on which upstream tools the agent may see.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Sequence

from calfkit_tpu import protocol
from calfkit_tpu.models.actions import ReturnCall
from calfkit_tpu.models.capability import CapabilityRecord, ToolDef
from calfkit_tpu.models.error_report import FaultTypes
from calfkit_tpu.models.payload import DataPart, TextPart
from calfkit_tpu.models.tool_dispatch import ToolBinding
from calfkit_tpu.mcp.transport import MCPServerSpec, MCPSession
from calfkit_tpu.nodes.base import BaseNodeDef, NodeRunContext, handler
from calfkit_tpu.nodes.tool import ToolNodeDef

logger = logging.getLogger(__name__)

NAMESPACE_SEP = "__"


class MCPToolboxNode(BaseNodeDef):
    kind = "toolbox"

    def __init__(self, spec: MCPServerSpec, **seams: Any):
        super().__init__(spec.name, **seams)
        self.spec = spec
        self._session: MCPSession | None = None
        self._tools: list[dict[str, Any]] = []
        self._list_lock = asyncio.Lock()

    # ------------------------------------------------------------- topics
    def input_topics(self) -> list[str]:
        return [protocol.toolbox_input_topic(self.name)]

    def return_topic(self) -> str:
        return protocol.require_topic_safe(
            f"mcp_server.{self.name}.private.return"
        )

    def publish_topic(self) -> str | None:
        return protocol.toolbox_publish_topic(self.name)

    # ----------------------------------------------------------- lifecycle
    async def start_session(self) -> None:
        """Connect + initial tools/list (the worker resource bracket)."""
        self._session = MCPSession(
            self.spec, on_tools_changed=self._relist
        )
        await self._session.start()
        await self._relist()

    async def stop_session(self) -> None:
        if self._session is not None:
            await self._session.stop()
            self._session = None

    async def _relist(self) -> None:
        if self._session is None:
            return
        async with self._list_lock:
            try:
                self._tools = await self._session.list_tools()
                logger.info(
                    "toolbox %s: %d tools listed", self.name, len(self._tools)
                )
            except Exception:  # noqa: BLE001
                logger.warning(
                    "toolbox %s: tools/list failed", self.name, exc_info=True
                )

    # -------------------------------------------------------- control plane
    def namespaced(self, tool_name: str) -> str:
        return f"{self.node_id}{NAMESPACE_SEP}{tool_name}"

    def capability_record(self) -> CapabilityRecord:
        return CapabilityRecord(
            node_id=self.node_id,
            node_kind=self.kind,
            dispatch_topic=protocol.toolbox_input_topic(self.name),
            tools=[
                ToolDef(
                    name=self.namespaced(t["name"]),
                    description=t.get("description", ""),
                    parameters_schema=t.get(
                        "inputSchema", {"type": "object", "properties": {}}
                    ),
                )
                for t in self._tools
            ],
        )

    # ---------------------------------------------------------------- body
    @handler("run")
    async def run(self, ctx: NodeRunContext) -> ReturnCall:
        if self._session is None:
            from calfkit_tpu.exceptions import NodeFaultError
            from calfkit_tpu.models.error_report import ErrorReport

            raise NodeFaultError(
                ErrorReport.build_safe(
                    FaultTypes.LIFECYCLE_ERROR,
                    f"toolbox {self.name} has no live MCP session",
                    node=self.node_id,
                )
            )
        args: dict[str, Any] = {}
        tool_name = ""
        for part in ctx.payload:
            if isinstance(part, DataPart) and isinstance(part.data, dict):
                tool_name = part.data.get("tool_name", "")
                raw = part.data.get("args", {})
                args = raw if isinstance(raw, dict) else {}
                break
        prefix = f"{self.node_id}{NAMESPACE_SEP}"
        upstream = tool_name.removeprefix(prefix)
        result = await self._session.call_tool(upstream, args)
        if isinstance(result, str):
            return ReturnCall(parts=[TextPart(text=result)])
        return ReturnCall(parts=[DataPart(data=result)])


class Toolbox:
    """Selector: every tool of one live toolbox (optionally filtered)."""

    def __init__(self, name: str, *, include: Sequence[str] | None = None):
        protocol.require_topic_safe(name, what="Toolbox name")
        self.name = name
        self.include = set(include) if include is not None else None

    def resolve(self, records: list[CapabilityRecord]) -> list[ToolBinding]:
        node_id = f"toolbox.{self.name}"
        bindings: list[ToolBinding] = []
        for record in records:
            if record.node_id != node_id:
                continue
            for tool in record.tools:
                upstream = tool.name.removeprefix(f"{node_id}{NAMESPACE_SEP}")
                if self.include is not None and upstream not in self.include:
                    continue  # the trust boundary
                bindings.append(
                    ToolBinding(tool=tool, dispatch_topic=record.dispatch_topic)
                )
        return bindings


class Toolboxes:
    """Selector over several toolboxes (reference: nodes/toolbox.py:62)."""

    def __init__(self, *boxes: "Toolbox | str"):
        if not boxes:
            raise ValueError("Toolboxes requires at least one toolbox")
        self.boxes = [b if isinstance(b, Toolbox) else Toolbox(b) for b in boxes]

    def resolve(self, records: list[CapabilityRecord]) -> list[ToolBinding]:
        bindings: list[ToolBinding] = []
        for box in self.boxes:
            bindings.extend(box.resolve(records))
        return bindings


def mixed_tools(*specs: Any):
    """Combine ToolNodeDefs / Tools / Toolbox(es) into one resolvable spec."""

    class _Mixed:
        def resolve(self, records: list[CapabilityRecord]) -> list[ToolBinding]:
            from calfkit_tpu.nodes.tool import eager_tools

            bindings: list[ToolBinding] = []
            node_defs = [s for s in specs if isinstance(s, ToolNodeDef)]
            bindings.extend(eager_tools(*node_defs))
            for spec in specs:
                if hasattr(spec, "resolve") and not isinstance(spec, ToolNodeDef):
                    bindings.extend(spec.resolve(records))
            return bindings

    return _Mixed()
