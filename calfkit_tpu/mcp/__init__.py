"""MCP toolbox support: host an MCP server connection as a mesh node.

The MCP python SDK is not a dependency — :mod:`calfkit_tpu.mcp.transport`
implements the minimal JSON-RPC client (stdio + streamable HTTP) the toolbox
needs: initialize, tools/list, tools/call, and list_changed notifications.
"""

from calfkit_tpu.mcp.toolbox import MCPToolboxNode, Toolbox, Toolboxes
from calfkit_tpu.mcp.transport import MCPServerSpec, MCPSession

__all__ = ["MCPServerSpec", "MCPSession", "MCPToolboxNode", "Toolbox", "Toolboxes"]
