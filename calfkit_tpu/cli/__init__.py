"""The ``ck`` command-line interface (reference: calfkit/cli/, SURVEY.md §1
layer 10).  Subcommands land as their subsystems do: ``run``, ``dev``,
``chat``, ``topics``."""
