"""Dev-loop state: the managed broker and detached agent daemons.

Reference anchors: connect-or-spawn with a spawn-race file lock
(/root/reference/calfkit/cli/_dev_broker.py:1-22) and detached agent
daemons with status/stop/down (/root/reference/calfkit/cli/_dev_agents.py,
cli/dev.py:41-51).

All state lives under ``$CALFKIT_DEV_DIR`` (default ``~/.calfkit_tpu/dev``):
``broker.json`` + ``broker.lock`` for the managed meshd, and
``agents/<name>.json`` + ``agents/<name>.log`` per detached daemon.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass
from pathlib import Path

DEFAULT_DEV_PORT = 19092


def dev_dir() -> Path:
    root = os.environ.get("CALFKIT_DEV_DIR") or os.path.expanduser(
        "~/.calfkit_tpu/dev"
    )
    path = Path(root)
    (path / "agents").mkdir(parents=True, exist_ok=True)
    return path


def _pid_alive(pid: int) -> bool:
    """Liveness that treats zombies as dead (a spawner that dropped its
    Popen handle never reaps; ``os.kill(pid, 0)`` still succeeds)."""
    with contextlib.suppress(ChildProcessError, OSError):
        os.waitpid(pid, os.WNOHANG)  # reap if it's our own child
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    with contextlib.suppress(OSError, IndexError):
        stat = Path(f"/proc/{pid}/stat").read_text()
        if stat.rsplit(")", 1)[1].split()[0] == "Z":
            return False
    return True


def _pid_is_ours(pid: int, needle: str) -> bool:
    """Never signal a recycled pid: the process must still look like the
    one this registry started."""
    with contextlib.suppress(OSError):
        cmdline = Path(f"/proc/{pid}/cmdline").read_bytes().replace(b"\0", b" ")
        return needle.encode() in cmdline
    return False


def _port_open(port: int, timeout: float = 0.5) -> bool:
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True
    except OSError:
        return False


# --------------------------------------------------------------------------- #
# broker: connect-or-spawn with a spawn-race file lock
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class BrokerInfo:
    port: int
    pid: int | None  # None = pre-existing broker we merely connected to
    spawned: bool

    @property
    def url(self) -> str:
        return f"tcp://127.0.0.1:{self.port}"


def ensure_broker(port: int = DEFAULT_DEV_PORT) -> BrokerInfo:
    """Connect to a live dev broker, or spawn one — exactly one, even when
    multiple ``ck dev`` invocations race (the reference's file-lock
    discipline, cli/_dev_broker.py:1-22)."""
    if _port_open(port):
        return BrokerInfo(port=port, pid=_read_broker_pid(port), spawned=False)
    lock_path = dev_dir() / "broker.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)  # losers wait here while one spawns
        try:
            if _port_open(port):  # the winner got it up while we waited
                return BrokerInfo(
                    port=port, pid=_read_broker_pid(port), spawned=False
                )
            from calfkit_tpu.mesh.tcp import spawn_meshd

            # own session: a ctrl-c aimed at the CLI must not take the
            # broker (daemons pointed at it) down with it
            proc = spawn_meshd(port, start_new_session=True)
            (dev_dir() / "broker.json").write_text(
                json.dumps({"port": port, "pid": proc.pid})
            )
            return BrokerInfo(port=port, pid=proc.pid, spawned=True)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _read_broker_pid(port: int) -> int | None:
    with contextlib.suppress(Exception):
        meta = json.loads((dev_dir() / "broker.json").read_text())
        if meta.get("port") == port and _pid_alive(meta.get("pid", -1)):
            return int(meta["pid"])
    return None


def broker_status(port: int = DEFAULT_DEV_PORT) -> dict:
    return {
        "port": port,
        "up": _port_open(port),
        "pid": _read_broker_pid(port),
    }


def stop_broker(port: int = DEFAULT_DEV_PORT) -> bool:
    """Stop the MANAGED broker (one we spawned and recorded); a broker this
    registry doesn't own — or a recycled pid — is left alone."""
    pid = _read_broker_pid(port)
    if pid is None:
        return False
    if _pid_is_ours(pid, "meshd"):
        with contextlib.suppress(ProcessLookupError):
            os.kill(pid, signal.SIGTERM)
        for _ in range(50):
            if not _pid_alive(pid):
                break
            time.sleep(0.1)
    with contextlib.suppress(FileNotFoundError):
        (dev_dir() / "broker.json").unlink()
    return True


# --------------------------------------------------------------------------- #
# detached agent daemons
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class DaemonInfo:
    name: str
    pid: int
    specs: list[str]
    mesh_url: str
    log_path: str

    @property
    def alive(self) -> bool:
        return _pid_alive(self.pid)


def _daemon_meta(name: str) -> Path:
    return dev_dir() / "agents" / f"{name}.json"


def spawn_daemon(
    name: str, specs: list[str], mesh_url: str
) -> DaemonInfo:
    """Detach a ``ck run`` worker serving ``specs`` against ``mesh_url``.

    Guarded by a per-name file lock (two terminals racing the same name
    must not leave an untracked second worker) and a short post-spawn
    liveness check (an immediately-crashing daemon is reported, not
    recorded as 'up')."""
    lock_path = dev_dir() / "agents" / f"{name}.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if (existing := get_daemon(name)) is not None and existing.alive:
                raise RuntimeError(
                    f"daemon {name!r} already running (pid {existing.pid})"
                )
            log_path = dev_dir() / "agents" / f"{name}.log"
            log = open(log_path, "ab")
            specs = [_absolutize(spec) for spec in specs]
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "calfkit_tpu.cli.main", "run",
                    *specs, "--mesh", mesh_url,
                ],
                stdout=log,
                stderr=log,
                stdin=subprocess.DEVNULL,
                start_new_session=True,  # survives the spawning terminal
            )
            log.close()
            # wait for the child's startup verdict: "serving" in the log
            # (ck run prints it once nodes load) or an early exit.  Bounded
            # so a pathological environment can't hang the CLI.
            log_start = log_path.stat().st_size if log_path.exists() else 0
            for _ in range(80):
                time.sleep(0.1)
                if proc.poll() is not None:
                    tail = ""
                    with contextlib.suppress(OSError):
                        tail = log_path.read_bytes()[-500:].decode(
                            errors="replace"
                        )
                    raise RuntimeError(
                        f"daemon {name!r} exited during startup "
                        f"(code {proc.returncode}); log tail:\n{tail}"
                    )
                with contextlib.suppress(OSError):
                    new = log_path.read_bytes()[log_start:]
                    if b"serving" in new:
                        break
            info = DaemonInfo(
                name=name, pid=proc.pid, specs=list(specs),
                mesh_url=mesh_url, log_path=str(log_path),
            )
            _daemon_meta(name).write_text(json.dumps(info.__dict__))
            return info
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _absolutize(spec: str) -> str:
    """File-based specs must survive the daemon's different cwd."""
    from calfkit_tpu.cli._common import is_file_spec

    if ":" not in spec:  # bare file spec (collect-all grammar)
        return str(Path(spec).resolve()) if is_file_spec(spec) else spec
    module_part, _, attr = spec.rpartition(":")
    if module_part and is_file_spec(module_part):
        return f"{Path(module_part).resolve()}:{attr}"
    return spec


def get_daemon(name: str) -> DaemonInfo | None:
    with contextlib.suppress(Exception):
        return DaemonInfo(**json.loads(_daemon_meta(name).read_text()))
    return None


def list_daemons() -> list[DaemonInfo]:
    out = []
    for meta in sorted((dev_dir() / "agents").glob("*.json")):
        with contextlib.suppress(Exception):
            out.append(DaemonInfo(**json.loads(meta.read_text())))
    return out


def stop_daemon(name: str, *, timeout: float = 10.0) -> bool:
    info = get_daemon(name)
    if info is None:
        return False
    # recycled-pid guard: only signal a process that is still OUR daemon
    if info.alive and _pid_is_ours(info.pid, "calfkit_tpu"):
        with contextlib.suppress(ProcessLookupError):
            os.kill(info.pid, signal.SIGTERM)
        deadline = time.time() + timeout
        while time.time() < deadline and _pid_alive(info.pid):
            time.sleep(0.1)
        if _pid_alive(info.pid):
            with contextlib.suppress(ProcessLookupError):
                os.kill(info.pid, signal.SIGKILL)
    with contextlib.suppress(FileNotFoundError):
        _daemon_meta(name).unlink()
    return True
