"""Dev-loop state: the managed broker and detached agent daemons.

Reference anchors: connect-or-spawn with a spawn-race file lock
(/root/reference/calfkit/cli/_dev_broker.py:1-22) and detached agent
daemons with status/stop/down (/root/reference/calfkit/cli/_dev_agents.py,
cli/dev.py:41-51).

All state lives under ``$CALFKIT_DEV_DIR`` (default ``~/.calfkit_tpu/dev``):
``broker.json`` + ``broker.lock`` for the managed meshd, and
``agents/<name>.json`` + ``agents/<name>.log`` per detached daemon.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass
from pathlib import Path

# the managed dev brokers: meshd (native line protocol) and kafkad (the
# real Kafka wire protocol — closest to the reference's bundled Tansu
# dev broker, which is itself Kafka-compatible)
logger = logging.getLogger(__name__)

BROKER_KINDS = {
    "meshd": {"default_port": 19092, "scheme": "tcp"},
    "kafkad": {"default_port": 19392, "scheme": "kafka+wire"},
}


def default_port(kind: str = "meshd") -> int:
    return BROKER_KINDS[kind]["default_port"]


def dev_dir() -> Path:
    root = os.environ.get("CALFKIT_DEV_DIR") or os.path.expanduser(
        "~/.calfkit_tpu/dev"
    )
    path = Path(root)
    (path / "agents").mkdir(parents=True, exist_ok=True)
    return path


def _pid_alive(pid: int) -> bool:
    """Liveness that treats zombies as dead (a spawner that dropped its
    Popen handle never reaps; ``os.kill(pid, 0)`` still succeeds)."""
    with contextlib.suppress(ChildProcessError, OSError):
        os.waitpid(pid, os.WNOHANG)  # reap if it's our own child
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    with contextlib.suppress(OSError, IndexError):
        stat = Path(f"/proc/{pid}/stat").read_text()
        if stat.rsplit(")", 1)[1].split()[0] == "Z":
            return False
    return True


def _pid_is_ours(pid: int, needle: str) -> bool:
    """Never signal a recycled pid: the process must still look like the
    one this registry started."""
    with contextlib.suppress(OSError):
        cmdline = Path(f"/proc/{pid}/cmdline").read_bytes().replace(b"\0", b" ")
        return needle.encode() in cmdline
    return False


def _port_open(port: int, timeout: float = 0.5) -> bool:
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True
    except OSError:
        return False


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes (recv may legally return partial reads)."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return buf
        buf += chunk
    return buf


def _probe_kind(port: int, kind: str, timeout: float = 0.5) -> bool:
    """Protocol-aware liveness: an open port is only 'our broker' if it
    answers the kind's own protocol (a meshd squatting the port must not
    be claimed as a kafkad and vice versa)."""
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
            s.settimeout(timeout)
            if kind == "meshd":
                s.sendall(b"PING\n")
                return _recv_exact(s, 4) == b"PONG"
            # kafkad: ApiVersions v0 (api_key 18) with correlation id 7
            req = (b"\x00\x12" b"\x00\x00" b"\x00\x00\x00\x07" b"\xff\xff")
            s.sendall(len(req).to_bytes(4, "big") + req)
            header = _recv_exact(s, 8)
            return (
                len(header) == 8
                and int.from_bytes(header[4:8], "big") == 7
            )
    except OSError:
        return False


# --------------------------------------------------------------------------- #
# broker: connect-or-spawn with a spawn-race file lock
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class BrokerInfo:
    port: int
    pid: int | None  # None = pre-existing broker we merely connected to
    spawned: bool
    kind: str = "meshd"

    @property
    def url(self) -> str:
        scheme = BROKER_KINDS[self.kind]["scheme"]
        return f"{scheme}://127.0.0.1:{self.port}"


def _broker_meta(kind: str) -> Path:
    # meshd keeps the legacy filename so existing dev state stays valid
    name = "broker.json" if kind == "meshd" else f"broker-{kind}.json"
    return dev_dir() / name


def ensure_broker(
    port: int | None = None, kind: str = "meshd", *,
    durable: "bool | None" = None,
) -> BrokerInfo:
    """Connect to a live dev broker, or spawn one — exactly one, even when
    multiple ``ck dev`` invocations race (the reference's file-lock
    discipline, cli/_dev_broker.py:1-22)."""
    if kind not in BROKER_KINDS:
        raise ValueError(f"unknown broker kind {kind!r}")
    if port is None:
        port = default_port(kind)
    def _connected() -> BrokerInfo:
        # warn only on a POSITIVE non-durable record: no record at all
        # means unknown provenance (racing sibling mid-meta-write) and a
        # spurious warning would be a lie
        if durable and _recorded_durable(port, kind) is False:
            logger.warning(
                "a NON-durable %s broker is already up on port %d; "
                "--durable has no effect until it is restarted "
                "(ck dev stop, then ck dev mesh --kafka --durable)",
                kind, port,
            )
        return BrokerInfo(
            port=port, pid=_read_broker_pid(port, kind), spawned=False,
            kind=kind,
        )

    if _probe_kind(port, kind):
        return _connected()
    if durable is None:
        # unstated durability INHERITS what this registry last spawned on
        # the port — `ck dev serve --kafka` must not silently demote a
        # broker the user created with --durable
        durable = bool(_recorded_durable(port, kind))
    if _port_open(port):
        # something is listening but the protocol probe above missed it.
        # That is EITHER a foreign listener, or a broker another racer
        # spawned between our two checks (bind happens before the probe
        # endpoint answers) — re-probe briefly before declaring foreign,
        # else a concurrent `ck dev` race misclassifies its sibling's
        # fresh broker and errors spuriously.
        for _ in range(10):
            # short probe timeout: a FOREIGN listener never answers, and
            # this path must stay a quick error (~1s), while a sibling's
            # fresh broker answers within the first try or two
            if _probe_kind(port, kind, timeout=0.1):
                return _connected()
            if not _port_open(port):
                break  # listener vanished: fall through to the spawn path
            time.sleep(0.05)
        else:
            # consistently open but never speaks our protocol: foreign —
            # claiming it would point daemons' wire clients at the wrong
            # protocol
            raise RuntimeError(
                f"port {port} is occupied by something that does not speak "
                f"the {kind} protocol — pick another --port"
            )
    lock_path = dev_dir() / f"broker-{kind}.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)  # losers wait here while one spawns
        try:
            if _probe_kind(port, kind):  # the winner got it up while we waited
                return _connected()
            if kind == "kafkad":
                from calfkit_tpu.mesh.kafka_wire import spawn_kafkad

                kwargs = {}
                if durable:
                    # per-PORT WAL dir: two brokers must never share a log
                    wal_dir = dev_dir() / f"kafkad-wal-{port}"
                    wal_dir.mkdir(parents=True, exist_ok=True)
                    kwargs["log_dir"] = str(wal_dir)

                def spawn(p, *, start_new_session=False):
                    return spawn_kafkad(
                        p, start_new_session=start_new_session, **kwargs
                    )
            else:
                from calfkit_tpu.mesh.tcp import spawn_meshd as spawn

            # own session: a ctrl-c aimed at the CLI must not take the
            # broker (daemons pointed at it) down with it
            proc = spawn(port, start_new_session=True)
            _broker_meta(kind).write_text(
                json.dumps({"port": port, "pid": proc.pid, "kind": kind,
                            "durable": bool(durable)})
            )
            return BrokerInfo(port=port, pid=proc.pid, spawned=True, kind=kind)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _recorded_durable(port: int, kind: str) -> "bool | None":
    """True/False when this registry recorded the port's broker; None
    when there is no record (unknown provenance — e.g. a sibling racer's
    broker whose meta isn't written yet)."""
    with contextlib.suppress(Exception):
        meta = json.loads(_broker_meta(kind).read_text())
        if meta.get("port") == port:
            return bool(meta.get("durable"))
    return None


def _read_broker_pid(port: int, kind: str = "meshd") -> int | None:
    with contextlib.suppress(Exception):
        meta = json.loads(_broker_meta(kind).read_text())
        if meta.get("port") == port and _pid_alive(meta.get("pid", -1)):
            return int(meta["pid"])
    return None


def recorded_port(kind: str) -> int | None:
    """The port this registry last spawned a ``kind`` broker on."""
    with contextlib.suppress(Exception):
        return int(json.loads(_broker_meta(kind).read_text())["port"])
    return None


def broker_status(port: int | None = None, kind: str = "meshd") -> dict:
    if port is None:
        port = recorded_port(kind) or default_port(kind)
    scheme = BROKER_KINDS[kind]["scheme"]
    return {
        "port": port,
        "kind": kind,
        "url": f"{scheme}://127.0.0.1:{port}",
        "up": _probe_kind(port, kind),
        "pid": _read_broker_pid(port, kind),
    }


def stop_broker(port: int | None = None, kind: str = "meshd") -> bool:
    """Stop the MANAGED broker (one we spawned and recorded); a broker this
    registry doesn't own — or a recycled pid — is left alone.  ``port=None``
    targets whatever port the registry recorded for this kind."""
    if port is None:
        port = recorded_port(kind) or default_port(kind)
    pid = _read_broker_pid(port, kind)
    if pid is None:
        return False
    if _pid_is_ours(pid, kind):
        with contextlib.suppress(ProcessLookupError):
            os.kill(pid, signal.SIGTERM)
        for _ in range(50):
            if not _pid_alive(pid):
                break
            time.sleep(0.1)
    with contextlib.suppress(FileNotFoundError):
        _broker_meta(kind).unlink()
    return True


# --------------------------------------------------------------------------- #
# detached agent daemons
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class DaemonInfo:
    name: str
    pid: int
    specs: list[str]
    mesh_url: str
    log_path: str

    @property
    def alive(self) -> bool:
        return _pid_alive(self.pid)


def _daemon_meta(name: str) -> Path:
    return dev_dir() / "agents" / f"{name}.json"


def spawn_daemon(
    name: str, specs: list[str], mesh_url: str
) -> DaemonInfo:
    """Detach a ``ck run`` worker serving ``specs`` against ``mesh_url``.

    Guarded by a per-name file lock (two terminals racing the same name
    must not leave an untracked second worker) and a short post-spawn
    liveness check (an immediately-crashing daemon is reported, not
    recorded as 'up')."""
    lock_path = dev_dir() / "agents" / f"{name}.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if (existing := get_daemon(name)) is not None and existing.alive:
                raise RuntimeError(
                    f"daemon {name!r} already running (pid {existing.pid})"
                )
            log_path = dev_dir() / "agents" / f"{name}.log"
            log = open(log_path, "ab")
            specs = [_absolutize(spec) for spec in specs]
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "calfkit_tpu.cli.main", "run",
                    *specs, "--mesh", mesh_url,
                ],
                stdout=log,
                stderr=log,
                stdin=subprocess.DEVNULL,
                start_new_session=True,  # survives the spawning terminal
            )
            log.close()
            # wait for the child's startup verdict: "serving" in the log
            # (ck run prints it once nodes load) or an early exit.  Bounded
            # so a pathological environment can't hang the CLI.
            log_start = log_path.stat().st_size if log_path.exists() else 0
            for _ in range(80):
                time.sleep(0.1)
                if proc.poll() is not None:
                    tail = ""
                    with contextlib.suppress(OSError):
                        tail = log_path.read_bytes()[-500:].decode(
                            errors="replace"
                        )
                    raise RuntimeError(
                        f"daemon {name!r} exited during startup "
                        f"(code {proc.returncode}); log tail:\n{tail}"
                    )
                with contextlib.suppress(OSError):
                    new = log_path.read_bytes()[log_start:]
                    if b"serving" in new:
                        break
            info = DaemonInfo(
                name=name, pid=proc.pid, specs=list(specs),
                mesh_url=mesh_url, log_path=str(log_path),
            )
            _daemon_meta(name).write_text(json.dumps(info.__dict__))
            return info
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _absolutize(spec: str) -> str:
    """File-based specs must survive the daemon's different cwd."""
    from calfkit_tpu.cli._common import is_file_spec

    if ":" not in spec:  # bare file spec (collect-all grammar)
        return str(Path(spec).resolve()) if is_file_spec(spec) else spec
    module_part, _, attr = spec.rpartition(":")
    if module_part and is_file_spec(module_part):
        return f"{Path(module_part).resolve()}:{attr}"
    return spec


def get_daemon(name: str) -> DaemonInfo | None:
    with contextlib.suppress(Exception):
        return DaemonInfo(**json.loads(_daemon_meta(name).read_text()))
    return None


def list_daemons() -> list[DaemonInfo]:
    out = []
    for meta in sorted((dev_dir() / "agents").glob("*.json")):
        with contextlib.suppress(Exception):
            out.append(DaemonInfo(**json.loads(meta.read_text())))
    return out


def stop_daemon(name: str, *, timeout: float = 10.0) -> bool:
    info = get_daemon(name)
    if info is None:
        return False
    # recycled-pid guard: only signal a process that is still OUR daemon
    if info.alive and _pid_is_ours(info.pid, "calfkit_tpu"):
        with contextlib.suppress(ProcessLookupError):
            os.kill(info.pid, signal.SIGTERM)
        deadline = time.time() + timeout
        while time.time() < deadline and _pid_alive(info.pid):
            time.sleep(0.1)
        if _pid_alive(info.pid):
            with contextlib.suppress(ProcessLookupError):
                os.kill(info.pid, signal.SIGKILL)
    with contextlib.suppress(FileNotFoundError):
        _daemon_meta(name).unlink()
    return True
