"""``ck`` entry point."""

from __future__ import annotations

import click

import calfkit_tpu


@click.group(help="calfkit-tpu: TPU-native agent-mesh framework CLI")
@click.version_option(calfkit_tpu.__version__, prog_name="ck")
def main() -> None:
    pass


def _register() -> None:
    """Attach subcommand groups that have landed.

    Absence is checked via ``find_spec`` so a genuine import failure inside a
    present module propagates instead of silently dropping the subcommand.
    """
    from importlib import import_module
    from importlib.util import find_spec

    for module_name, attr in (
        ("calfkit_tpu.cli.run", "run_command"),
        ("calfkit_tpu.cli.dev", "dev_group"),
        ("calfkit_tpu.cli.chat", "chat_command"),
        ("calfkit_tpu.cli.topics", "topics_group"),
        ("calfkit_tpu.cli.obs", "trace_command"),
        ("calfkit_tpu.cli.obs", "stats_command"),
        ("calfkit_tpu.cli.obs", "fleet_command"),
        ("calfkit_tpu.cli.obs", "leases_command"),
        ("calfkit_tpu.cli.obs", "timeline_command"),
        ("calfkit_tpu.cli.obs", "slo_command"),
        ("calfkit_tpu.cli.obs", "capacity_command"),
        ("calfkit_tpu.cli.sim", "sim_command"),
    ):
        if find_spec(module_name) is None:
            continue
        main.add_command(getattr(import_module(module_name), attr))


_register()


if __name__ == "__main__":
    main()
