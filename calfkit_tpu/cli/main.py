"""``ck`` entry point."""

from __future__ import annotations

import click

import calfkit_tpu


@click.group(help="calfkit-tpu: TPU-native agent-mesh framework CLI")
@click.version_option(calfkit_tpu.__version__, prog_name="ck")
def main() -> None:
    pass


def _register() -> None:
    """Attach subcommand groups; each is optional while subsystems land."""
    try:
        from calfkit_tpu.cli.run import run_command

        main.add_command(run_command)
    except ImportError:
        pass
    try:
        from calfkit_tpu.cli.dev import dev_group

        main.add_command(dev_group)
    except ImportError:
        pass
    try:
        from calfkit_tpu.cli.chat import chat_command

        main.add_command(chat_command)
    except ImportError:
        pass
    try:
        from calfkit_tpu.cli.topics import topics_group

        main.add_command(topics_group)
    except ImportError:
        pass


_register()


if __name__ == "__main__":
    main()
