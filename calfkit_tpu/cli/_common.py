"""Shared CLI helpers: node loading, mesh resolution."""

from __future__ import annotations

import importlib
import importlib.util
import sys
from pathlib import Path
from typing import Any

import click


def is_file_spec(module_part: str) -> bool:
    """The single authority for file-vs-module spec classification —
    shared by the loader, the reload watcher, and daemon absolutization."""
    return module_part.endswith(".py") or "/" in module_part


def load_object(spec: str) -> Any:
    """Load ``module:attr`` / ``path/to/file.py:attr``, or — with no
    ``:attr`` — every node defined at the module's top level (node files
    need no boilerplate; the reference's ``ck run`` spec grammar)."""
    if ":" in spec:
        module_part, attr = spec.rsplit(":", 1)
    else:
        module_part, attr = spec, None
    if is_file_spec(module_part):
        path = Path(module_part).resolve()
        if not path.exists():
            raise click.ClickException(f"no such file: {path}")
        sys.path.insert(0, str(path.parent))
        spec_obj = importlib.util.spec_from_file_location(path.stem, path)
        module = importlib.util.module_from_spec(spec_obj)
        sys.modules[path.stem] = module
        spec_obj.loader.exec_module(module)
    else:
        try:
            module = importlib.import_module(module_part)
        except ModuleNotFoundError as exc:
            if exc.name and not (
                module_part == exc.name
                or module_part.startswith(exc.name + ".")
            ):
                # the spec resolved; one of ITS imports is missing — name
                # the real missing dependency, not the spec grammar
                raise click.ClickException(
                    f"error importing {module_part!r}: {exc}"
                ) from exc
            raise click.ClickException(
                f"cannot import {module_part!r} "
                "(specs are 'module:attr', 'file.py:attr', or a bare "
                "'file.py' to collect its nodes)"
            ) from exc
    if attr is None:
        from calfkit_tpu.nodes.base import BaseNodeDef

        found = [
            value
            for name, value in vars(module).items()
            if not name.startswith("_") and isinstance(value, BaseNodeDef)
        ]
        # dedupe while preserving definition order (an attr alias like
        # ``TEAM = [a, b]`` is a list, not a BaseNodeDef — untouched here)
        unique: list[Any] = []
        for node in found:
            if all(node is not seen for seen in unique):
                unique.append(node)
        if not unique:
            raise click.ClickException(
                f"{module_part!r} defines no nodes at top level; "
                "name one with 'module:attr'"
            )
        return unique
    try:
        return getattr(module, attr)
    except AttributeError as exc:
        raise click.ClickException(
            f"{module_part!r} has no attribute {attr!r}"
        ) from exc


def load_nodes(specs: tuple[str, ...]) -> list[Any]:
    """Load every spec, deduping by ``node_id`` (first-seen order).

    The reference's loader semantics (calfkit/cli/_loader.py:132
    ``dedupe_by_node_id``): a node imported into one spec and also loaded
    from its own file — even as a re-exec'd second instance — is served
    once; two different nodes claiming one name resolve to the first seen.
    """
    nodes: list[Any] = []
    seen: set[str] = set()
    for spec in specs:
        obj = load_object(spec)
        for node in obj if isinstance(obj, (list, tuple)) else [obj]:
            key = getattr(node, "node_id", None)
            if key is not None:
                if key in seen:
                    continue
                seen.add(key)
            nodes.append(node)
    return nodes


def resolve_mesh_for_cli(url: str | None, *, hosts_worker: bool = True) -> Any:
    """CLI flavor of the shared grammar, errors as ClickException.

    ``hosts_worker=True`` (ck run / ck dev run) defaults to memory:// — the
    command hosts the worker in-process, so an isolated mesh is meaningful.
    Worker-less commands (chat, topics) must point at a REAL mesh: memory://
    there would be a silent no-op world.
    """
    from calfkit_tpu.mesh.urls import resolve_mesh

    try:
        transport, _ = resolve_mesh(
            url,
            default="memory://" if hosts_worker else None,
            allow_memory=hosts_worker,
        )
        return transport
    except ValueError as exc:
        raise click.ClickException(str(exc)) from exc
