"""``ck sim`` — render a fleet-simulation report (ISSUE 11).

Reads a ``SIM.json`` produced by ``scripts/perf_gate.py`` (or any
:meth:`calfkit_tpu.sim.report.SimReport.to_json` document) and renders
one row per scenario plus the failed checks, so an operator can read a
CI perf-gate artifact without spelunking JSON.  ``--checks`` expands
every check row; ``--scenario`` filters to one.

The renderer is a pure function over the parsed document
(:func:`render_sim_table`) — tested without a CLI runner, same pattern
as ``render_fleet_table``.
"""

from __future__ import annotations

import json
from typing import Any

import click


def _fmt(value: "Any") -> str:
    if value is None:
        return "-"
    if isinstance(value, bool):
        return "yes" if value else "no"
    if isinstance(value, float):
        return f"{value:.3f}".rstrip("0").rstrip(".")
    return str(value)


def _metric(scenario: "dict[str, Any]", path: str) -> "Any":
    node: Any = scenario.get("metrics", {})
    for part in path.split("."):
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    return node


def render_sim_table(
    document: "dict[str, Any]",
    *,
    show_checks: bool = False,
    only: "str | None" = None,
) -> str:
    """The ``ck sim`` body: one row per scenario, failed checks always
    expanded (a pass/fail table that hides WHY it failed is useless),
    every check expanded with ``show_checks``."""
    scenarios = [
        s
        for s in document.get("scenarios", [])
        if only is None or s.get("name") == only
    ]
    rows: "list[tuple[str, ...]]" = [
        (
            "SCENARIO", "VERDICT", "REPLICAS", "OFFERED", "COMPLETED",
            "FAILED", "SHEDS", "FAILOVERS", "HIT RATE", "SKEW P95",
            "MAKESPAN S",
        )
    ]
    for s in scenarios:
        rows.append(
            (
                str(s.get("name", "?")),
                "pass" if s.get("passed") else "FAIL",
                _fmt(s.get("replicas")),
                _fmt(_metric(s, "requests.offered")),
                _fmt(_metric(s, "requests.completed")),
                _fmt(_metric(s, "requests.failed")),
                _fmt(_metric(s, "shed.sheds")),
                _fmt(_metric(s, "routing.failover_arrivals")),
                _fmt(_metric(s, "prefix.hit_rate")),
                _fmt(_metric(s, "routing.skew_p95_over_mean")),
                _fmt(_metric(s, "time.makespan_s")),
            )
        )
    widths = [
        max(len(row[i]) for row in rows) for i in range(len(rows[0]))
    ]
    lines = [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row))
        .rstrip()
        for row in rows
    ]
    for s in scenarios:
        checks = s.get("checks", [])
        shown = [
            c for c in checks if show_checks or not c.get("passed", True)
        ]
        if not shown:
            continue
        lines.append("")
        lines.append(f"{s.get('name')}:")
        for c in shown:
            mark = "ok  " if c.get("passed") else "FAIL"
            lines.append(
                f"  [{mark}] {c.get('name')}: {c.get('metric')} "
                f"{c.get('op')} {_fmt(c.get('bound'))} "
                f"(got {_fmt(c.get('value'))})"
            )
    capture = document.get("capture") or {}
    suite = document.get("suite", "?")
    verdict = "pass" if document.get("passed") else "FAIL"
    footer = f"suite {suite}: {verdict}"
    if capture.get("captured_at"):
        footer += f"  (captured {capture['captured_at']}"
        if capture.get("wall_s") is not None:
            footer += f", wall {capture['wall_s']}s — not a gated metric"
        footer += ")"
    lines.extend(["", footer])
    return "\n".join(lines)


@click.command(
    "sim",
    help="render a fleet-simulation report (SIM.json from "
         "scripts/perf_gate.py)",
)
@click.option(
    "--path", default="SIM.json", show_default=True,
    help="report file to render",
)
@click.option(
    "--checks", "show_checks", is_flag=True,
    help="expand every check row (failed checks always show)",
)
@click.option(
    "--scenario", "only", default=None,
    help="render one scenario only",
)
def sim_command(path: str, show_checks: bool, only: "str | None") -> None:
    try:
        with open(path) as f:
            document = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        raise click.ClickException(f"cannot read {path}: {exc}") from None
    click.echo(render_sim_table(document, show_checks=show_checks, only=only))
    if not document.get("passed"):
        raise SystemExit(1)
