"""``ck trace`` / ``ck stats`` / ``ck fleet`` / ``ck timeline`` /
``ck run`` / ``ck slo`` — the operator surface.

``ck trace <correlation-id>`` reads the compacted ``mesh.traces`` topic
and prints the run's per-hop waterfall (trace_id equals the correlation
id by client convention, so the id on any log line or client handle is
the lookup key).  ``ck stats`` reads the ``mesh.engine_stats`` directory
and prints a live table of every engine's serving metrics.
``ck fleet`` reads the SAME directory per-instance (ISSUE 7): one row
per replica, with exactly the eligibility signals the fleet router
routes on — readiness, drain state, heartbeat age, queue depth,
shed/expired deltas — so "why is this replica (not) getting traffic"
is answerable from the operator's chair.
``ck timeline <correlation-id>`` reconstructs one request's scheduler
lifecycle — admission → waves → spec/overlap dispatches → retirement →
frees — from an engine flight-recorder dump (same correlation id as the
trace, so a fault report's id works for both commands).
``ck run <run-id>`` (ISSUE 17) stitches ONE logical run's attempts —
every retry/failover/hedge/resume placement recorded on the compacted
``mesh.runs`` table — into a single run-level waterfall, joining each
attempt's spans (``mesh.traces``) and flight-recorder events across
replicas: the view ``ck trace``/``ck timeline`` cannot produce, because
each attempt carries its own correlation id.  ``ck slo`` prints the
per-agent windowed run-level SLO rollups from ``mesh.slo``.
``ck capacity [agent]`` (ISSUE 19) is the HBM page view: per-replica
pool/headroom scalars from the same adverts, then the occupancy
timeline (unicode sparklines) and the page-attribution owner breakdown
from the newest local capacity dump — "who holds this replica's HBM,
and could an admission fit right now".

Rendering is split into pure functions (``render_waterfall`` /
``render_stats_table`` / ``render_fleet_table`` / ``render_timeline`` /
``render_run_timeline`` / ``render_slo_table`` /
``render_capacity_table`` / ``render_capacity_timeline`` /
``render_capacity_breakdown``) so tests cover the formatting without a
mesh.
"""

from __future__ import annotations

import asyncio
import glob
import os
from typing import Iterable

import click

from calfkit_tpu import protocol
from calfkit_tpu.cli._common import resolve_mesh_for_cli
from calfkit_tpu.fleet.registry import DEFAULT_STALE_AFTER
from calfkit_tpu.models.records import (
    ControlPlaneRecord,
    EngineStatsRecord,
    RunRecord,
    SloRollupRecord,
    SpanRecord,
)

_BAR_WIDTH = 32


def _format_table(rows: "list[tuple]") -> str:
    """Shared column-aligned table rendering (stats / fleet / leases —
    one layout authority, not three drifting copies)."""
    widths = [
        max(len(row[i]) for row in rows) for i in range(len(rows[0]))
    ]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in rows
    )


def _depth_of(span: SpanRecord, by_id: dict[str, SpanRecord]) -> int:
    depth = 0
    seen: set[str] = {span.span_id}
    parent = span.parent_span_id
    while parent and parent in by_id and parent not in seen:
        seen.add(parent)
        depth += 1
        parent = by_id[parent].parent_span_id
    return depth


def render_waterfall(spans: "list[SpanRecord]") -> str:
    """The per-hop waterfall: one line per span, bar positioned on the
    trace's wall-clock window, indented by parent depth."""
    if not spans:
        return "no spans"
    by_id = {s.span_id: s for s in spans}
    t0 = min(s.start_s for s in spans)
    t1 = max(s.start_s + s.duration_ms / 1000.0 for s in spans)
    total_ms = max((t1 - t0) * 1000.0, 0.001)
    lines = [
        f"trace {spans[0].trace_id}  —  {len(spans)} spans, "
        f"{total_ms:.1f} ms end-to-end"
    ]
    for span in sorted(spans, key=lambda s: (s.start_s, s.span_id)):
        offset_ms = (span.start_s - t0) * 1000.0
        left = int(offset_ms / total_ms * _BAR_WIDTH)
        left = min(left, _BAR_WIDTH - 1)
        width = max(
            1,
            int((offset_ms + span.duration_ms) / total_ms * _BAR_WIDTH) - left,
        )
        bar = " " * left + "#" * min(width, _BAR_WIDTH - left)
        indent = "  " * _depth_of(span, by_id)
        flag = "" if span.status == "ok" else f"  !{span.status}"
        lines.append(
            f"{offset_ms:9.1f}ms  [{bar:<{_BAR_WIDTH}}] "
            f"{span.duration_ms:9.1f}ms  {indent}{span.name}"
            f"  ({span.emitter or span.kind}){flag}"
        )
    return "\n".join(lines)


def render_stats_table(records: "Iterable[EngineStatsRecord]") -> str:
    """The live engine table: one row per engine-backed node."""
    rows = [
        (
            "NODE", "MODEL", "TOK/S", "OCC", "BATCH OCC", "TOK/DISP",
            "ACTIVE", "SLOTS",
            "DECODED", "TTFT P50/P99 MS", "GAP P99 MS", "WASTE",
            "QUEUE I/B", "SHED", "EXPIRED", "CANCELS", "ORPHANS",
            "FAILOVER/HEDGE",
            "RUNS/ATT", "WEDGE", "FREC APP/DROP",
        )
    ]
    for r in records:
        lat = r.latency_ms or {}
        ttft = (
            f"{lat.get('ttft_p50', 0):.0f}/{lat.get('ttft_p99', 0):.0f}"
            if lat else "-"
        )
        # overlapped execution health: the p99 inter-dispatch device-idle
        # bubble (should sit at ~0 with overlap on) and the pad tokens
        # one-dispatch-late retirement discarded
        gap = (
            f"{lat.get('dispatch_gap_p99', 0):.2f}"
            if "dispatch_gap_p99" in lat else "-"
        )
        waste = (
            str(r.overlap_wasted_tokens) if r.overlap_dispatch else "off"
        )
        # flight-recorder ring accounting: a growing DROP count means the
        # ring is overwriting history faster than anyone dumps it — raise
        # RuntimeConfig.flightrec_events if postmortems come up short
        fr = r.flightrec
        frec = f"{fr.get('appended', 0)}/{fr.get('dropped', 0)}" if fr else "-"
        # overload-protection health: admission sheds (bounded queues are
        # DOING THEIR JOB — a growing SHED under load beats silent
        # queue-wait growth), deadline expiries, and reaped cancels with
        # the mesh-propagated subset in parentheses.  Once any per-class
        # counter is nonzero (ISSUE 20) the cell splits i/b — under the
        # shed-order law the interactive share should stay 0 while batch
        # work remains sheddable, and this column is where that shows
        shed = str(r.shed_requests) if r.max_pending else "off"
        if r.interactive_shed or r.batch_shed:
            shed = f"i{r.interactive_shed}/b{r.batch_shed}"
        expired = str(r.expired_requests)
        if r.interactive_expired or r.batch_expired:
            expired = f"i{r.interactive_expired}/b{r.batch_expired}"
        # per-class queued depth: "-" until either class queues (pre-QoS
        # adverts and idle engines render identically quiet)
        queue_split = (
            f"i{r.interactive_pending}/b{r.batch_pending}"
            if r.interactive_pending or r.batch_pending else "-"
        )
        cancels = (
            f"{r.cancelled_requests}({r.cancel_propagated})"
            if r.cancel_propagated
            else str(r.cancelled_requests)
        )
        # failure recovery (ISSUE 9): arrivals that were failover
        # re-dispatches / hedge duplicates — which replicas absorb
        # recovered work — and the wedge watchdog's state: "WEDGED!"
        # while tripped (requests are being faulted retriable), else
        # lifetime trips (requests faulted in parentheses)
        recovery = f"{r.failover_requests}/{r.hedge_requests}"
        # run-scoped observability (ISSUE 17): run-level arrivals vs
        # every linked placement, counted from the x-mesh-run header —
        # ATT exceeding RUNS is the attempt amplification failover and
        # hedging add on this replica ("-" = no linked arrivals yet)
        runs_att = (
            f"{r.run_requests}/{r.attempt_requests}"
            if r.attempt_requests else "-"
        )
        wedge = (
            "WEDGED!" if r.wedged
            else f"{r.watchdog_trips}({r.watchdog_faulted})"
            if r.watchdog_trips else "-"
        )
        # prefer the per-heartbeat-interval rates: lifetime cumulative
        # tok/s flattens toward the mean (an engine idle for an hour then
        # bursting shows ~0 lifetime) — the window field exists for this
        window = r.window or {}
        tok_s = window.get("tokens_per_second", r.tokens_per_second)
        occupancy = window.get("mean_occupancy", r.mean_occupancy)
        # BATCH OCC: lifetime mean batch occupancy — with ragged waves on
        # it counts absorbed prefill rows as dispatch participants, so
        # this is THE unified-wave fill metric (OCC stays the windowed
        # rate); TOK/DISP is tokens processed (decode + absorbed prefill)
        # per dispatch
        batch_occ = (
            f"{r.mean_occupancy:.2f}"
            + ("*" if r.ragged_waves else "")
        )
        tok_disp = (
            f"{r.tokens_per_dispatch:.1f}" if r.tokens_per_dispatch else "-"
        )
        rows.append(
            (
                r.node_id,
                r.model_name,
                f"{tok_s:.1f}",
                f"{occupancy:.2f}",
                batch_occ,
                tok_disp,
                str(r.active_requests),
                f"{r.max_batch_size - r.free_slots}/{r.max_batch_size}"
                if r.max_batch_size else "-",
                str(r.decode_tokens),
                ttft,
                gap,
                waste,
                queue_split,
                shed,
                expired,
                cancels,
                # caller liveness (ISSUE 10): runs the server-side
                # reaper abandoned because their caller's lease lapsed —
                # nonzero here means dead callers' work is being
                # reclaimed instead of burning TPU time to its deadline
                str(r.orphaned_requests),
                recovery,
                runs_att,
                wedge,
                frec,
            )
        )
    if len(rows) == 1:
        return "no live engines (is a worker with a local model running?)"
    return _format_table(rows)


def render_fleet_table(
    replicas: "Iterable", *, stale_after: float, now: "float | None" = None
) -> str:
    """One row per replica instance: the router's view of the fleet.

    ``ROUTE`` is the verdict the router's eligibility filter returns for
    a NEW run right now — ``yes``, or the FIRST reason the replica is
    skipped (``drain`` / ``stale`` / ``unready`` / ``shared-only``) —
    computed by the SAME :func:`~calfkit_tpu.fleet.registry.
    eligibility_verdict` the router uses, so this table cannot drift
    from actual routing behavior.  When the DEAD-placement law
    (:func:`~calfkit_tpu.fleet.failover.placement_verdict`, ISSUE 9)
    declares the replica dead — stale heartbeat, or unready without
    drain — the verdict renders as ``dead(stale)`` / ``dead(unready)``
    with the last-seen heartbeat age in HB AGE S: runs still placed
    there are being failed over, not just new runs routed away.
    SHED/EXPIRED prefer the per-heartbeat-interval delta (``+n``) over
    lifetime values: what matters for routing is whether a replica is
    shedding NOW.  HEADROOM (ISSUE 19) is the pages an admission could
    still obtain — free-list plus evictable zero-ref cache pages —
    straight from :attr:`~calfkit_tpu.fleet.registry.Replica.
    headroom_pages`, ``-`` when the replica advertises no page pool."""
    from calfkit_tpu import cancellation
    from calfkit_tpu.fleet.failover import placement_verdict
    from calfkit_tpu.fleet.registry import eligibility_verdict

    if now is None:
        now = cancellation.wall_clock()
    rows = [
        (
            "MODEL", "NODE", "INSTANCE", "ROUTE", "READY", "DRAIN",
            "HB AGE S", "DEPTH", "ACTIVE", "PENDING", "SLOTS",
            "HEADROOM", "SHED", "EXPIRED", "TOK/S", "PREFIX HIT",
        )
    ]
    for r in replicas:
        s = r.stats
        age = r.age(now)
        verdict = eligibility_verdict(r, stale_after=stale_after, now=now)
        placement = placement_verdict(r, stale_after=stale_after, now=now)
        if placement != "alive":
            # the dead-placement law outranks the routing verdict: this
            # replica isn't merely skipped for new runs — outstanding
            # placements on it are declared dead and failed over
            verdict = f"dead({placement.partition(':')[2]})"
        window = s.window or {}
        shed = (
            f"+{window['shed_requests']}"
            if "shed_requests" in window else str(s.shed_requests)
        )
        expired = (
            f"+{window['expired_requests']}"
            if "expired_requests" in window else str(s.expired_requests)
        )
        tok_s = window.get("tokens_per_second", s.tokens_per_second)
        rows.append(
            (
                s.model_name,
                s.node_id,
                r.instance_id,
                verdict,
                "y" if s.ready else "n",
                "y" if s.draining else "n",
                f"{age:.1f}",
                str(r.queue_depth),
                str(s.active_requests),
                str(s.pending_requests),
                f"{s.max_batch_size - s.free_slots}/{s.max_batch_size}"
                if s.max_batch_size else "-",
                # pages an admission could still obtain (ISSUE 19) —
                # "-" when the replica advertises no page pool (dense
                # layout, pre-capacity record): no signal must not read
                # as a full replica
                str(r.headroom_pages)
                if getattr(r, "headroom_pages", None) is not None
                else "-",
                shed,
                expired,
                f"{tok_s:.1f}",
                # "-" ONLY when the replica shows no sign of a prefix
                # cache at all: a momentarily-evicted cache (0 resident
                # pages, nonzero lifetime hits) must not render like
                # caching-disabled
                str(s.prefix_hits)
                if (
                    s.prefix_cached_pages or s.prefix_hits
                    or s.prefix_reused_tokens
                )
                else "-",
            )
        )
    if len(rows) == 1:
        return (
            "no advertised replicas (is a worker with a local model "
            "running, and the control plane enabled?)"
        )
    return _format_table(rows)


def _parse_spans(items: dict[str, bytes], correlation_id: str) -> list[SpanRecord]:
    spans: list[SpanRecord] = []
    prefix = f"{correlation_id}/"
    for key, value in items.items():
        if not key.startswith(prefix):
            continue
        try:
            spans.append(SpanRecord.from_wire(value))
        except Exception:  # noqa: BLE001 - skip undecodable records, keep the rest
            continue
    return spans


def _parse_engine_stats(items: dict[str, bytes]) -> list[EngineStatsRecord]:
    out: list[EngineStatsRecord] = []
    for value in items.values():
        try:
            wrapped = ControlPlaneRecord.from_wire(value)
            out.append(EngineStatsRecord.model_validate(wrapped.record))
        except Exception:  # noqa: BLE001
            continue
    return sorted(out, key=lambda r: r.node_id)


@click.command("trace", help="print a run's per-hop trace waterfall")
@click.argument("correlation_id")
@click.option("--mesh", "mesh_url", default=None, help="mesh url (or $CALFKIT_MESH_URL)")
@click.option("--timeout", default=15.0, show_default=True, help="catch-up timeout (s)")
def trace_command(correlation_id: str, mesh_url: str | None, timeout: float) -> None:
    async def main() -> None:
        mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
        await mesh.start()
        try:
            reader = mesh.table_reader(protocol.TRACES_TOPIC)
            await reader.start(timeout=timeout)
            await reader.barrier(timeout=timeout)
            spans = _parse_spans(reader.items(), correlation_id)
            await reader.stop()
        finally:
            await mesh.stop()
        if not spans:
            raise click.ClickException(
                f"no spans for {correlation_id!r} on {protocol.TRACES_TOPIC} "
                "(run too old for compaction, or tracing not flowing?)"
            )
        click.echo(render_waterfall(spans))

    asyncio.run(main())


@click.command("stats", help="print live engine serving metrics")
@click.option("--mesh", "mesh_url", default=None, help="mesh url (or $CALFKIT_MESH_URL)")
@click.option("--timeout", default=15.0, show_default=True, help="catch-up timeout (s)")
def stats_command(mesh_url: str | None, timeout: float) -> None:
    async def main() -> None:
        mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
        await mesh.start()
        try:
            reader = mesh.table_reader(protocol.ENGINE_STATS_TOPIC)
            await reader.start(timeout=timeout)
            await reader.barrier(timeout=timeout)
            records = _parse_engine_stats(reader.items())
            await reader.stop()
        finally:
            await mesh.stop()
        click.echo(render_stats_table(records))

    asyncio.run(main())


@click.command(
    "fleet",
    help="print the live replica fleet per model: readiness, drain, "
    "heartbeat age, queue depth — the router's eligibility view",
)
@click.option("--mesh", "mesh_url", default=None, help="mesh url (or $CALFKIT_MESH_URL)")
@click.option("--timeout", default=15.0, show_default=True, help="catch-up timeout (s)")
@click.option(
    "--stale-after",
    # the router's own default, imported so tuning it cannot silently
    # desynchronize the operator table's ROUTE verdicts from routing
    default=DEFAULT_STALE_AFTER,
    show_default=True,
    help="heartbeat age (s) past which a replica is routed around "
    "(match the router's setting)",
)
def fleet_command(
    mesh_url: str | None, timeout: float, stale_after: float
) -> None:
    from calfkit_tpu.fleet.registry import parse_replicas

    async def main() -> None:
        mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
        await mesh.start()
        try:
            reader = mesh.table_reader(protocol.ENGINE_STATS_TOPIC)
            await reader.start(timeout=timeout)
            await reader.barrier(timeout=timeout)
            replicas = parse_replicas(reader.items())
            await reader.stop()
        finally:
            await mesh.stop()
        replicas.sort(key=lambda r: (r.model_name, r.key))
        click.echo(render_fleet_table(replicas, stale_after=stale_after))

    asyncio.run(main())


# ----------------------------------------------------------------- leases
def render_leases_table(
    items: "dict[str, bytes]", *, now: "float | None" = None
) -> str:
    """The caller-liveness view (ISSUE 10): one row per lease on the
    compacted ``mesh.caller_liveness`` table — lease id, beat age, TTL,
    and the verdict the engines' orphan reaper would reach RIGHT NOW
    (``live`` / ``lapsed``), computed by the same lapse law
    (``age > ttl``) so the operator table cannot drift from reaping.

    Rows sort by beat age DESCENDING (ISSUE 20): the silent leases rank
    first — under overload they are exactly the callers the engine's
    lease-aware shed evicts first, so the top of this table is the shed
    order.  A still-live lease past 80% of its TTL is flagged
    ``live (lapsing)``: one more missed beat window and its runs are
    orphan-reap candidates.  Undecodable rows sink to the bottom."""
    import json as _json

    from calfkit_tpu import cancellation

    if now is None:
        now = cancellation.wall_clock()
    rows = [("LEASE", "BEAT AGE S", "TTL S", "VERDICT")]
    parsed: "list[tuple[float, tuple[str, str, str, str]]]" = []
    undecodable: "list[tuple[str, str, str, str]]" = []
    for key in sorted(items):
        try:
            body = _json.loads(items[key])
            beat_at = float(body["beat_at"])
            ttl = float(body["ttl_s"])
        except (ValueError, KeyError, TypeError):
            undecodable.append((key, "?", "?", "undecodable"))
            continue
        age = max(0.0, now - beat_at)
        if age > ttl:
            verdict = "lapsed"
        elif ttl > 0 and age > 0.8 * ttl:
            verdict = "live (lapsing)"
        else:
            verdict = "live"
        parsed.append((age, (key, f"{age:.1f}", f"{ttl:.1f}", verdict)))
    parsed.sort(key=lambda entry: (-entry[0], entry[1][0]))
    rows.extend(row for _, row in parsed)
    rows.extend(undecodable)
    if len(rows) == 1:
        return (
            "no caller leases (no leased client is running, or none has "
            "beaten yet — leases are opt-in via Client(lease_ttl=...))"
        )
    return _format_table(rows)


@click.command(
    "leases",
    help="print live caller-liveness leases: beat age vs TTL, and the "
    "orphan reaper's live/lapsed verdict per lease",
)
@click.option("--mesh", "mesh_url", default=None, help="mesh url (or $CALFKIT_MESH_URL)")
@click.option("--timeout", default=15.0, show_default=True, help="catch-up timeout (s)")
def leases_command(mesh_url: str | None, timeout: float) -> None:
    async def main() -> None:
        mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
        await mesh.start()
        try:
            reader = mesh.table_reader(protocol.CALLER_LIVENESS_TOPIC)
            await reader.start(timeout=timeout)
            await reader.barrier(timeout=timeout)
            items = reader.items()
            await reader.stop()
        finally:
            await mesh.stop()
        click.echo(render_leases_table(items))

    asyncio.run(main())


# --------------------------------------------------------------- timeline
def render_timeline(events: "list[dict]", correlation_id: str) -> str:
    """One request's flight-recorder lifecycle, one line per event:
    relative time since the first event, the event name, its decoded int
    payload (labels from ``flightrec.ARG_LABELS``), and a ``(batch)``
    marker on wave/dispatch events borrowed from the request's active
    window (they covered its slot but carry no correlation id)."""
    from calfkit_tpu.observability.flightrec import ARG_LABELS

    if not events:
        return "no events"
    t0 = min(e.get("t_s", 0.0) for e in events)
    span_ms = (max(e.get("t_s", 0.0) for e in events) - t0) * 1000.0
    slot = next((e["slot"] for e in events if e.get("slot", -1) >= 0), -1)
    lines = [
        f"timeline {correlation_id}  —  {len(events)} events"
        + (f", slot {slot}" if slot >= 0 else "")
        + f", {span_ms:.1f} ms first→last"
    ]
    for e in events:
        offset_ms = (e.get("t_s", t0) - t0) * 1000.0
        name = e.get("event", "?")
        labels = ARG_LABELS.get(name, ("a", "b"))
        payload = "  ".join(
            f"{label}={e.get(key, 0)}"
            for label, key in zip(labels, ("a", "b"))
            if label
        )
        note = e.get("note")
        if note:
            payload = (payload + "  " if payload else "") + f"note={note}"
        marker = "" if e.get("corr") == correlation_id else "  (batch)"
        lines.append(
            f"{offset_ms:+11.3f}ms  {name:<16}"
            + (f" {payload}" if payload else "")
            + marker
        )
    return "\n".join(lines)


def _newest_dump(directory: str) -> str | None:
    paths = glob.glob(os.path.join(directory, "*.jsonl"))
    return max(paths, key=os.path.getmtime) if paths else None


@click.command(
    "timeline",
    help="reconstruct one request's scheduler lifecycle from a "
    "flight-recorder dump",
)
@click.argument("correlation_id")
@click.option(
    "--dump",
    "dump_path",
    default=None,
    help="dump file (default: newest *.jsonl in $CALFKIT_FLIGHTREC_DIR / "
    "the fault-dump directory)",
)
def timeline_command(correlation_id: str, dump_path: str | None) -> None:
    from calfkit_tpu.observability import flightrec

    if dump_path is None:
        directory = flightrec.default_dump_dir()
        dump_path = _newest_dump(directory)
        if dump_path is None:
            raise click.ClickException(
                f"no flight-recorder dumps in {directory!r} — trigger one "
                "with SIGUSR2, GET /flightrec, or pass --dump PATH"
            )
        click.echo(f"reading {dump_path}", err=True)
    try:
        with open(dump_path) as f:
            events = flightrec.parse_dump(f)
    except OSError as exc:
        raise click.ClickException(f"cannot read dump: {exc}") from exc
    selected = flightrec.timeline_events(events, correlation_id)
    if not selected:
        raise click.ClickException(
            f"no events for {correlation_id!r} in {dump_path} "
            "(wrong dump, or the ring overwrote this request — see the "
            "FREC APP/DROP column of `ck stats`)"
        )
    click.echo(render_timeline(selected, correlation_id))


# --------------------------------------------------- run timeline (ISSUE 17)
def _parse_run_record(
    items: "dict[str, bytes]", run_id: str
) -> "RunRecord | None":
    value = items.get(run_id)
    if value is None:
        return None
    try:
        return RunRecord.from_wire(value)
    except Exception:  # noqa: BLE001 - undecodable record = not found
        return None


def _parse_run_spans(
    items: "dict[str, bytes]", correlation_ids: "Iterable[str]"
) -> "list[SpanRecord]":
    """Every span belonging to ANY of the run's attempts (span keys are
    ``<trace_id>/<span_id>`` and trace_id == the attempt's correlation
    id by client convention — the stitch needs no other join)."""
    wanted = set(correlation_ids)
    spans: "list[SpanRecord]" = []
    for key, value in items.items():
        if key.partition("/")[0] not in wanted:
            continue
        try:
            spans.append(SpanRecord.from_wire(value))
        except Exception:  # noqa: BLE001 - skip undecodable, keep the rest
            continue
    return spans


def render_run_timeline(
    record: "RunRecord",
    spans: "list[SpanRecord]",
    flight_events: "dict[str, list[dict]] | None" = None,
) -> str:
    """The stitched run-level waterfall (ISSUE 17): one timeline joining
    every attempt's spans and (where a dump is available) flight-recorder
    events, all positioned on the RUN's wall-clock window — so a
    failover reads as attempt 0's bar ending where attempt 1's begins,
    across replicas.  Pure: tests cover it without a mesh."""
    flight_events = flight_events or {}
    by_corr: "dict[str, list[SpanRecord]]" = {}
    for s in spans:
        by_corr.setdefault(s.trace_id, []).append(s)
    starts = [s.start_s for s in spans]
    ends = [s.start_s + s.duration_ms / 1000.0 for s in spans]
    if record.started_at:
        starts.append(record.started_at)
    if record.finished_at:
        ends.append(record.finished_at)
    for rows in flight_events.values():
        starts.extend(e.get("t_s", 0.0) for e in rows)
        ends.extend(e.get("t_s", 0.0) for e in rows)
    t0 = min(starts) if starts else 0.0
    t1 = max(ends) if ends else t0
    total_ms = max((t1 - t0) * 1000.0, 0.001)
    recovery = "".join(
        f", {n} {label}(s)"
        for n, label in (
            (record.failovers, "failover"),
            (record.hedges, "hedge"),
            (record.resumes, "resume"),
            (record.sheds, "shed"),
        )
        if n
    )
    lines = [
        f"run {record.run_id}  —  agent {record.agent or '?'}, "
        f"outcome {record.outcome}"
        + (f" ({record.error_type})" if record.error_type else "")
        + f", {len(record.attempts)} attempt(s)"
        + recovery
        + (
            f", {record.tokens_delivered} tokens"
            if record.tokens_delivered else ""
        )
        + f", {total_ms:.1f} ms end-to-end"
    ]
    for attempt in sorted(record.attempts, key=lambda a: a.attempt_no):
        off_ms = (
            max(0.0, (attempt.started_at - t0) * 1000.0)
            if attempt.started_at else 0.0
        )
        outcome = attempt.outcome + (
            f"({attempt.error_type})" if attempt.error_type else ""
        )
        lines.append(
            f"  attempt {attempt.attempt_no} [{attempt.kind}]  "
            f"corr {attempt.correlation_id[:12] or '?'}  "
            f"placement {attempt.placement or 'shared'}  "
            f"{outcome}  +{off_ms:.1f}ms"
            + (
                f"  {attempt.tokens_delivered} tok"
                if attempt.tokens_delivered else ""
            )
        )
        attempt_spans = by_corr.get(attempt.correlation_id, [])
        by_id = {s.span_id: s for s in attempt_spans}
        for span in sorted(
            attempt_spans, key=lambda s: (s.start_s, s.span_id)
        ):
            offset_ms = (span.start_s - t0) * 1000.0
            left = max(0, min(
                int(offset_ms / total_ms * _BAR_WIDTH), _BAR_WIDTH - 1
            ))
            width = max(
                1,
                int((offset_ms + span.duration_ms) / total_ms * _BAR_WIDTH)
                - left,
            )
            bar = " " * left + "#" * min(width, _BAR_WIDTH - left)
            indent = "  " * _depth_of(span, by_id)
            flag = "" if span.status == "ok" else f"  !{span.status}"
            lines.append(
                f"  {offset_ms:9.1f}ms  [{bar:<{_BAR_WIDTH}}] "
                f"{span.duration_ms:9.1f}ms  {indent}{span.name}"
                f"  ({span.emitter or span.kind}){flag}"
            )
        for e in flight_events.get(attempt.correlation_id, []):
            ev_off = (e.get("t_s", t0) - t0) * 1000.0
            lines.append(
                f"  {ev_off:9.1f}ms  [{'':<{_BAR_WIDTH}}] "
                f"{'':>9}    · flightrec {e.get('event', '?')}"
            )
    return "\n".join(lines)


def show_run_timeline(
    run_id: str,
    mesh_url: "str | None",
    timeout: float,
    dump_path: "str | None" = None,
) -> None:
    """The body of ``ck run <run-id>`` — dispatched from
    :mod:`calfkit_tpu.cli.run` when the single argument is id-shaped
    (32 hex chars; node specs always carry ``:`` / ``.py`` / dots).

    Reads the run's record off ``mesh.runs``, every attempt's spans off
    ``mesh.traces``, and joins flight-recorder events from the newest
    local dump (or ``--dump``) where one exists — the flightrec join is
    strictly best-effort: no dump, no engine events, timeline still
    renders."""
    from calfkit_tpu.observability import flightrec

    async def read_tables() -> "tuple[RunRecord, list[SpanRecord]]":
        mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
        await mesh.start()
        try:
            reader = mesh.table_reader(protocol.RUNS_TOPIC)
            await reader.start(timeout=timeout)
            await reader.barrier(timeout=timeout)
            record = _parse_run_record(reader.items(), run_id)
            await reader.stop()
            if record is None:
                raise click.ClickException(
                    f"no run record for {run_id!r} on "
                    f"{protocol.RUNS_TOPIC} (run still in flight, aged "
                    "out of compaction, or served by a pre-run-ledger "
                    "client?)"
                )
            treader = mesh.table_reader(protocol.TRACES_TOPIC)
            await treader.start(timeout=timeout)
            await treader.barrier(timeout=timeout)
            spans = _parse_run_spans(
                treader.items(),
                [a.correlation_id for a in record.attempts],
            )
            await treader.stop()
        finally:
            await mesh.stop()
        return record, spans

    record, spans = asyncio.run(read_tables())
    # the flightrec join is a local-disk read — it runs OUTSIDE the
    # event loop, and strictly best-effort: no dump, no engine events,
    # the timeline still renders
    flight: "dict[str, list[dict]]" = {}
    path = dump_path or _newest_dump(flightrec.default_dump_dir())
    if path is not None:
        try:
            with open(path) as f:
                events = flightrec.parse_dump(f)
            for a in record.attempts:
                own = [
                    e
                    for e in flightrec.timeline_events(
                        events, a.correlation_id
                    )
                    if e.get("corr") == a.correlation_id
                ]
                if own:
                    flight[a.correlation_id] = own
        except OSError:
            pass
    click.echo(render_run_timeline(record, spans, flight))


# ------------------------------------------------------------ slo (ISSUE 17)
def _parse_slo(items: "dict[str, bytes]") -> "list[SloRollupRecord]":
    out: "list[SloRollupRecord]" = []
    for value in items.values():
        try:
            wrapped = ControlPlaneRecord.from_wire(value)
            out.append(SloRollupRecord.model_validate(wrapped.record))
        except Exception:  # noqa: BLE001 - skip undecodable records
            continue
    return sorted(out, key=lambda r: (r.agent, r.node_id))


def render_slo_table(records: "Iterable[SloRollupRecord]") -> str:
    """The fleet SLO view (ISSUE 17): one row per per-agent rollup
    advert — RUN-level numbers (what callers experienced), with the
    attempt amplification failover/hedge adds shown separately.  BURN is
    the window's error-budget burn: observed failure ratio over the
    allowed ratio for the completion objective (>1 = burning ahead of
    budget).  INTERACTIVE/BATCH (ISSUE 20) split the window per class —
    ``ok/runs@p95s`` each — so degraded batch completion under overload
    is visible next to the interactive tail it protects (``-`` = no runs
    of that class in the window, including every pre-QoS rollup)."""
    rows = [
        (
            "AGENT", "NODE", "WINDOW S", "RUNS", "OK", "RATIO",
            "P50/P95/P99 S", "INTERACTIVE", "BATCH", "ATT AMP", "SHED",
            "FAILOVER", "ORPHAN", "BURN",
        )
    ]

    def class_cell(completed: int, runs: int, p95_s: float) -> str:
        if not runs:
            return "-"
        return f"{completed}/{runs}@{p95_s:.2f}s"

    for r in records:
        rows.append(
            (
                r.agent,
                r.node_id or "-",
                f"{r.window_s:.0f}",
                str(r.runs),
                str(r.completed),
                f"{r.completion_ratio:.4f}",
                f"{r.e2e_p50_s:.2f}/{r.e2e_p95_s:.2f}/{r.e2e_p99_s:.2f}",
                class_cell(
                    r.interactive_completed, r.interactive_runs,
                    r.interactive_p95_s,
                ),
                class_cell(r.batch_completed, r.batch_runs, r.batch_p95_s),
                f"{r.attempt_amplification:.2f}",
                f"{r.shed_rate:.3f}",
                f"{r.failover_rate:.3f}",
                f"{r.orphan_rate:.3f}",
                f"{r.error_budget_burn:.2f}",
            )
        )
    if len(rows) == 1:
        return (
            "no SLO rollups (no worker with an agent is publishing, or "
            "no finished runs have been folded yet)"
        )
    return _format_table(rows)


@click.command(
    "slo",
    help="print per-agent run-level SLO rollups: completion ratio, "
    "end-to-end percentiles, shed/failover/orphan rates, budget burn",
)
@click.option("--mesh", "mesh_url", default=None, help="mesh url (or $CALFKIT_MESH_URL)")
@click.option("--timeout", default=15.0, show_default=True, help="catch-up timeout (s)")
def slo_command(mesh_url: "str | None", timeout: float) -> None:
    async def main() -> None:
        mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
        await mesh.start()
        try:
            reader = mesh.table_reader(protocol.SLO_TOPIC)
            await reader.start(timeout=timeout)
            await reader.barrier(timeout=timeout)
            records = _parse_slo(reader.items())
            await reader.stop()
        finally:
            await mesh.stop()
        click.echo(render_slo_table(records))

    asyncio.run(main())


# ----------------------------------------------------- capacity (ISSUE 19)
_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: "Iterable[float]", *, width: int = 60) -> str:
    """Pure unicode sparkline of the LAST ``width`` values, scaled
    against the series max.  An all-zero series renders as a flat floor
    of ``▁`` — a drained pool must look flat, not invisible."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    hi = max(vals)
    top = len(_SPARK_CHARS) - 1
    if hi <= 0:
        return _SPARK_CHARS[0] * len(vals)
    return "".join(
        _SPARK_CHARS[min(top, int(v / hi * top + 0.5))] for v in vals
    )


def render_capacity_table(replicas: "Iterable") -> str:
    """Per-replica page-pool scalars, straight from the adverts: the
    fleet-wide "could an admission fit" view.  ``-`` across the page
    columns marks a replica with no pool signal (dense layout or a
    pre-capacity advert) — the same None semantics as
    :attr:`~calfkit_tpu.fleet.registry.Replica.headroom_pages`.
    EVICT is the per-heartbeat-interval eviction delta where the advert
    carries a window, else lifetime."""
    rows = [
        (
            "MODEL", "NODE", "INSTANCE", "PAGES", "IN USE", "RESIDENT",
            "HEADROOM", "EVICT", "STALLS",
        )
    ]
    for r in replicas:
        s = r.stats
        if s.pages_total <= 0:
            rows.append(
                (
                    s.model_name, s.node_id, r.instance_id,
                    "-", "-", "-", "-", "-", "-",
                )
            )
            continue
        rows.append(
            (
                s.model_name,
                s.node_id,
                r.instance_id,
                str(s.pages_total),
                str(s.pages_in_use),
                str(s.prefix_resident_pages),
                str(max(0, s.pages_total - s.pages_in_use)),
                str(s.evictions_window),
                str(s.alloc_stalls),
            )
        )
    if len(rows) == 1:
        return (
            "no advertised replicas (is a worker with a local model "
            "running, and the control plane enabled?)"
        )
    return _format_table(rows)


def render_capacity_breakdown(breakdown: "dict") -> str:
    """The page-attribution ledger view: one summary line (the in-use
    identity ``private + shared = in use``), then the top page owners
    (correlation id / run / lane), the per-lane totals, and the hottest
    shared prefix chains by refcount."""
    lines = [
        f"pages {breakdown.get('pages_in_use', 0)}"
        f"/{breakdown.get('pages_total', 0)} in use"
        f"  (private {breakdown.get('private_pages', 0)}"
        f" + shared {breakdown.get('shared_referenced_pages', 0)};"
        f" resident {breakdown.get('prefix_resident_pages', 0)})"
        f"  headroom {breakdown.get('headroom_pages', 0)}"
        f"  evicted {breakdown.get('evicted_pages', 0)}"
        f"  stalls {breakdown.get('alloc_stalls', 0)}"
    ]
    owners = breakdown.get("by_owner") or []
    if owners:
        rows = [("OWNER", "RUN", "LANE", "PAGES")]
        for o in owners:
            rows.append(
                (
                    str(o.get("corr") or "-"),
                    str(o.get("run") or "-"),
                    str(o.get("lane") or "-"),
                    str(o.get("pages", 0)),
                )
            )
        other = breakdown.get("by_owner_other_pages", 0)
        if other:
            rows.append(("(other)", "-", "-", str(other)))
        lines.append(_format_table(rows))
    lanes = breakdown.get("by_lane") or {}
    if lanes:
        lines.append(
            "lanes   "
            + "  ".join(f"{k}={v}" for k, v in sorted(lanes.items()))
        )
    chains = breakdown.get("by_chain") or []
    if chains:
        parts = [
            f"{str(c.get('chain', '?'))[:12]}×{c.get('refs', 0)}"
            for c in chains
        ]
        other = breakdown.get("by_chain_other_pages", 0)
        if other:
            parts.append(f"(other)×{other}")
        lines.append("chains  " + "  ".join(parts))
    return "\n".join(lines)


def render_capacity_timeline(
    meta: "dict | None", samples: "list[dict]"
) -> str:
    """The occupancy timeline from one capacity dump: a sparkline per
    sampled field (occupancy, free pool, resident prefix pages, batch
    fill, queue, dispatch size, the analytic HBM bytes/token), each with
    its min/max/last so the glyphs have units.  Pure: tests cover it
    without an engine."""
    if not samples:
        return "no capacity samples (is RuntimeConfig.capacity_samples 0?)"
    # capacity.parse_dump hands back the header's inner capacity object
    cap = meta or {}
    header = (
        f"capacity {cap.get('label', '?')}  —  {len(samples)} samples"
    )
    if "appended" in cap:
        header += (
            f" (ring appended {cap.get('appended', 0)},"
            f" dropped {cap.get('dropped', 0)})"
        )
    lines = [header]
    for field in (
        "pages_in_use",
        "pages_free",
        "prefix_resident_pages",
        "active_slots",
        "pending",
        "tokens_per_dispatch",
        "hbm_bytes_per_token",
    ):
        vals = [float(s.get(field, 0)) for s in samples]
        lines.append(
            f"  {field:<22} {sparkline(vals)}"
            f"  min {min(vals):g}  max {max(vals):g}  last {vals[-1]:g}"
        )
    return "\n".join(lines)


def _newest_capacity_dump(directory: str) -> "str | None":
    # capacity dumps share the flight-recorder directory but carry their
    # own prefix — a plain *.jsonl glob would hand back a flightrec dump
    paths = glob.glob(os.path.join(directory, "capacity-*.jsonl"))
    return max(paths, key=os.path.getmtime) if paths else None


@click.command(
    "capacity",
    help="print page-grain HBM capacity: per-replica pool/headroom from "
    "the adverts, plus the occupancy timeline and owner breakdown from "
    "the newest local capacity dump",
)
@click.argument("agent", required=False, default=None)
@click.option("--mesh", "mesh_url", default=None, help="mesh url (or $CALFKIT_MESH_URL)")
@click.option("--timeout", default=15.0, show_default=True, help="catch-up timeout (s)")
@click.option(
    "--dump",
    "dump_path",
    default=None,
    help="capacity dump file (default: newest capacity-*.jsonl in "
    "$CALFKIT_FLIGHTREC_DIR / the fault-dump directory); with --dump "
    "the mesh is not read at all",
)
def capacity_command(
    agent: "str | None",
    mesh_url: "str | None",
    timeout: float,
    dump_path: "str | None",
) -> None:
    from calfkit_tpu.fleet.registry import parse_replicas
    from calfkit_tpu.observability import capacity, flightrec

    if dump_path is None:
        # fleet half: the advert scalars every replica heartbeats
        async def read_adverts() -> "list":
            mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
            await mesh.start()
            try:
                reader = mesh.table_reader(protocol.ENGINE_STATS_TOPIC)
                await reader.start(timeout=timeout)
                await reader.barrier(timeout=timeout)
                out = parse_replicas(reader.items())
                await reader.stop()
            finally:
                await mesh.stop()
            return out

        replicas = asyncio.run(read_adverts())
        if agent is not None:
            replicas = [
                r
                for r in replicas
                if r.agent_name == agent or r.node_id == agent
            ]
            if not replicas:
                raise click.ClickException(
                    f"no advertised replicas for agent {agent!r}"
                )
        replicas.sort(key=lambda r: (r.model_name, r.key))
        click.echo(render_capacity_table(replicas))
        # local half, strictly best-effort (same contract as ck run's
        # flightrec join): the timeline/breakdown live in a local dump —
        # co-located operators get them, remote ones still get the table
        path = _newest_capacity_dump(flightrec.default_dump_dir())
        if path is None:
            return
        click.echo(f"reading {path}", err=True)
    else:
        path = dump_path
    try:
        with open(path) as f:
            meta, samples = capacity.parse_dump(f)
    except OSError as exc:
        if dump_path is None:
            return  # the best-effort join must never fail the table
        raise click.ClickException(f"cannot read dump: {exc}") from exc
    click.echo(render_capacity_timeline(meta, samples))
    bd = (meta or {}).get("breakdown")
    if bd:
        click.echo(render_capacity_breakdown(bd))
