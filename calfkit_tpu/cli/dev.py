"""``ck dev`` — the zero-setup dev loop (reference: cli/dev.py:41-51).

Two modes:

- **Single-process**: ``ck dev run file.py:agent`` hosts the nodes AND the
  chat REPL in one process on an in-memory mesh — no broker, no setup.
- **Multi-process**: a managed native meshd broker (connect-or-spawn with a
  spawn-race file lock) plus detached agent daemons —
  ``ck dev serve file.py:agent`` detaches a worker, ``ck dev chat`` talks
  to it, ``ck dev status`` / ``stop`` / ``down`` manage the fleet.
"""

from __future__ import annotations

import asyncio

import click


@click.group("dev", help="dev mesh: serve + chat, managed broker + daemons")
def dev_group() -> None:
    pass


@dev_group.command("run")
@click.argument("specs", nargs=-1, required=True)
@click.option("--agent", "agent_name", default=None)
def dev_run(specs: tuple[str, ...], agent_name: str | None) -> None:
    """Serve nodes on an in-memory mesh and chat with them (one process)."""
    from calfkit_tpu.cli._common import load_nodes
    from calfkit_tpu.cli.chat import repl
    from calfkit_tpu.client import Client
    from calfkit_tpu.mesh import InMemoryMesh
    from calfkit_tpu.worker import Worker

    nodes = load_nodes(specs)

    async def main() -> None:
        mesh = InMemoryMesh()
        async with Worker(nodes, mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            name = agent_name
            if name is None:
                agents = [n.name for n in nodes if n.kind == "agent"]
                if not agents:
                    raise click.ClickException("no agent nodes among the specs")
                name = agents[0]
            click.echo(f"dev mesh up: {[n.name for n in nodes]}; chatting with {name!r}")
            await repl(client, name)
            await client.close()

    asyncio.run(main())


@dev_group.command("mesh")
@click.option("--port", default=None, type=int,
              help="broker port (default: 19092 meshd, 19392 kafkad)")
@click.option("--kafka", "use_kafka", is_flag=True,
              help="manage the kafkad broker (real Kafka wire protocol) "
                   "instead of meshd")
@click.option("--detach", is_flag=True, help="leave the broker running and return")
@click.option("--durable", is_flag=True,
              help="kafkad only: keep topics/records/offsets across broker "
                   "restarts (append-only WAL under the dev dir)")
def dev_mesh(port: int | None, use_kafka: bool, detach: bool,
             durable: bool) -> None:
    """Ensure the native dev broker is up — connect-or-spawn.

    Default broker is meshd (native line protocol); ``--kafka`` manages
    kafkad, the in-repo broker speaking the real Kafka wire protocol
    (the reference's dev broker is Kafka-compatible too).  Safe to run
    from several terminals at once: a file lock guarantees exactly one
    spawn wins and the rest connect.
    """
    from calfkit_tpu.cli._dev_state import ensure_broker

    kind = "kafkad" if use_kafka else "meshd"
    if durable and not use_kafka:
        raise click.ClickException("--durable requires --kafka (kafkad WAL)")
    try:
        # flag unset -> None: inherit the port's recorded durability (a
        # crashed durable broker must not be silently demoted on respawn)
        info = ensure_broker(port, kind, durable=True if durable else None)
    except (FileNotFoundError, RuntimeError, TimeoutError) as exc:
        raise click.ClickException(str(exc)) from exc
    verb = "spawned" if info.spawned else "already up"
    click.echo(
        f"{kind} {verb} on {info.url} — export CALFKIT_MESH_URL={info.url}"
    )
    if detach or not info.spawned:
        return
    click.echo("(ctrl-c to stop)")
    import signal

    try:
        signal.pause()
    except KeyboardInterrupt:
        from calfkit_tpu.cli._dev_state import stop_broker

        stop_broker(info.port, kind)
        click.echo(f"{kind} stopped")


@dev_group.command("serve")
@click.argument("specs", nargs=-1, required=True)
@click.option("--name", "daemon_name", default=None,
              help="daemon name (default: first spec's attr)")
@click.option("--port", default=None, type=int,
              help="broker port (default: 19092 meshd, 19392 kafkad)")
@click.option("--kafka", "use_kafka", is_flag=True,
              help="serve on the kafkad broker (real Kafka wire protocol)")
def dev_serve(specs: tuple[str, ...], daemon_name: str | None,
              port: int | None, use_kafka: bool) -> None:
    """Detach a worker daemon serving SPECS on the managed dev broker."""
    from calfkit_tpu.cli._dev_state import ensure_broker, spawn_daemon

    try:
        broker = ensure_broker(port, "kafkad" if use_kafka else "meshd")
    except (FileNotFoundError, RuntimeError, TimeoutError) as exc:
        raise click.ClickException(str(exc)) from exc
    name = daemon_name or specs[0].rsplit(":", 1)[-1]
    try:
        info = spawn_daemon(name, list(specs), broker.url)
    except RuntimeError as exc:
        raise click.ClickException(str(exc)) from exc
    click.echo(
        f"daemon {info.name!r} up (pid {info.pid}) on {broker.url}; "
        f"logs: {info.log_path}"
    )


@dev_group.command("chat")
@click.option("--agent", "agent_name", default=None)
@click.option("--port", default=None, type=int,
              help="broker port (default: 19092 meshd, 19392 kafkad)")
@click.option("--kafka", "use_kafka", is_flag=True,
              help="chat over the kafkad broker (real Kafka wire protocol)")
def dev_chat(agent_name: str | None, port: int | None, use_kafka: bool) -> None:
    """Chat with the detached dev-mesh agents."""
    from calfkit_tpu.cli._dev_state import broker_status
    from calfkit_tpu.cli.chat import _chat
    from calfkit_tpu.mesh.urls import mesh_from_url

    kind = "kafkad" if use_kafka else "meshd"
    status = broker_status(port, kind)
    if not status["up"]:
        flag = " --kafka" if use_kafka else ""
        raise click.ClickException(
            f"{kind} is down on port {status['port']} — start it with "
            f"`ck dev mesh{flag}` (or `ck dev serve{flag} file.py:agent`)"
        )
    try:
        asyncio.run(_chat(mesh_from_url(status["url"]), agent_name))
    except OSError as exc:
        raise click.ClickException(f"mesh connection failed: {exc}") from exc


@dev_group.command("status")
@click.option("--stats", is_flag=True,
              help="also query live agents + engine metrics off the mesh")
def dev_status(stats: bool) -> None:
    """Broker + daemon liveness (add --stats for mesh-level detail).

    Each broker kind is probed on the port this registry recorded for it
    (falling back to its default), so custom ``ck dev mesh --port``
    spawns show up without re-passing the port here."""
    from calfkit_tpu.cli._dev_state import broker_status, list_daemons

    statuses = [
        broker_status(None, kind) for kind in ("meshd", "kafkad")
    ]
    for broker in statuses:
        state = "up" if broker["up"] else "down"
        owner = f" (managed pid {broker['pid']})" if broker["pid"] else ""
        click.echo(f"broker {broker['url']}: {state}{owner}")
    daemons = list_daemons()
    if not daemons:
        click.echo("daemons: none")
    for d in daemons:
        mark = "alive" if d.alive else "DEAD"
        click.echo(f"  {d.name}: {mark} pid {d.pid} specs={','.join(d.specs)}")
    live = next((b for b in statuses if b["up"]), None)
    if stats and live is not None:
        try:
            asyncio.run(_mesh_stats(live["url"]))
        except Exception as exc:  # noqa: BLE001 - CLI boundary
            raise click.ClickException(f"mesh stats unavailable: {exc}") from exc


async def _mesh_stats(url: str) -> None:
    from calfkit_tpu.client import Client

    client = Client.connect(url)
    try:
        cards = await client.mesh_directory.get_agents()
        click.echo(f"live agents: {[c.name for c in cards] or 'none'}")
        for rec in await client.mesh_directory.get_engine_stats():
            pages = (
                f" free_pages={rec.free_pages}"
                if rec.free_pages is not None else ""
            )
            hbm = (
                f" hbm={rec.hbm_gb_in_use}GB"
                if rec.hbm_gb_in_use is not None else ""
            )
            click.echo(
                f"  engine {rec.node_id}: {rec.model_name} "
                f"[{rec.kv_layout}] tok/s={rec.tokens_per_second} "
                f"occ={rec.mean_occupancy} "
                f"slots={rec.max_batch_size - rec.free_slots}/"
                f"{rec.max_batch_size}{pages}{hbm}"
            )
    finally:
        await client.mesh_directory.close()
        await client.close()


@dev_group.command("stop")
@click.argument("names", nargs=-1)
def dev_stop(names: tuple[str, ...]) -> None:
    """Stop named daemons (or all of them with no argument)."""
    from calfkit_tpu.cli._dev_state import list_daemons, stop_daemon

    targets = list(names) or [d.name for d in list_daemons()]
    if not targets:
        click.echo("no daemons to stop")
        return
    for name in targets:
        click.echo(
            f"{name}: {'stopped' if stop_daemon(name) else 'not found'}"
        )


@dev_group.command("down")
def dev_down() -> None:
    """Stop every daemon AND the managed brokers (meshd + kafkad).

    Each broker is stopped on the port this registry recorded for it —
    a broker someone else runs is left alone."""
    from calfkit_tpu.cli._dev_state import list_daemons, stop_broker, stop_daemon

    for d in list_daemons():
        stop_daemon(d.name)
        click.echo(f"daemon {d.name}: stopped")
    for kind in ("meshd", "kafkad"):
        if stop_broker(None, kind):
            click.echo(f"{kind}: stopped")
        else:
            click.echo(f"{kind}: not managed here (left alone)")
