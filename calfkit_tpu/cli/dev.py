"""``ck dev`` — the zero-setup dev loop (reference: cli/dev.py:41-51).

The reference spawns a bundled single-binary broker; this build's dev mesh is
the in-process :class:`InMemoryMesh`, so ``ck dev run`` hosts the nodes AND
the chat REPL in one process — no broker, no setup.
"""

from __future__ import annotations

import asyncio

import click


@click.group("dev", help="single-process dev mesh: serve + chat, no broker")
def dev_group() -> None:
    pass


@dev_group.command("run")
@click.argument("specs", nargs=-1, required=True)
@click.option("--agent", "agent_name", default=None)
def dev_run(specs: tuple[str, ...], agent_name: str | None) -> None:
    """Serve nodes on an in-memory mesh and chat with them."""
    from calfkit_tpu.cli._common import load_nodes
    from calfkit_tpu.cli.chat import repl
    from calfkit_tpu.client import Client
    from calfkit_tpu.mesh import InMemoryMesh
    from calfkit_tpu.worker import Worker

    nodes = load_nodes(specs)

    async def main() -> None:
        mesh = InMemoryMesh()
        async with Worker(nodes, mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            name = agent_name
            if name is None:
                agents = [n.name for n in nodes if n.kind == "agent"]
                if not agents:
                    raise click.ClickException("no agent nodes among the specs")
                name = agents[0]
            click.echo(f"dev mesh up: {[n.name for n in nodes]}; chatting with {name!r}")
            await repl(client, name)
            await client.close()

    asyncio.run(main())


@dev_group.command("mesh")
@click.option("--port", default=19092, show_default=True)
def dev_mesh(port: int) -> None:
    """Run the native multi-process dev broker (meshd).

    Then serve/chat from other terminals with --mesh tcp://127.0.0.1:PORT.
    """
    from calfkit_tpu.mesh.tcp import spawn_meshd

    try:
        proc = spawn_meshd(port)
    except (FileNotFoundError, RuntimeError, TimeoutError) as exc:
        raise click.ClickException(str(exc)) from exc
    click.echo(
        f"meshd up on tcp://127.0.0.1:{port} — export "
        f"CALFKIT_MESH_URL=tcp://127.0.0.1:{port} (ctrl-c to stop)"
    )
    try:
        proc.wait()
    except KeyboardInterrupt:
        proc.terminate()
        click.echo("meshd stopped")


@dev_group.command("status")
def dev_status() -> None:
    """Explain the dev-mesh model."""
    click.echo(
        "Single-process: `ck dev run file.py:agent` (memory:// — serve + chat "
        "in one process, zero setup).\nMulti-process: `ck dev mesh` runs the "
        "native meshd broker; point --mesh/CALFKIT_MESH_URL at "
        "tcp://127.0.0.1:19092.\nProduction: kafka://host:port."
    )
