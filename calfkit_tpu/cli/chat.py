"""``ck chat`` — REPL against a live mesh with step streaming
(reference: cli/chat.py + cli/_chat_render.py)."""

from __future__ import annotations

import asyncio

import click


@click.command("chat")
@click.option("--mesh", "mesh_url", default=None)
@click.option("--agent", "agent_name", default=None, help="agent to talk to")
def chat_command(mesh_url: str | None, agent_name: str | None) -> None:
    """Chat with a live agent (steps stream inline)."""
    from calfkit_tpu.cli._common import resolve_mesh_for_cli

    asyncio.run(_chat(resolve_mesh_for_cli(mesh_url, hosts_worker=False), agent_name))


async def _chat(mesh, agent_name: str | None) -> None:
    from calfkit_tpu.client import Client

    client = Client.connect(mesh)
    try:
        if agent_name is None:
            cards = await client.mesh_directory.get_agents()
            if not cards:
                raise click.ClickException("no live agents on the mesh")
            if len(cards) == 1:
                agent_name = cards[0].name
            else:
                for i, card in enumerate(cards):
                    click.echo(f"  [{i}] {card.name}: {card.description}")
                index = click.prompt("agent", type=int, default=0)
                agent_name = cards[index].name
        click.echo(f"chatting with {agent_name!r} (ctrl-d to exit)")
        await repl(client, agent_name)
    finally:
        await client.close()
        await mesh.stop()


async def repl(client, agent_name: str) -> None:
    """The chat loop, reusable by ``ck dev run`` (history carries over)."""
    gateway = client.agent(agent_name)
    history = None
    while True:
        try:
            prompt = await asyncio.to_thread(input, f"\nyou> ")
        except (EOFError, KeyboardInterrupt):
            click.echo("\nbye")
            return
        if not prompt.strip():
            continue
        handle = await gateway.start(prompt, message_history=history, timeout=300)
        async for event in handle.stream():
            if hasattr(event, "step"):
                step = event.step
                if step.kind == "tool_call":
                    click.echo(f"  ⚙ {step.tool_name}({step.args})")
                elif step.kind == "tool_result":
                    mark = "✓" if step.ok else "✗"
                    click.echo(f"  {mark} {step.tool_name} → {step.content[:120]}")
                elif step.kind == "handoff":
                    click.echo(f"  ↪ handoff → {step.to_agent}")
                elif step.kind == "token":
                    click.echo(step.text, nl=False)
                elif step.kind == "inference":
                    click.echo(
                        f"  ∙ {step.model_name}: {step.generated_tokens} tok "
                        f"in {step.decode_ms:.0f}ms"
                    )
            else:
                click.echo(f"\n{agent_name}> {event.output}")
                history = event.state.message_history
