"""``--reload`` support: restart a serve subprocess when watched files change.

Reference anchor: ``ck run --reload`` via watchfiles
(/root/reference/calfkit/cli/run.py:37).  This image has no watchfiles, so
the watcher is a stat-polling scan — the observable behavior (edit a file →
the worker restarts with the new code) is the same; the serve runs in a
child process so a restart is a clean re-import.
"""

from __future__ import annotations

import contextlib
import subprocess
import sys
import time
from pathlib import Path
from typing import Iterable

_MAX_WATCHED = 2000


def watch_roots_for_specs(specs: Iterable[str]) -> list[Path]:
    """Directories worth watching for the given node specs."""
    from calfkit_tpu.cli._common import is_file_spec

    roots: list[Path] = []
    for spec in specs:
        module_part = spec.rsplit(":", 1)[0]
        if is_file_spec(module_part):
            path = Path(module_part).resolve()
            if path.exists():
                roots.append(path.parent)
        else:  # a module name: watch the cwd tree like the reference does
            roots.append(Path.cwd())
    # dedupe, parents swallow children
    uniq: list[Path] = []
    for root in sorted(set(roots)):
        if not any(root.is_relative_to(kept) for kept in uniq):
            uniq.append(root)
    return uniq


def snapshot(roots: Iterable[Path]) -> dict[str, float]:
    """mtimes of every watched .py file (bounded scan)."""
    seen: dict[str, float] = {}
    for root in roots:
        for path in root.rglob("*.py"):
            if any(part.startswith(".") or part == "__pycache__"
                   for part in path.parts):
                continue
            try:
                seen[str(path)] = path.stat().st_mtime
            except OSError:
                continue
            if len(seen) >= _MAX_WATCHED:
                return seen
    return seen


def serve_with_reload(
    child_argv: list[str],
    roots: list[Path],
    *,
    poll_interval: float = 0.5,
    echo=print,
    max_restarts: int | None = None,
) -> int:
    """Run ``child_argv`` as a subprocess; restart it whenever a watched
    ``.py`` changes.  Returns the child's final exit code."""
    import signal

    def _term(_signum, _frame):
        raise KeyboardInterrupt  # SIGTERM must not orphan the serving child

    with contextlib.suppress(ValueError):  # non-main thread (tests)
        signal.signal(signal.SIGTERM, _term)
    restarts = 0
    while True:
        before = snapshot(roots)
        proc = subprocess.Popen(child_argv)
        try:
            changed = None
            while changed is None:
                code = proc.poll()
                if code is not None:
                    return code  # child exited on its own: propagate
                time.sleep(poll_interval)
                now = snapshot(roots)
                if now != before:
                    changed = [p for p in now if now.get(p) != before.get(p)]
                    changed += [p for p in before if p not in now]
            echo(f"change detected ({Path(changed[0]).name}): restarting")
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        except KeyboardInterrupt:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            return 0
        restarts += 1
        if max_restarts is not None and restarts >= max_restarts:
            return 0


def reload_child_argv(specs: tuple[str, ...], passthrough: list[str]) -> list[str]:
    return [
        sys.executable, "-m", "calfkit_tpu.cli.main", "run", *specs,
        *passthrough,
    ]
