"""``ck topics`` — provisioning (reference: cli/topics.py)."""

from __future__ import annotations

import asyncio

import click

from calfkit_tpu.cli._common import load_nodes, resolve_mesh_for_cli


@click.group("topics", help="topic provisioning")
def topics_group() -> None:
    pass


@topics_group.command("provision")
@click.argument("specs", nargs=-1, required=True)
@click.option("--mesh", "mesh_url", default=None)
@click.option("--dry-run", is_flag=True, help="print the topic plan only")
def provision_command(specs: tuple[str, ...], mesh_url: str | None,
                      dry_run: bool) -> None:
    """Create every topic the given nodes need."""
    from calfkit_tpu.provisioning import (
        framework_topics_for_nodes,
        provision,
        topics_for_nodes,
    )

    nodes = load_nodes(specs)
    if dry_run:
        for topic in topics_for_nodes(nodes):
            click.echo(f"  {topic}")
        for topic in framework_topics_for_nodes(nodes):
            click.echo(f"  {topic} (compacted)")
        return

    async def main() -> None:
        mesh = resolve_mesh_for_cli(mesh_url, hosts_worker=False)
        await mesh.start()
        result = await provision(mesh, nodes)
        click.echo(
            f"provisioned {len(result['plain'])} topics "
            f"+ {len(result['compacted'])} compacted"
        )
        await mesh.stop()

    asyncio.run(main())
