"""``ck run module:attr ...`` — serve nodes (reference: cli/run.py:37)."""

from __future__ import annotations

import click

from calfkit_tpu.cli._common import load_nodes, resolve_mesh_for_cli


@click.command("run")
@click.argument("specs", nargs=-1, required=True)
@click.option("--mesh", "mesh_url", default=None, help="memory:// | tcp://host:port | kafka://host:port")
@click.option("--max-workers", default=8, show_default=True)
@click.option("--group-id", default=None, help="override per-node consumer groups")
@click.option("--reload", "reload_", is_flag=True,
              help="restart when watched .py files change (dev loop)")
def run_command(specs: tuple[str, ...], mesh_url: str | None, max_workers: int,
                group_id: str | None, reload_: bool) -> None:
    """Serve the given nodes until interrupted."""
    if reload_:
        from calfkit_tpu.cli._reload import (
            reload_child_argv,
            serve_with_reload,
            watch_roots_for_specs,
        )

        passthrough = ["--max-workers", str(max_workers)]
        if mesh_url:
            passthrough += ["--mesh", mesh_url]
        if group_id:
            passthrough += ["--group-id", group_id]
        roots = watch_roots_for_specs(specs)
        click.echo(f"watching {', '.join(str(r) for r in roots)} for changes")
        raise SystemExit(
            serve_with_reload(
                reload_child_argv(specs, passthrough), roots, echo=click.echo
            )
        )

    from calfkit_tpu.worker import Worker

    nodes = load_nodes(specs)
    mesh = resolve_mesh_for_cli(mesh_url)
    click.echo(f"serving {len(nodes)} node(s): {[n.name for n in nodes]}")
    worker = Worker(
        nodes, mesh=mesh, owns_transport=True, max_workers=max_workers,
        group_id=group_id,
    )
    worker.run()
