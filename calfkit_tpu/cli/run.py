"""``ck run module:attr ...`` — serve nodes (reference: cli/run.py:37).

``ck run <run-id>`` (ISSUE 17) — when the single argument is id-shaped
(hex run id, which node specs can never be: specs carry ``:`` or a
path) the command instead prints the run's stitched cross-attempt
timeline from ``mesh.runs`` + ``mesh.traces``.
"""

from __future__ import annotations

import click

from calfkit_tpu.cli._common import load_nodes, resolve_mesh_for_cli


def _is_run_id(spec: str) -> bool:
    """True when a spec can only be a run id (32-hex ``new_id()``), never
    a servable node spec.  Specs are ``module:attr`` or ``path.py:attr``
    — always carrying ``:``, a dot, or a path separator — so a bare hex
    token was previously a guaranteed load error, making this dispatch
    regression-free."""
    if len(spec) < 12 or any(c in spec for c in ":/.\\"):
        return False
    try:
        int(spec, 16)
    except ValueError:
        return False
    return True


@click.command("run")
@click.argument("specs", nargs=-1, required=True)
@click.option("--mesh", "mesh_url", default=None, help="memory:// | tcp://host:port | kafka://host:port")
@click.option("--max-workers", default=8, show_default=True)
@click.option("--group-id", default=None, help="override per-node consumer groups")
@click.option("--timeout", default=15.0, show_default=True,
              help="catch-up timeout (s) for the run-timeline view")
@click.option("--dump", "dump_path", default=None, type=click.Path(),
              help="flight-recorder dump to join into the run timeline "
              "(default: newest local dump)")
@click.option("--reload", "reload_", is_flag=True,
              help="restart when watched .py files change (dev loop)")
def run_command(specs: tuple[str, ...], mesh_url: str | None, max_workers: int,
                group_id: str | None, timeout: float,
                dump_path: str | None, reload_: bool) -> None:
    """Serve the given nodes until interrupted — or, given a single run
    id, print that run's stitched cross-attempt timeline."""
    if len(specs) == 1 and _is_run_id(specs[0]):
        from calfkit_tpu.cli.obs import show_run_timeline

        show_run_timeline(
            specs[0], mesh_url, timeout, dump_path=dump_path
        )
        return
    if reload_:
        from calfkit_tpu.cli._reload import (
            reload_child_argv,
            serve_with_reload,
            watch_roots_for_specs,
        )

        passthrough = ["--max-workers", str(max_workers)]
        if mesh_url:
            passthrough += ["--mesh", mesh_url]
        if group_id:
            passthrough += ["--group-id", group_id]
        roots = watch_roots_for_specs(specs)
        click.echo(f"watching {', '.join(str(r) for r in roots)} for changes")
        raise SystemExit(
            serve_with_reload(
                reload_child_argv(specs, passthrough), roots, echo=click.echo
            )
        )

    from calfkit_tpu.worker import Worker

    nodes = load_nodes(specs)
    mesh = resolve_mesh_for_cli(mesh_url)
    click.echo(f"serving {len(nodes)} node(s): {[n.name for n in nodes]}")
    worker = Worker(
        nodes, mesh=mesh, owns_transport=True, max_workers=max_workers,
        group_id=group_id,
    )
    worker.run()
