"""Shared curated-XOR-discover validation for handle selectors
(reference: calfkit/_handle_names.py:1-127 — Tools/Toolboxes/Messaging/
Handoff all share this rail)."""

from __future__ import annotations

from typing import Sequence

from calfkit_tpu import protocol


def validate_curated_or_discover(
    what: str, names: Sequence[str], discover: bool
) -> None:
    if names and discover:
        raise ValueError(f"{what} takes either names or discover=True, not both")
    if not names and not discover:
        raise ValueError(f"{what} requires names, or discover=True")
    seen: set[str] = set()
    for name in names:
        protocol.require_topic_safe(name, what=f"{what} name")
        if name in seen:
            raise ValueError(f"{what}: duplicate name {name!r}")
        seen.add(name)
