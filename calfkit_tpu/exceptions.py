"""Public exception types (reference: calfkit/exceptions.py:1-233) and the
authoritative ``x-mesh-error-type`` ↔ exception-class table.

The table (ISSUE 5 satellite) is the single place the wire fault vocabulary
and the Python exception surface meet: the fault publisher in
:mod:`calfkit_tpu.nodes.base` uses :func:`error_type_for` to give a typed
exception a typed fault code (instead of harvesting it as a generic
``mesh.node_error``), and the caller-side classifier uses
:data:`RETRIABLE_FAULT_TYPES` / :func:`exception_for` to decide whether a
fault is worth a backoff-retry and which local type represents it.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from calfkit_tpu.models.error_report import FaultTypes

if TYPE_CHECKING:
    from calfkit_tpu.models.error_report import ErrorReport
    from calfkit_tpu.models.session_context import Envelope

__all__ = [
    "CalfkitError",
    "NodeFaultError",
    "ClientTimeoutError",
    "ClientClosedError",
    "DeserializationError",
    "MeshUnavailableError",
    "RegistryConfigError",
    "SeamContractError",
    "LifecycleConfigError",
    "ProvisioningError",
    "InferenceError",
    "EngineOverloadedError",
    "EngineWedgedError",
    "DeadlineExceededError",
    "RunCancelledError",
    "RunOrphanedError",
    "TenantRateLimitedError",
    "FAULT_TYPE_BY_EXCEPTION",
    "RETRIABLE_FAULT_TYPES",
    "error_type_for",
    "exception_for",
]


class CalfkitError(Exception):
    """Base for all framework exceptions."""


class NodeFaultError(CalfkitError):
    """The typed-fault mint gesture.

    User code raises this (or the kernel mints it) to produce a typed
    ``FaultMessage``; catching it at the client surfaces the ErrorReport.
    """

    def __init__(
        self, report: "ErrorReport", envelope: "Envelope | None" = None
    ):
        self.report = report
        # the terminal fault envelope when available (client side): exposes
        # degradation facts like state_elided to callers
        self.envelope = envelope
        super().__init__(report.describe())


class ClientTimeoutError(CalfkitError, TimeoutError):
    pass


class ClientClosedError(CalfkitError):
    pass


class DeserializationError(CalfkitError):
    pass


class MeshUnavailableError(CalfkitError):
    def __init__(self, message: str, *, reason: str = "unavailable"):
        self.reason = reason
        super().__init__(message)


class RegistryConfigError(CalfkitError):
    """Bad handler registration (duplicate route, invalid pattern, ...)."""


class SeamContractError(CalfkitError):
    """A policy seam had the wrong arity or returned an illegal value."""


class LifecycleConfigError(CalfkitError):
    """Worker lifecycle hook/resource misconfiguration."""


class ProvisioningError(CalfkitError):
    pass


class InferenceError(CalfkitError):
    """Local inference backend failure."""


class EngineOverloadedError(CalfkitError):
    """Bounded admission shed this request (ISSUE 5).

    Raised AT SUBMIT when an engine lane's queue is at
    ``RuntimeConfig.max_pending`` (or when a stalled consumer tripped the
    ``max_out_blocks`` delivery bound, or a draining worker refused the
    call).  Typed and retriable by contract: the caller may back off and
    retry the same call against the same or another engine — nothing was
    partially executed.
    """

    def __init__(
        self,
        message: str,
        *,
        lane: str = "short",
        pending: int = 0,
        limit: int = 0,
    ):
        self.lane = lane
        self.pending = pending
        self.limit = limit
        super().__init__(message)


class EngineWedgedError(CalfkitError):
    """The engine's dispatch-progress watchdog tripped (ISSUE 9): work was
    pending but no dispatch landed for ``RuntimeConfig.watchdog_stall_s``
    — the BENCH-documented "wedged device grant" state.  Requests caught
    in (or queued behind) the wedge are faulted with this instead of
    silently burning their deadlines.  Typed and RETRIABLE by contract:
    the caller observed no tokens from this engine, so the same call may
    run whole on another replica — the fleet gateway's failover path
    treats it exactly like a shed.
    """

    def __init__(self, message: str, *, stalled_s: float = 0.0):
        self.stalled_s = stalled_s
        super().__init__(message)


class DeadlineExceededError(CalfkitError, TimeoutError):
    """The request's absolute deadline (``x-mesh-deadline``) passed.

    Minted wherever the expiry is first observed — engine admission, the
    queued-request reaper, or a mesh hop receiving an already-expired
    call.  NOT retriable: the caller's budget is spent, retrying would
    burn capacity for an answer nobody is waiting for.
    """


class RunCancelledError(CalfkitError):
    """The run's caller published a mesh ``cancel`` before this call
    started executing — the admission gate hit the correlation id's
    tombstone (see :func:`calfkit_tpu.cancellation.was_cancelled`) and
    refused to execute for a caller that already left.  NOT retriable:
    the cancel was deliberate.
    """


class RunOrphanedError(CalfkitError):
    """The run's CALLER liveness lease lapsed (ISSUE 10): heartbeats on
    ``mesh.caller_liveness`` stopped for longer than the lease TTL (hard
    caller death), or the caller released the lease on clean close — and
    the engine's orphan reaper abandoned the run, freeing its slot,
    pages, and prefix refs for callers that are still alive.  NOT
    retriable: there is nobody left to answer.  This is what makes
    fire-and-forget ``send()`` safe — the client-side failover
    supervisor (ISSUE 9) cannot cover a run nobody awaits.
    """

    def __init__(self, message: str, *, lease_id: str = "", lapsed_s: float = 0.0):
        self.lease_id = lease_id
        self.lapsed_s = lapsed_s
        super().__init__(message)


class TenantRateLimitedError(CalfkitError):
    """The node kernel's per-tenant token bucket refused this call
    (ISSUE 20): the tenant (lease id where present, else caller client
    id) spent its admission budget.  Refused BEFORE the engine's queues
    — nothing was admitted, no slot or page was held.  Typed and
    RETRIABLE by contract: the bucket refills on the deadline clock's
    schedule, so ``retry_after_s`` is an honest backoff hint (unlike a
    deadline fault, where the budget is gone forever).
    """

    def __init__(
        self,
        message: str,
        *,
        tenant_id: str = "",
        retry_after_s: float = 0.0,
    ):
        self.tenant_id = tenant_id
        self.retry_after_s = retry_after_s
        super().__init__(message)


# --------------------------------------------------------------------------- #
# the authoritative x-mesh-error-type ↔ exception-class table
# --------------------------------------------------------------------------- #
# One direction is a plain dict; subclass lookups go through
# error_type_for's MRO walk so e.g. a subclass of EngineOverloadedError
# still classifies as mesh.overloaded.  NodeFaultError is deliberately
# absent: it CARRIES a report with its own error_type rather than mapping
# to one.

FAULT_TYPE_BY_EXCEPTION: dict[type[BaseException], str] = {
    EngineOverloadedError: FaultTypes.OVERLOADED,
    EngineWedgedError: FaultTypes.WEDGED,
    DeadlineExceededError: FaultTypes.DEADLINE_EXCEEDED,
    RunCancelledError: FaultTypes.CANCELLED,
    RunOrphanedError: FaultTypes.ORPHANED,
    TenantRateLimitedError: FaultTypes.RATE_LIMITED,
    ClientTimeoutError: FaultTypes.TIMEOUT,
    DeserializationError: FaultTypes.DESERIALIZATION_ERROR,
    InferenceError: FaultTypes.MODEL_ERROR,
    MeshUnavailableError: FaultTypes.CAPABILITY_UNAVAILABLE,
    ProvisioningError: FaultTypes.LIFECYCLE_ERROR,
    LifecycleConfigError: FaultTypes.LIFECYCLE_ERROR,
}

# faults a caller may retry with backoff: the work was refused whole
# (shed, drain, transport hiccup), never half-done.  Deadline faults are
# deliberately NOT here — the budget is gone; timeouts are here because a
# TRANSIENT downstream timeout (not the caller's own deadline) can succeed
# on a less-loaded instance.
RETRIABLE_FAULT_TYPES: frozenset[str] = frozenset(
    {
        FaultTypes.OVERLOADED,
        FaultTypes.TIMEOUT,
        FaultTypes.CAPABILITY_UNAVAILABLE,
        # a wedge fault means NOTHING reached the caller from this engine
        # (the watchdog faults before any terminal): the call is whole and
        # another replica can serve it — failover territory (ISSUE 9)
        FaultTypes.WEDGED,
        # a rate-limit refusal (ISSUE 20) happens at the node kernel's
        # gate, before any queue or slot — the token bucket refills on a
        # known schedule, so backoff-and-retry is exactly right
        FaultTypes.RATE_LIMITED,
    }
)

# reverse direction, first-writer-wins where two exceptions share a code
# (the dict above lists the canonical class first per code)
_EXCEPTION_BY_FAULT_TYPE: dict[str, type[BaseException]] = {}
for _exc_type, _code in FAULT_TYPE_BY_EXCEPTION.items():
    _EXCEPTION_BY_FAULT_TYPE.setdefault(_code, _exc_type)


def error_type_for(exc: BaseException) -> "str | None":
    """The ``x-mesh-error-type`` code for an exception, honoring subclass
    relationships; ``None`` when the exception has no typed code (the
    fault publisher then falls back to its own generic code)."""
    for klass in type(exc).__mro__:
        code = FAULT_TYPE_BY_EXCEPTION.get(klass)
        if code is not None:
            return code
    return None


def exception_for(error_type: "str | None") -> "type[BaseException] | None":
    """The canonical local exception class for a wire fault code;
    ``None`` for unknown/untyped codes."""
    if not error_type:
        return None
    return _EXCEPTION_BY_FAULT_TYPE.get(error_type)
