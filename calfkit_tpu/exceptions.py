"""Public exception types (reference: calfkit/exceptions.py:1-233)."""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from calfkit_tpu.models.error_report import ErrorReport
    from calfkit_tpu.models.session_context import Envelope


class CalfkitError(Exception):
    """Base for all framework exceptions."""


class NodeFaultError(CalfkitError):
    """The typed-fault mint gesture.

    User code raises this (or the kernel mints it) to produce a typed
    ``FaultMessage``; catching it at the client surfaces the ErrorReport.
    """

    def __init__(
        self, report: "ErrorReport", envelope: "Envelope | None" = None
    ):
        self.report = report
        # the terminal fault envelope when available (client side): exposes
        # degradation facts like state_elided to callers
        self.envelope = envelope
        super().__init__(report.describe())


class ClientTimeoutError(CalfkitError, TimeoutError):
    pass


class ClientClosedError(CalfkitError):
    pass


class DeserializationError(CalfkitError):
    pass


class MeshUnavailableError(CalfkitError):
    def __init__(self, message: str, *, reason: str = "unavailable"):
        self.reason = reason
        super().__init__(message)


class RegistryConfigError(CalfkitError):
    """Bad handler registration (duplicate route, invalid pattern, ...)."""


class SeamContractError(CalfkitError):
    """A policy seam had the wrong arity or returned an illegal value."""


class LifecycleConfigError(CalfkitError):
    """Worker lifecycle hook/resource misconfiguration."""


class ProvisioningError(CalfkitError):
    pass


class InferenceError(CalfkitError):
    """Local inference backend failure."""
