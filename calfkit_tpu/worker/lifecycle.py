"""Worker lifecycle: two-phase hook brackets + ``@resource`` generators.

Reference: calfkit/worker/lifecycle.py:182-340.  Two bracket families:

- **resource phase** (outer): ``on_startup`` hooks and ``@resource`` async
  generators run before the broker serves; their teardown (``after_shutdown``
  + generator finalizers) runs last, after traffic has drained.
- **serving phase** (inner): ``after_startup`` runs once the broker is
  consuming (e.g. control-plane liveness announcements); ``on_shutdown``
  runs first at stop (e.g. tombstoning adverts while the broker still works).

A failed boot rolls back whatever started, in reverse order.
"""

from __future__ import annotations

import inspect
import logging
from typing import Any, AsyncIterator, Awaitable, Callable

from calfkit_tpu.exceptions import LifecycleConfigError

logger = logging.getLogger(__name__)

Hook = Callable[[], Awaitable[None] | None]
ResourceFactory = Callable[[], AsyncIterator[Any]]


class LifecycleHookMixin:
    def __init__(self) -> None:
        self._on_startup: list[Hook] = []
        self._after_startup: list[Hook] = []
        self._on_shutdown: list[Hook] = []
        self._after_shutdown: list[Hook] = []
        self._resource_factories: list[tuple[str | None, ResourceFactory]] = []
        self._live_resources: list[tuple[str | None, AsyncIterator[Any]]] = []

    # ------------------------------------------------------------ decorators
    def on_startup(self, fn: Hook) -> Hook:
        self._on_startup.append(fn)
        return fn

    def after_startup(self, fn: Hook) -> Hook:
        self._after_startup.append(fn)
        return fn

    def on_shutdown(self, fn: Hook) -> Hook:
        self._on_shutdown.append(fn)
        return fn

    def after_shutdown(self, fn: Hook) -> Hook:
        self._after_shutdown.append(fn)
        return fn

    def resource(
        self, fn: ResourceFactory | None = None, *, key: str | None = None
    ) -> Any:
        """``@worker.resource`` on an async generator: code before ``yield``
        runs at boot, after it at teardown; a yielded value is stored under
        ``key`` (or the function name) in the worker's resource bag."""

        def register(f: ResourceFactory) -> ResourceFactory:
            if not inspect.isasyncgenfunction(f):
                raise LifecycleConfigError(
                    f"@resource requires an async generator function, got {f!r}"
                )
            self._resource_factories.append((key or f.__name__, f))
            return f

        return register(fn) if fn is not None else register

    # -------------------------------------------------------------- running
    async def _run_hooks(self, hooks: list[Hook], *, phase: str) -> None:
        for hook in hooks:
            result = hook()
            if inspect.isawaitable(result):
                await result

    async def _enter_resources(self, bag: dict[str, Any]) -> None:
        for key, factory in self._resource_factories:
            gen = factory()
            value = await gen.__anext__()
            self._live_resources.append((key, gen))
            if key is not None and value is not None:
                bag[key] = value

    async def _exit_resources(self) -> None:
        # swap-then-iterate (meshlint await-atomicity): detach before the
        # first await so enter/exit can never race a stale snapshot
        live, self._live_resources = self._live_resources, []
        for key, gen in reversed(live):
            try:
                await gen.__anext__()
            except StopAsyncIteration:
                pass
            except Exception:  # noqa: BLE001
                logger.exception("resource %r teardown failed", key)
            else:
                logger.warning("resource %r yielded more than once", key)
