"""The Worker: hosts nodes on a shared mesh transport.

Reference: calfkit/worker/worker.py:40-746.  Responsibilities:

- register each node's key-ordered subscriber (input + return topics, one
  consumer group per node name → horizontal scaling via group membership);
- provision topics at boot;
- provide per-node durable fan-out stores;
- run the lifecycle brackets (see :mod:`calfkit_tpu.worker.lifecycle`) with
  rollback on failed boot;
- wire the control plane (adverts + heartbeats + views) when available;
- three run surfaces: ``run()`` (blocking), ``start()/stop()``,
  ``async with``.

Workers are single-use objects (a stopped worker is not restartable),
matching the reference's stance (worker.py:628).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import signal
from typing import Any, Sequence

from calfkit_tpu.exceptions import LifecycleConfigError
from calfkit_tpu.mesh.transport import MeshTransport, Subscription
from calfkit_tpu.nodes.base import BaseNodeDef
from calfkit_tpu.nodes.fanout_store import FANOUT_STORE_KEY, KtablesFanoutBatchStore
from calfkit_tpu.worker.lifecycle import LifecycleHookMixin

logger = logging.getLogger(__name__)


class Worker(LifecycleHookMixin):
    def __init__(
        self,
        nodes: Sequence[BaseNodeDef],
        *,
        mesh: "MeshTransport | str | None" = None,
        group_id: str | None = None,
        max_workers: int = 8,
        owns_transport: bool = False,
        control_plane: Any = None,
        fanout: Any = None,  # FanoutConfig | None
        provisioning: Any = None,  # ProvisioningConfig | None
        qos: Any = None,  # qos.TenantRateLimiter | None
    ):
        super().__init__()
        if not nodes:
            raise LifecycleConfigError("Worker requires at least one node")
        names = [n.name for n in nodes]
        if len(set(names)) != len(names):
            raise LifecycleConfigError(f"duplicate node names: {names}")
        self.nodes = list(nodes)
        from calfkit_tpu.mesh.urls import resolve_mesh

        # mesh may be a transport, a url string, or None ($CALFKIT_MESH_URL);
        # a transport built HERE from a url is owned by the worker
        self.mesh, owned = resolve_mesh(mesh)
        self.group_id = group_id
        self.max_workers = max_workers
        self.owns_transport = owns_transport or owned
        from calfkit_tpu.tuning import FanoutConfig

        if fanout is not None and not isinstance(fanout, FanoutConfig):
            raise LifecycleConfigError(
                f"fanout must be a FanoutConfig, got {type(fanout).__name__}"
            )
        self.fanout_config = fanout
        from calfkit_tpu.provisioning import ProvisioningConfig

        if provisioning is not None and not isinstance(
            provisioning, ProvisioningConfig
        ):
            raise LifecycleConfigError(
                "provisioning must be a ProvisioningConfig, got "
                f"{type(provisioning).__name__}"
            )
        self.provisioning_config = provisioning
        # control plane default ON: pass False (or a disabled config) to opt
        # out; a ControlPlaneConfig customizes; a ControlPlane is used as-is
        from calfkit_tpu.controlplane import ControlPlane, ControlPlaneConfig

        if control_plane is None or control_plane is True:
            control_plane = ControlPlane()
        elif control_plane is False:
            control_plane = None
        elif isinstance(control_plane, ControlPlaneConfig):
            control_plane = (
                ControlPlane(control_plane) if control_plane.enabled else None
            )
        elif not hasattr(control_plane, "attach"):
            raise LifecycleConfigError(
                f"control_plane must be a ControlPlane, ControlPlaneConfig, "
                f"True/False or None, got {type(control_plane).__name__}"
            )
        self.control_plane = control_plane
        # multi-tenant QoS (ISSUE 20): an opt-in per-tenant admission
        # token bucket shared by every node this worker hosts — the node
        # kernel's admission gate spends one token per ENTERING run and
        # refuses over-budget tenants with a typed, retriable
        # ``mesh.rate_limited`` fault before any queue or slot is held
        from calfkit_tpu.qos import TenantRateLimiter

        if qos is not None and not isinstance(qos, TenantRateLimiter):
            raise LifecycleConfigError(
                f"qos must be a TenantRateLimiter, got {type(qos).__name__}"
            )
        self.qos = qos
        self.resources: dict[str, Any] = {}
        self._subscriptions: list[Subscription] = []
        self._stores: list[KtablesFanoutBatchStore] = []
        self._state = "new"  # new -> serving -> draining -> stopped
        self._advertiser: Any = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._state != "new":
            raise LifecycleConfigError(
                f"workers are single-use; this one is {self._state!r}"
            )
        try:
            await self._boot()
        except BaseException:
            logger.exception("worker boot failed; rolling back")
            await self._teardown(rollback=True)
            raise
        # atomicity-ok: workers are single-use single-owner (the guard
        # above raises on re-entry); nothing else writes _state during boot
        self._state = "serving"

    def ready(self) -> "tuple[bool, str]":
        """Readiness probe for ``MetricsServer.set_readiness``: True once
        boot finished — subscriptions registered, dispatch lanes running,
        control plane advertised.  Distinct from liveness: a worker mid-boot
        (or one that failed boot) is alive but must not receive traffic —
        and a DRAINING worker flips unready so load balancers route away
        while in-flight deliveries finish."""
        if self._state != "serving":
            return False, f"worker is {self._state}, not serving"
        return True, "serving"

    @property
    def draining(self) -> bool:
        """True once :meth:`drain` ran: the node kernel refuses NEW calls
        with a typed, retriable ``mesh.overloaded`` fault while in-flight
        deliveries (and owed returns/faults) complete normally."""
        return self._state == "draining"

    def drain(self) -> None:
        """Enter drain mode (ISSUE 5; SIGTERM does this automatically in
        :meth:`serve_forever`): ``/readyz`` flips false, new calls get
        typed ``OVERLOADED`` faults callers can retry elsewhere, in-flight
        work runs to completion.  Follow with :meth:`stop` — the
        dispatcher's graceful drain then finds empty lanes."""
        if self._state == "serving":
            self._state = "draining"
            logger.info("worker draining: new calls will be refused")

    async def _boot(self) -> None:
        await self._run_hooks(self._on_startup, phase="on_startup")
        await self._enter_resources(self.resources)
        await self.mesh.start()

        # provision every topic the nodes touch, through the classifying
        # provisioner (retry on transient broker trouble; an unauthorized
        # cluster fails loudly instead of looking flaky)
        from calfkit_tpu.provisioning import ProvisioningConfig, provision

        await provision(self.mesh, self.nodes, self.provisioning_config)
        # downstream starters (fan-out store, control plane) skip their own
        # ensure_topics when the provisioner covered the framework tables —
        # AND when provisioning is disabled outright: enabled=False is the
        # operator saying "topics pre-exist; issue no admin round-trips at
        # all" (e.g. an ACL-restricted cluster), and a raw ensure here would
        # bypass the provisioner's unauthorized/retry classification
        prov = self.provisioning_config or ProvisioningConfig()
        ensure_framework = prov.enabled and not prov.include_framework

        for node in self.nodes:
            node.bind(self.mesh)
            node.resources.setdefault("worker", self)
            if self.qos is not None:
                from calfkit_tpu.nodes.base import QOS_LIMITER_KEY

                node.resources.setdefault(QOS_LIMITER_KEY, self.qos)
            for key, value in self.resources.items():
                node.resources.setdefault(key, value)
            if FANOUT_STORE_KEY not in node.resources:
                store = KtablesFanoutBatchStore(
                    self.mesh, node.node_id, self.fanout_config
                )
                await store.start(ensure=ensure_framework)
                self._stores.append(store)
                node.resources[FANOUT_STORE_KEY] = store

        # session-backed nodes (MCP toolboxes) connect before adverts so
        # their capability records list real tools; independent handshakes
        # run in parallel
        sessions = [n for n in self.nodes if hasattr(n, "start_session")]
        if sessions:
            await asyncio.gather(*(n.start_session() for n in sessions))

        # control plane attaches BEFORE subscriptions: a delivery consumed
        # in the boot window must already find its views
        if self.control_plane is not None:
            self._advertiser = await self.control_plane.attach(
                self, ensure=ensure_framework
            )

        for node in self.nodes:
            subscribe_topics = list(node.input_topics()) + [node.return_topic()]
            subscription = await self.mesh.subscribe(
                subscribe_topics,
                node.handler,
                group_id=self.group_id or node.name,
                max_workers=self.max_workers,
            )
            self._subscriptions.append(subscription)

        await self._run_hooks(self._after_startup, phase="after_startup")

    async def stop(self) -> None:
        if self._state == "stopped":
            return
        self._state = "stopped"
        await self._teardown(rollback=False)

    async def _teardown(self, *, rollback: bool) -> None:
        with contextlib.suppress(Exception):
            await self._run_hooks(self._on_shutdown, phase="on_shutdown")
        if self._advertiser is not None:
            with contextlib.suppress(Exception):
                await self._advertiser.stop()  # tombstones before drain
            self._advertiser = None
        # swap-then-iterate (meshlint await-atomicity): detach before
        # the first await so a subscription registered mid-teardown can
        # never be dropped from a snapshot already walked
        subscriptions, self._subscriptions = self._subscriptions, []
        for subscription in subscriptions:
            with contextlib.suppress(Exception):
                await subscription.stop()
        stores, self._stores = self._stores, []
        for store in stores:
            with contextlib.suppress(Exception):
                await store.stop()
        for node in self.nodes:
            if hasattr(node, "stop_session"):
                with contextlib.suppress(Exception):
                    await node.stop_session()
        with contextlib.suppress(Exception):
            await self._run_hooks(self._after_shutdown, phase="after_shutdown")
        await self._exit_resources()
        if self.owns_transport:
            with contextlib.suppress(Exception):
                await self.mesh.stop()
        if rollback:
            self._state = "stopped"

    # --------------------------------------------------------- run surfaces
    async def __aenter__(self) -> "Worker":
        await self.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.stop()

    async def serve_forever(self) -> None:
        """Start and serve until cancelled (SIGINT/SIGTERM aware).

        SIGTERM is the orchestrator's polite eviction: it triggers drain
        mode FIRST (readiness flips, new calls fault ``OVERLOADED``) and
        then the normal stop, whose dispatcher drain lets in-flight
        deliveries finish.  SIGINT stops without the drain gate (the
        operator at the keyboard wants out now)."""
        await self.start()
        stop_event = asyncio.Event()
        loop = asyncio.get_running_loop()

        def terminate() -> None:
            self.drain()
            stop_event.set()

        with contextlib.suppress(NotImplementedError, RuntimeError):
            loop.add_signal_handler(signal.SIGINT, stop_event.set)
        with contextlib.suppress(NotImplementedError, RuntimeError):
            loop.add_signal_handler(signal.SIGTERM, terminate)
        try:
            await stop_event.wait()
        finally:
            await self.stop()

    def run(self) -> None:
        """Blocking entrypoint: boot, serve until SIGINT/SIGTERM, drain."""
        asyncio.run(self.serve_forever())
