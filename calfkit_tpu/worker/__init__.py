"""The serving host (SURVEY.md §1 layer 7)."""

from calfkit_tpu.worker.lifecycle import LifecycleHookMixin
from calfkit_tpu.worker.worker import Worker

__all__ = ["LifecycleHookMixin", "Worker"]
