"""Handoff: transfer the current obligation to another agent.

The model calls ``handoff_to_agent(agent_name)``; the node retargets its own
frame (TailCall) so the target agent replies directly to the original
caller.  Whole-response arbitration: the FIRST valid handoff in a model turn
wins; sibling tool calls in the same turn are stubbed as superseded
(reference: calfkit/peers/handoff.py:27-191, ``arbitrate_handoff`` at :162).
"""

from __future__ import annotations

from dataclasses import dataclass

from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.models.capability import ToolDef
from calfkit_tpu.models.messages import ToolCallOutput
from calfkit_tpu.peers.directory import render_directory
from calfkit_tpu.utils_names import validate_curated_or_discover

HANDOFF_TOOL = "handoff_to_agent"

# pinned model-visible strings (reference keeps these stable for the model)
SUPERSEDED_STUB = "This call was not executed: the conversation was handed off."
INVALID_TARGET = "Hand-off rejected: {name!r} is not an available agent."


class Handoff:
    kind = "handoff"

    def __init__(self, *names: str, discover: bool = False):
        validate_curated_or_discover("Handoff", names, discover)
        self.names = list(names)
        self.discover = discover

    def allowed(self, cards: list[AgentCard], self_name: str) -> list[AgentCard]:
        cards = [c for c in cards if c.name != self_name]
        if self.discover:
            return cards
        by_name = {c.name: c for c in cards}
        return [by_name[n] for n in self.names if n in by_name]

    def tool_def(self, cards: list[AgentCard], self_name: str) -> ToolDef:
        allowed = self.allowed(cards, self_name)
        names = [c.name for c in allowed]
        return ToolDef(
            name=HANDOFF_TOOL,
            description=(
                "Hand the whole conversation off to another agent; it will "
                "answer the user directly and you will not see the reply.\n"
                + render_directory(allowed)
            ),
            parameters_schema={
                "type": "object",
                "properties": {
                    "agent_name": (
                        {"type": "string", "enum": names}
                        if names
                        else {"type": "string"}
                    ),
                },
                "required": ["agent_name"],
            },
        )


@dataclass(frozen=True)
class HandoffDecision:
    winner: ToolCallOutput | None
    target: str | None
    # calls to stub as superseded (id -> stub text), incl. losing handoffs
    stubbed: dict[str, str]
    # invalid handoff attempts (id -> retry text)
    rejected: dict[str, str]


def arbitrate_handoff(
    calls: list[ToolCallOutput], allowed_names: set[str]
) -> HandoffDecision:
    """First valid handoff wins; everything else in the turn is stubbed."""
    winner: ToolCallOutput | None = None
    target: str | None = None
    stubbed: dict[str, str] = {}
    rejected: dict[str, str] = {}
    for call in calls:
        if call.tool_name != HANDOFF_TOOL:
            continue
        try:
            name = call.args_dict().get("agent_name")
        except ValueError:
            name = None
        if winner is not None:
            stubbed[call.tool_call_id] = SUPERSEDED_STUB
            continue
        if isinstance(name, str) and name in allowed_names:
            winner = call
            target = name
        else:
            rejected[call.tool_call_id] = INVALID_TARGET.format(name=name)
    if winner is not None:
        for call in calls:
            if call.tool_name != HANDOFF_TOOL:
                stubbed.setdefault(call.tool_call_id, SUPERSEDED_STUB)
    return HandoffDecision(
        winner=winner, target=target, stubbed=stubbed, rejected=rejected
    )
