"""Live-peer directory rendering (reference: calfkit/peers/directory.py)."""

from __future__ import annotations

from calfkit_tpu.models.agents import AgentCard


def render_directory(cards: list[AgentCard]) -> str:
    if not cards:
        return "No agents are currently available."
    lines = ["Available agents:"]
    for card in sorted(cards, key=lambda c: c.name):
        description = card.description or "(no description)"
        lines.append(f"- {card.name}: {description}")
    return "\n".join(lines)
