"""Agent-to-agent capabilities (SURVEY.md §1 layer 6)."""

from calfkit_tpu.peers.handoff import HANDOFF_TOOL, Handoff, arbitrate_handoff
from calfkit_tpu.peers.messaging import MESSAGE_AGENT_TOOL, Messaging
from calfkit_tpu.peers.directory import render_directory

__all__ = [
    "HANDOFF_TOOL",
    "Handoff",
    "MESSAGE_AGENT_TOOL",
    "Messaging",
    "arbitrate_handoff",
    "render_directory",
]
