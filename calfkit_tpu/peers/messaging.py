"""Messaging: the ``message_agent`` built-in peer capability.

The model calls ``message_agent(agent_name, message)``; the agent node
dispatches it as an isolated-state Call to the target agent's input topic
(a degenerate durable batch — the caller's conversation never leaks to the
callee, and the caller's state survives outside the wire).  Reference:
calfkit/peers/messaging.py:12 + nodes/agent.py:540.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.models.capability import ToolDef
from calfkit_tpu.peers.directory import render_directory
from calfkit_tpu.utils_names import validate_curated_or_discover

if TYPE_CHECKING:
    pass

MESSAGE_AGENT_TOOL = "message_agent"


class Messaging:
    """Curated names XOR discover: which live agents this agent may message."""

    kind = "messaging"

    def __init__(self, *names: str, discover: bool = False):
        validate_curated_or_discover("Messaging", names, discover)
        self.names = list(names)
        self.discover = discover

    def allowed(self, cards: list[AgentCard], self_name: str) -> list[AgentCard]:
        cards = [c for c in cards if c.name != self_name]
        if self.discover:
            return cards
        by_name = {c.name: c for c in cards}
        return [by_name[n] for n in self.names if n in by_name]

    def tool_def(self, cards: list[AgentCard], self_name: str) -> ToolDef:
        allowed = self.allowed(cards, self_name)
        names = [c.name for c in allowed]
        return ToolDef(
            name=MESSAGE_AGENT_TOOL,
            description=(
                "Send a message to another agent and wait for its reply.\n"
                + render_directory(allowed)
            ),
            parameters_schema={
                "type": "object",
                "properties": {
                    "agent_name": (
                        {"type": "string", "enum": names}
                        if names
                        else {"type": "string"}
                    ),
                    "message": {"type": "string"},
                },
                "required": ["agent_name", "message"],
            },
        )
