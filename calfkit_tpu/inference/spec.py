"""Speculative-decoding drafters (the proposal half of the scheme).

The engine's spec tick is drafter-agnostic: each tick it asks the drafter
for up to ``k`` candidate tokens per active request, then a single verify
dispatch scores all k+1 positions against the paged/dense KV cache and
accepts a (possibly empty) prefix per row — see
``engine.InferenceEngine._spec_decode_tick`` and
``sampler.spec_accept_slots``.  Drafters only PROPOSE; correctness never
depends on them (a useless drafter just degrades to ~1 token/dispatch).

Two implementations behind one protocol:

- :class:`NgramDrafter` — prompt-lookup decoding: match the tail of the
  generated sequence against the prompt + generated history and propose
  the continuation of the most recent earlier occurrence.  Zero weights,
  zero device work, pure host.  This is the agent-serving drafter: tool
  schemas, quoted documents, and repeated instruction blocks make the
  history highly self-similar, exactly where lookup hits.
- :class:`DraftModelDrafter` — a second, smaller model proposes greedily.
  Loaded through the SAME init/sharding path as the target
  (``param_shardings``/``place_params``; pass real checkpoint params via
  ``InferenceEngine(draft_params=...)`` using the existing loader).  It
  keeps its own dense KV cache per slot and catches up on whatever the
  target emitted since its last call — rejected speculation simply gets
  overwritten by the next catch-up chunk (same garbage-beyond-length
  tolerance as the main cache).

Interplay with overlapped execution (``RuntimeConfig.overlap_dispatch``):
the spec tick stays LOCKSTEP.  Both drafters propose from the landed
token history, so there is nothing correct to pre-launch before the
previous verify dispatch syncs — pre-launching with a stale history
would draft continuations of a position the device has already moved
past, collapsing acceptance to ~0 while still paying the k+1-wide
dispatch.  What speculation does share with the overlap scheme is the
device-side retirement mask: the verify jit returns per-row
``(n_valid, done)`` via the same ``sampler.retire_mask_slots``, so stop
tokens and generation bounds are classified once, on device, in both
modes.
"""

from __future__ import annotations

import logging
from typing import Any, Protocol

import numpy as np

from calfkit_tpu.inference.config import ModelConfig, RuntimeConfig, SpecConfig

logger = logging.getLogger(__name__)


class Drafter(Protocol):
    """What the engine's spec tick needs from a proposal source."""

    k: int

    def admit(self, slot: int, prompt: list[int]) -> None:
        """A request was activated into ``slot``."""

    def retire(self, slot: int) -> None:
        """``slot``'s request retired (or was cancelled)."""

    def propose(
        self, requests: "list[tuple[int, list[int]]]"
    ) -> "list[list[int]]":
        """Per (slot, token history) entry: up to ``k`` draft tokens for
        the positions after the history's final token.  Fewer (or zero)
        proposals are fine — the verify wave pads and masks."""


class NgramDrafter:
    """Prompt-lookup drafting: propose the continuation of the most recent
    earlier occurrence of the sequence tail.

    Longest tails first (``ngram_max`` down to ``ngram_min``): a longer
    match carries more context and is less likely to propose a spurious
    continuation.  The search runs over the int32 byte view so the hot
    path is C-speed ``bytes.rfind``, alignment-checked (a byte-level hit
    must fall on a 4-byte token boundary to be a token-level hit).  The
    byte view is kept INCREMENTALLY per slot (appended as history grows)
    — rebuilding it from the token list each wave would be an O(history)
    host cost per row per tick on the scheduler's latency path.
    """

    def __init__(self, spec: SpecConfig):
        self.k = spec.k
        self.ngram_max = max(1, spec.ngram_max)
        self.ngram_min = max(1, min(spec.ngram_min, self.ngram_max))
        self._bufs: dict[int, bytearray] = {}  # slot -> history byte view

    def admit(self, slot: int, prompt: "list[int]") -> None:
        self._bufs[slot] = bytearray()

    def retire(self, slot: int) -> None:
        self._bufs.pop(slot, None)

    def _slot_bytes(self, slot: int, history: "list[int]") -> bytearray:
        # returned WITHOUT copying: rfind/slicing work on bytearray, and a
        # bytes(...) wrap here would reintroduce the O(history) per-tick
        # cost the incremental buffer exists to avoid
        buf = self._bufs.setdefault(slot, bytearray())
        synced = len(buf) // 4
        if synced > len(history):  # defensive: slot reused without admit()
            buf.clear()
            synced = 0
        if synced < len(history):
            # blocking-ok: host token LIST → bytes (incremental n-gram
            # buffer), never a device array — nothing syncs
            buf += np.asarray(history[synced:], np.int32).tobytes()
        return buf

    def _lookup(self, buf: "bytearray", history: "list[int]") -> "list[int]":
        L = len(history)
        if L < 2:
            return []
        for n in range(min(self.ngram_max, L - 1), self.ngram_min - 1, -1):
            tail = buf[(L - n) * 4 :]
            # rightmost earlier occurrence, excluding the tail matching
            # itself; byte hits must land on token boundaries
            end = (L - 1) * 4  # candidate start strictly before L - n
            while end >= n * 4:
                hit = buf.rfind(tail, 0, end)
                if hit < 0:
                    break
                if hit % 4 == 0:
                    # the end bound forces start <= L-1, so at least one
                    # continuation token always exists
                    start = hit // 4 + n
                    return history[start : start + self.k]
                end = hit + len(tail) - 1
        return []

    def propose(
        self, requests: "list[tuple[int, list[int]]]"
    ) -> "list[list[int]]":
        return [
            self._lookup(self._slot_bytes(slot, history), history)
            for slot, history in requests
        ]


class DraftModelDrafter:
    """A second, smaller model drafting greedily from its own dense KV.

    State contract: ``_dlen[slot]`` tokens of the request's history are in
    the draft cache.  Each ``propose`` feeds the catch-up delta
    (``history[_dlen:]`` — the tokens the target emitted since last time,
    padded to a power-of-two bucket so compile count stays logarithmic),
    then rolls ``k`` greedy steps.  Draft K/V written during speculation
    sits beyond ``_dlen`` after the call and is overwritten by the next
    catch-up — rejections cost nothing to roll back, mirroring the target
    cache's scheme.
    """

    def __init__(
        self,
        spec: SpecConfig,
        runtime: RuntimeConfig,
        mesh: Any,
        params: Any = None,
        seed: int = 17,
    ):
        import jax
        import jax.numpy as jnp

        from calfkit_tpu.inference import model as M
        from calfkit_tpu.inference.sharding import (
            cache_sharding,
            param_shardings,
            place_params,
        )

        assert spec.draft is not None
        self.k = spec.k
        self.config: ModelConfig = spec.draft
        self._runtime = runtime
        if params is None:
            # correctness never depends on the draft, but RANDOM draft
            # weights mean ~0 acceptance while still paying every draft
            # forward — worse than speculation off.  Loud, not silent.
            logger.warning(
                "draft model %s initialized with RANDOM weights — pass "
                "draft_params (engine) / draft_checkpoint (client) for a "
                "real drafter; expect ~zero acceptance until then",
                self.config.name,
            )
            params = M.init_params(self.config, jax.random.key(seed))
        self.params = place_params(
            params, param_shardings(self.config, mesh)
        )
        B, S = runtime.max_batch_size, runtime.max_seq_len
        cfg = self.config
        self._kc = jax.device_put(
            jnp.zeros(
                (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim),
                jnp.dtype(cfg.dtype),
            ),
            cache_sharding(cfg, mesh, B),
        )
        self._vc = jax.device_put(
            jnp.zeros_like(self._kc), cache_sharding(cfg, mesh, B)
        )
        self._dlen = np.zeros((B,), np.int64)
        self._jits: dict[int, Any] = {}

    def admit(self, slot: int, prompt: "list[int]") -> None:
        # lazy: the first propose's catch-up covers the whole prompt
        self._dlen[slot] = 0

    def retire(self, slot: int) -> None:
        self._dlen[slot] = 0

    def _propose_jit(self, width: int) -> Any:
        """One compile per catch-up bucket: forward the [B, width] catch-up
        chunk at per-row offsets, then k greedy single-token steps."""
        fn = self._jits.get(width)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from calfkit_tpu.inference import model as M

        cfg = self.config
        k_steps = self.k

        def propose(params, kc, vc, catchup, base, cat_len):
            B = base.shape[0]
            pos = base[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
            seq_lens = base + cat_len
            logits, (kc, vc) = M.forward(
                params, cfg, catchup, pos, (kc, vc), seq_lens,
                unroll=True, insert_at=base,
            )
            idx = jnp.clip(cat_len - 1, 0, width - 1)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1
            )[:, 0]
            cur = jnp.argmax(last, axis=-1).astype(jnp.int32)
            outs = [cur]
            lens = seq_lens
            for _ in range(k_steps - 1):
                logits, (kc, vc) = M.forward(
                    params, cfg, cur[:, None], lens[:, None], (kc, vc),
                    lens + 1, unroll=True,
                )
                cur = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                outs.append(cur)
                lens = lens + 1
            return kc, vc, jnp.stack(outs, axis=1)  # [B, k]

        fn = jax.jit(propose, donate_argnums=(1, 2))
        self._jits[width] = fn
        return fn

    def propose(
        self, requests: "list[tuple[int, list[int]]]"
    ) -> "list[list[int]]":
        import jax.numpy as jnp

        if not requests:
            return []
        B = self._runtime.max_batch_size
        S = self._runtime.max_seq_len
        deltas = [
            len(history) - int(self._dlen[slot]) for slot, history in requests
        ]
        width = 1
        while width < max(max(deltas), 1):
            width *= 2
        # the catch-up bucket can never exceed the draft cache (a non-
        # power-of-two max_seq_len would otherwise overflow it); a row
        # whose delta still exceeds the clamped width feeds only its TAIL
        # — proposals degrade, verified output never depends on them
        width = min(width, S)
        catchup = np.zeros((B, width), np.int32)
        base = np.zeros((B,), np.int32)
        cat_len = np.zeros((B,), np.int32)
        live: list[tuple[int, int]] = []  # (slot, room) rows actually fed
        for (slot, history), delta in zip(requests, deltas):
            if delta <= 0:  # defensive: history never shrinks mid-request
                continue
            d = int(self._dlen[slot])
            if delta > width:
                d = len(history) - width
                delta = width
            elif d + width > S:
                # the batch-wide width bucket would overhang this row's
                # cache end and dynamic_update_slice CLAMPS the start
                # backward — which would overwrite valid early positions
                # with wrong-position K/V.  Re-feed from S - width
                # instead: positions [d, dlen) rewrite identically,
                # positions before d stay untouched, nothing clamps.
                d = max(0, S - width)
                delta = len(history) - d
            catchup[slot, :delta] = history[d:]
            base[slot] = d
            cat_len[slot] = delta
            self._dlen[slot] = len(history)
            # a draft would write beyond the draft cache near the end of a
            # sequence's life; cap proposals by the cache room instead
            live.append((slot, S - len(history) - 1))
        fn = self._propose_jit(width)
        self._kc, self._vc, drafts = fn(
            self.params, self._kc, self._vc,
            jnp.asarray(catchup), jnp.asarray(base), jnp.asarray(cat_len),
        )
        # blocking-ok: the spec tick is lockstep BY DESIGN — the host
        # drafter must read the draft model's tokens before the verify
        # dispatch can be formed (see ISSUE 3: spec stays lockstep)
        drafts = np.asarray(drafts)
        by_slot = {
            slot: [int(t) for t in drafts[slot, : max(0, min(self.k, room))]]
            for slot, room in live
        }
        return [by_slot.get(slot, []) for slot, _ in requests]


def build_drafter(
    spec: SpecConfig,
    runtime: RuntimeConfig,
    mesh: Any,
    draft_params: Any = None,
    seed: int = 17,
) -> Drafter:
    if spec.draft is not None:
        return DraftModelDrafter(
            spec, runtime, mesh, params=draft_params, seed=seed
        )
    return NgramDrafter(spec)
