"""Pure-functional Llama-family decoder in JAX.

TPU-first design decisions (NOT a port of any torch modeling file):

- params are a plain pytree of ``jax.Array`` so GSPMD shardings attach
  directly (see :mod:`calfkit_tpu.inference.sharding`);
- the whole forward is expressed in batched einsums — every FLOP lands on
  the MXU; no data-dependent Python control flow anywhere under ``jit``;
- layers run under ``lax.scan`` over a stacked-parameter pytree, so compile
  time is O(1) in depth and XLA schedules one fused layer body;
- KV cache updates are functional (``dynamic_update_slice``) — the engine
  owns cache buffers and threads them through jit;
- attention is GQA with a pluggable core: the XLA einsum path (fallback,
  differentiable, CPU-testable) or the Pallas paged kernel (decode hot path).

Weight layout (per layer, stacked on axis 0 across layers):
    attn: wq [L, D, H, hd], wk/wv [L, D, K, hd], wo [L, H, hd, D]
    mlp:  w_gate/w_up [L, D, F], w_down [L, F, D]
    norms: attn_norm/mlp_norm [L, D]
    top:   embed [V, D], final_norm [D], lm_head [D, V] (absent when tied)
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from calfkit_tpu.inference.config import ModelConfig
from calfkit_tpu.inference.quant import dequant as _w

Params = dict[str, Any]


# --------------------------------------------------------------------------- #
# init
# --------------------------------------------------------------------------- #


def init_params(config: ModelConfig, key: jax.Array, dtype: Any = None) -> Params:
    """Random-init params (He-ish scaling); the loader overwrites these with
    checkpoint weights when one is given."""
    dtype = dtype or jnp.dtype(config.dtype)
    L, D, H, K, hd, F, V = (
        config.n_layers,
        config.d_model,
        config.n_heads,
        config.n_kv_heads,
        config.head_dim,
        config.d_ff,
        config.vocab_size,
    )
    keys = jax.random.split(key, 8)

    def norm_init(k, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params: Params = {
        "embed": norm_init(keys[0], (V, D), D),
        "layers": {
            "wq": norm_init(keys[1], (L, D, H, hd), D),
            "wk": norm_init(keys[2], (L, D, K, hd), D),
            "wv": norm_init(keys[3], (L, D, K, hd), D),
            "wo": norm_init(keys[4], (L, H, hd, D), H * hd),
            "w_gate": norm_init(keys[5], (L, D, F), D),
            "w_up": norm_init(keys[6], (L, D, F), D),
            "w_down": norm_init(keys[7], (L, F, D), F),
            "attn_norm": jnp.ones((L, D), dtype),
            "mlp_norm": jnp.ones((L, D), dtype),
        },
        "final_norm": jnp.ones((D,), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = norm_init(jax.random.split(keys[0])[0], (D, V), D)
    return params


# --------------------------------------------------------------------------- #
# building blocks
# --------------------------------------------------------------------------- #


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(orig_dtype)


def rope_tables(
    positions: jax.Array, head_dim: int, theta: float
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for ``positions`` [..., seq] → [..., seq, hd/2]."""
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions[..., None].astype(jnp.float32) * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs. x: [B, S, N, hd]; cos/sin: [B, S, hd/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _einsum_f32(spec: str, a: jax.Array, b: jax.Array) -> jax.Array:
    """einsum with fp32 accumulation.  TPU: ``preferred_element_type`` (MXU
    accumulates fp32 natively, no input copies).  CPU XLA lacks the
    bf16×bf16→f32 dot kernel, so inputs upcast there (tests only)."""
    if a.dtype == jnp.bfloat16 and jax.default_backend() == "cpu":
        return jnp.einsum(spec, a.astype(jnp.float32), b.astype(jnp.float32))
    return jnp.einsum(spec, a, b, preferred_element_type=jnp.float32)


def _gqa_scores_mask(
    q_pos: jax.Array, kv_len: int, seq_lens: jax.Array
) -> jax.Array:
    """Causal + length mask [B, Sq, Skv] (True = attendable)."""
    kv_pos = jnp.arange(kv_len)[None, None, :]
    causal = kv_pos <= q_pos[:, :, None]
    valid = kv_pos < seq_lens[:, None, None]
    return causal & valid


def attn_qkv(
    x: jax.Array,  # [B, S, D]
    lp: Params,  # one layer's params
    cos: jax.Array,
    sin: jax.Array,
    eps: float,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The block's attention front half: norm → QKV projections → rope.

    Shared by prefill, decode, and the sequence-parallel ring — ONE place
    for the projection math.
    """
    h = rms_norm(x, lp["attn_norm"], eps)
    q = jnp.einsum("bsd,dnh->bsnh", h, _w(lp["wq"]))
    k = jnp.einsum("bsd,dkh->bskh", h, _w(lp["wk"]))
    v = jnp.einsum("bsd,dkh->bskh", h, _w(lp["wv"]))
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v


def attn_out_mlp(
    x: jax.Array,  # [B, S, D] residual stream
    attn: jax.Array,  # [B, S, H, hd]
    lp: Params,
    eps: float,
) -> jax.Array:
    """The block's back half: output projection + residual + SwiGLU MLP."""
    x = x + jnp.einsum("bsnh,nhd->bsd", attn, _w(lp["wo"]))
    h = rms_norm(x, lp["mlp_norm"], eps)
    gate = jnp.einsum("bsd,df->bsf", h, _w(lp["w_gate"]))
    up = jnp.einsum("bsd,df->bsf", h, _w(lp["w_up"]))
    return x + jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up, _w(lp["w_down"]))


def lm_logits(x: jax.Array, params: Params, eps: float) -> jax.Array:
    """Final norm + (tied or untied) LM head."""
    x = rms_norm(x, params["final_norm"], eps)
    head = params.get("lm_head")
    if head is None:
        return jnp.einsum("bsd,vd->bsv", x, params["embed"])
    return jnp.einsum("bsd,dv->bsv", x, _w(head))


def attention_xla(
    q: jax.Array,  # [B, Sq, H, hd]
    k_cache: jax.Array,  # [B, K, Skv, hd]  (kv-head-major: contiguous scans)
    v_cache: jax.Array,  # [B, K, Skv, hd]
    q_pos: jax.Array,  # [B, Sq] absolute positions of the queries
    seq_lens: jax.Array,  # [B] total valid kv per sequence
) -> jax.Array:
    """GQA attention over the cache, masked by position/length.

    The XLA path: one batched einsum pair the compiler fuses tightly; used
    for prefill everywhere and decode when the Pallas kernel is off.
    The cache is kv-head-major ([B, K, S, hd]) so each head's scan over S is
    a contiguous HBM stream, and accumulation is fp32 via
    ``preferred_element_type`` — the bf16 cache is never materialized as an
    fp32 copy (HBM is the decode bottleneck).
    """
    B, Sq, H, hd = q.shape
    K = k_cache.shape[1]
    G = H // K  # query heads per kv head
    scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(B, Sq, K, G, hd)
    scores = _einsum_f32("bqkgh,bksh->bkgqs", qg, k_cache) * scale
    mask = _gqa_scores_mask(q_pos, k_cache.shape[2], seq_lens)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(k_cache.dtype)
    out = _einsum_f32("bkgqs,bksh->bqkgh", probs, v_cache)
    return out.reshape(B, Sq, H, hd).astype(q.dtype)


def prefill_attention(
    q: jax.Array,  # [B, Sq, H, hd]
    k_cache: jax.Array,  # [B, K, Skv, hd]
    v_cache: jax.Array,
    q_pos: jax.Array,  # [B, Sq]
    seq_lens: jax.Array,  # [B]
    *,
    attn_impl: str = "xla",
) -> jax.Array:
    """Prefill attention dispatch: the Pallas flash kernel when opted in
    and the shapes are block-eligible, else the XLA einsum path.

    The flash kernel never materializes the [Sq, Skv] score matrix, so
    long-chunk prefill stays VMEM-resident; eligibility mirrors the
    engine's power-of-two chunk/bucket grammar (see
    pallas_attention.prefill_attention_pallas).
    """
    if attn_impl.startswith("pallas"):
        from calfkit_tpu.inference.pallas_attention import (
            PREFILL_BLOCK_Q,
            PREFILL_KV_CHUNK,
            prefill_attention_pallas,
        )

        Sq, Skv = q.shape[1], k_cache.shape[2]
        if (
            Sq % min(PREFILL_BLOCK_Q, Sq) == 0
            and Skv % min(PREFILL_KV_CHUNK, Skv) == 0
        ):

            return prefill_attention_pallas(
                q, k_cache, v_cache, q_pos, seq_lens,
                interpret=attn_impl == "pallas_interpret",
            )
    return attention_xla(q, k_cache, v_cache, q_pos, seq_lens)


# --------------------------------------------------------------------------- #
# the transformer
# --------------------------------------------------------------------------- #


def forward(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [B, S] int32
    positions: jax.Array,  # [B, S] absolute positions
    kv_cache: tuple[jax.Array, jax.Array] | None,  # ([L,B,K,Smax,hd], ...)
    seq_lens: jax.Array,  # [B] kv length AFTER inserting this chunk
    attn_window: int | None = None,  # static: attend only cache[..., :W, :]
    unroll: bool = False,  # static: python layer loop (the decode hot path)
    attn_impl: str = "xla",  # static: "xla" | "pallas" | "pallas_interpret"
    insert_at: jax.Array | None = None,  # [B] explicit per-row write offset
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """Run the decoder over a token chunk, updating the cache functionally.

    Works for prefill (S = prompt chunk) and decode (S = 1) alike; the
    engine jits specializations per shape/window.  ``attn_window`` bounds
    the attention scan to the first W cache positions — the engine picks the
    smallest bucket covering every live sequence, so short conversations
    never pay full-``max_seq`` HBM reads.

    ``unroll=True`` trades compile time for the decode-critical memory
    pattern: layers indexed statically, so the chunk's K/V writes land
    in-place in the donated cache (bytes ∝ chunk) instead of round-tripping
    a full 2×[B,K,S,hd] page per layer through a scan carry (measured ~2x
    end-to-end decode slowdown).  Returns (logits, new_cache).
    """
    eps = config.norm_eps
    x = params["embed"][tokens]  # [B, S, D] gather
    cos, sin = rope_tables(positions, config.head_dim, config.rope_theta)
    if insert_at is None:
        # default: the chunk is fully valid and ends at seq_lens.  An
        # explicit insert_at serves RAGGED chunks (speculative draft
        # catch-up: per-row valid lengths shorter than the padded width)
        insert_at = seq_lens - tokens.shape[1]  # where this chunk lands

    layer_params = params["layers"]
    k_pages, v_pages = kv_cache  # [L, B, K, Smax, hd]
    W = attn_window or k_pages.shape[3]

    def layer_math(x, lp, k_page, v_page):
        """One block given this layer's cache page; returns (x, k, v chunk).

        The caller owns how pages are read/written (scan carry vs static).
        """
        q, k, v = attn_qkv(x, lp, cos, sin, eps)
        k_page = _insert_chunk(k_page, k, insert_at)
        v_page = _insert_chunk(v_page, v, insert_at)
        attn = prefill_attention(
            q, k_page[:, :, :W], v_page[:, :, :W], positions, seq_lens,
            attn_impl=attn_impl,
        )
        return attn_out_mlp(x, attn, lp, eps), k_page, v_page

    if unroll:
        new_k, new_v = k_pages, v_pages
        for i in range(config.n_layers):
            lp = jax.tree.map(lambda a: a[i], layer_params)
            x, k_page, v_page = layer_math(x, lp, new_k[i], new_v[i])
            new_k = new_k.at[i].set(k_page)
            new_v = new_v.at[i].set(v_page)
    else:
        def layer_body(carry, lp):
            x, k_all, v_all, i = carry
            k_page = lax.dynamic_index_in_dim(k_all, i, 0, keepdims=False)
            v_page = lax.dynamic_index_in_dim(v_all, i, 0, keepdims=False)
            x, k_page, v_page = layer_math(x, lp, k_page, v_page)
            k_all = lax.dynamic_update_index_in_dim(k_all, k_page, i, 0)
            v_all = lax.dynamic_update_index_in_dim(v_all, v_page, i, 0)
            return (x, k_all, v_all, i + 1), None

        (x, new_k, new_v, _), _ = lax.scan(
            layer_body, (x, k_pages, v_pages, jnp.int32(0)), layer_params
        )
    logits = lm_logits(x, params, eps)
    return logits, (new_k, new_v)


def _decode_step_with_ring(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [B, 1]
    ring: tuple[jax.Array, jax.Array],  # [L, T, B, K, hd] fresh-token ring
    t: jax.Array,  # scalar: this dispatch's step index (ring write slot)
    base_lens: jax.Array,  # [B]
    attn_source: Any,  # (i, q, ring_k_i, ring_v_i) -> attn [B, 1, H, hd]
    scan_xs: Any,  # extra per-layer scan inputs threaded to attn_source
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """The shared decode-step transformer body (ring-buffer scheme).

    Why a ring: per-token scatters into the main cache cost ~10ms/step on
    TPU (measured, TinyLlama bs=64) — scatter with per-row offsets is the
    single most expensive op in naive decode.  Here every step writes its
    K/V *densely* at ring slot ``t`` (same index for all rows: one cheap
    dynamic_update_index), attention merges (main cache ⊕ ring) with a
    flash-style logsumexp combine, and the consolidate function writes the
    whole dispatch's tokens back in one amortized pass.

    The main-cache read is the ONLY thing the dense and paged layouts do
    differently, so it arrives as ``attn_source`` (with its per-layer scan
    inputs in ``scan_xs``); everything else lives once, here.

    Layers run via scan: main-cache buffers are read-only scan inputs or
    closed-over invariants (no carry round-trip), only the small ring
    travels in the carry.  An unrolled python loop has the same memory
    pattern but compiles ~10x slower for deep models.
    """
    eps = config.norm_eps
    positions = (base_lens + t)[:, None]  # [B, 1] absolute position
    x = params["embed"][tokens]
    cos, sin = rope_tables(positions, config.head_dim, config.rope_theta)
    ring_k, ring_v = ring

    def layer_body(carry, inputs):
        x, ring_k, ring_v, i = carry
        lp, extra = inputs
        q, k, v = attn_qkv(x, lp, cos, sin, eps)
        # dense ring write at (layer i, slot t) — no scatter anywhere
        slab = k[:, 0].astype(ring_k.dtype)[None, None]
        ring_k = lax.dynamic_update_slice(ring_k, slab, (i, t, 0, 0, 0))
        slab = v[:, 0].astype(ring_v.dtype)[None, None]
        ring_v = lax.dynamic_update_slice(ring_v, slab, (i, t, 0, 0, 0))
        attn = attn_source(
            i,
            q,
            lax.dynamic_index_in_dim(ring_k, i, 0, keepdims=False),
            lax.dynamic_index_in_dim(ring_v, i, 0, keepdims=False),
            extra,
        )
        return (attn_out_mlp(x, attn, lp, eps), ring_k, ring_v, i + 1), None

    (x, ring_k, ring_v, _), _ = lax.scan(
        layer_body,
        (x, ring_k, ring_v, jnp.int32(0)),
        (params["layers"], scan_xs),
    )
    logits = lm_logits(x, params, eps)
    return logits, (ring_k, ring_v)


def decode_step_ring(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [B, 1]
    kv_cache: tuple[jax.Array, jax.Array],  # main pages, READ-ONLY here
    ring: tuple[jax.Array, jax.Array],  # [L, T, B, K, hd] fresh-token ring
    t: jax.Array,  # scalar: this dispatch's step index (ring write slot)
    base_lens: jax.Array,  # [B] kv length at dispatch start (main cache)
    attn_window: int | None = None,
    attn_impl: str = "xla",  # static: "xla" | "pallas" | "pallas_interpret"
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """One decode step over the dense [L, B, K, S, hd] cache layout."""
    k_pages, v_pages = kv_cache
    W = attn_window or k_pages.shape[3]

    def attn_source(i, q, rk, rv, extra):
        k_page, v_page = extra
        attn_args = (q, k_page[:, :, :W], v_page[:, :, :W], rk, rv, base_lens, t)
        if attn_impl.startswith("pallas"):
            from calfkit_tpu.inference.pallas_attention import (
                merged_decode_attention_pallas,
            )

            return merged_decode_attention_pallas(
                *attn_args, interpret=attn_impl == "pallas_interpret"
            )
        return _merged_decode_attention(*attn_args)

    return _decode_step_with_ring(
        params, config, tokens, ring, t, base_lens, attn_source,
        (k_pages, v_pages),
    )


def _merged_decode_attention(
    q: jax.Array,  # [B, 1, H, hd]
    k_cache: jax.Array,  # [B, K, W, hd] main pages (stale within dispatch)
    v_cache: jax.Array,
    ring_k: jax.Array,  # [T, B, K, hd] this layer's ring
    ring_v: jax.Array,
    base_lens: jax.Array,  # [B]
    t: jax.Array,  # current step (ring slots 0..t valid)
) -> jax.Array:
    """Softmax over (main cache ⊕ ring) via a two-source logsumexp merge."""
    B, _, H, hd = q.shape
    K = k_cache.shape[1]
    G = H // K
    qg = q.reshape(B, K, G, hd)

    # source 1: the main cache
    valid1 = jnp.arange(k_cache.shape[2])[None, :] < base_lens[:, None]
    o1, m1, z1 = masked_attention_source(qg, k_cache, v_cache, valid1)

    o2, m2, z2 = ring_attention_source(qg, ring_k, ring_v, t)
    out = logsumexp_merge((o1, m1, z1), (o2, m2, z2))
    return out.reshape(B, 1, H, hd).astype(q.dtype)


def masked_attention_source(
    qg: jax.Array,  # [B, K, G, hd] (unscaled)
    k_cache: jax.Array,  # [B, K, S, hd]
    v_cache: jax.Array,
    valid: jax.Array,  # [B, S] bool — attendable positions
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One masked flash-stats attention source → (o unnormalized, m, z).

    The numerically delicate idiom (-1e30 mask → running max → -1e29
    finite-floor clamp → exp/z) lives HERE once; the dense decode merge and
    the context-parallel shard source both call it.
    """
    scale = 1.0 / math.sqrt(qg.shape[-1])
    s = _einsum_f32("bkgh,bksh->bkgs", qg, k_cache) * scale
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, -1e29)  # fully-masked rows stay finite
    p = jnp.exp(s - m).astype(k_cache.dtype)
    z = jnp.sum(p.astype(jnp.float32), axis=-1, keepdims=True)
    o = _einsum_f32("bkgs,bksh->bkgh", p, v_cache)
    return o, m, z


def ring_attention_source(
    qg: jax.Array,  # [B, K, G, hd]
    ring_k: jax.Array,  # [T, B, K, hd]
    ring_v: jax.Array,
    t: jax.Array,  # ring slots 0..t valid
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The fresh-token attention source (tiny: T ≤ steps-per-dispatch) →
    (o unnormalized, m, z) — shared by the XLA and Pallas merged paths."""
    T = ring_k.shape[0]
    scale = 1.0 / math.sqrt(qg.shape[-1])
    s2 = _einsum_f32("bkgh,tbkh->bkgt", qg, ring_k) * scale  # [B,K,G,T]
    valid2 = (jnp.arange(T) <= t).reshape(1, 1, 1, T)
    s2 = jnp.where(valid2, s2, -1e30)
    m2 = jnp.max(s2, axis=-1, keepdims=True)
    p2 = jnp.exp(s2 - m2).astype(ring_k.dtype)
    z2 = jnp.sum(p2.astype(jnp.float32), axis=-1, keepdims=True)
    o2 = _einsum_f32("bkgt,tbkh->bkgh", p2, ring_v)
    return o2, m2, z2


def logsumexp_merge(
    a: tuple[jax.Array, jax.Array, jax.Array],
    b: tuple[jax.Array, jax.Array, jax.Array],
) -> jax.Array:
    """Combine two (o unnormalized, m, z) attention sources."""
    o1, m1, z1 = a
    o2, m2, z2 = b
    m = jnp.maximum(m1, m2)
    w1 = jnp.exp(m1 - m)
    w2 = jnp.exp(m2 - m)
    return (o1 * w1 + o2 * w2) / (z1 * w1 + z2 * w2)


def _verify_step_with_ring(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [B, S] fed tokens: [last, d_0, .., d_{S-2}]
    base_lens: jax.Array,  # [B] kv length at dispatch start
    ring_dtype: Any,
    attn_source: Any,  # (i, q [B,S,H,hd], rk, rv, extra) -> [B, S, H, hd]
    scan_xs: Any,
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """The shared speculative-VERIFY transformer body.

    Structurally :func:`_decode_step_with_ring` generalized from one query
    to S = k+1 queries per row: the whole drafted chunk runs as ONE forward
    (this is the point — the full weight read is amortized over every
    accepted token), its K/V lands densely in a chunk ring (slot j = the
    token at position ``base_lens + j``), attention merges (main cache ⊕
    causal chunk), and the caller consolidates the ring exactly like a
    decode dispatch — so ragged acceptance needs NO physical rollback:
    rejected slots simply sit beyond the advanced ``lens`` and the next
    wave overwrites them.
    """
    eps = config.norm_eps
    B, S = tokens.shape
    positions = base_lens[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    x = params["embed"][tokens]
    cos, sin = rope_tables(positions, config.head_dim, config.rope_theta)
    ring_shape = (config.n_layers, S, B, config.n_kv_heads, config.head_dim)
    ring_k = jnp.zeros(ring_shape, ring_dtype)
    ring_v = jnp.zeros(ring_shape, ring_dtype)

    def layer_body(carry, inputs):
        x, ring_k, ring_v, i = carry
        lp, extra = inputs
        q, k, v = attn_qkv(x, lp, cos, sin, eps)
        # [B, S, K, hd] -> ring layout [S, B, K, hd], written densely at
        # layer i — same no-scatter scheme as the decode ring
        slab = jnp.swapaxes(k, 0, 1).astype(ring_k.dtype)[None]
        ring_k = lax.dynamic_update_slice(ring_k, slab, (i, 0, 0, 0, 0))
        slab = jnp.swapaxes(v, 0, 1).astype(ring_v.dtype)[None]
        ring_v = lax.dynamic_update_slice(ring_v, slab, (i, 0, 0, 0, 0))
        attn = attn_source(
            i,
            q,
            lax.dynamic_index_in_dim(ring_k, i, 0, keepdims=False),
            lax.dynamic_index_in_dim(ring_v, i, 0, keepdims=False),
            extra,
        )
        return (attn_out_mlp(x, attn, lp, eps), ring_k, ring_v, i + 1), None

    (x, ring_k, ring_v, _), _ = lax.scan(
        layer_body,
        (x, ring_k, ring_v, jnp.int32(0)),
        (params["layers"], scan_xs),
    )
    logits = lm_logits(x, params, eps)
    return logits, (ring_k, ring_v)  # logits [B, S, V]


def ragged_attention_source(
    qg: jax.Array,  # [B, S, K, G, hd] multi-query, kv-grouped (unscaled)
    k_cache: jax.Array,  # [B, K, W, hd]
    v_cache: jax.Array,
    q_starts: jax.Array,  # [B] absolute position of each row's query 0
    kv_lens: jax.Array,  # [B] valid kv length each row may attend
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """THE ragged multi-query attention source (XLA reference path for the
    unified prefill+decode wave, ISSUE 6) → (o unnormalized [B,K,G,S,hd],
    m [B,K,G,S,1], z [B,K,G,S,1]).

    One masking law serves every row kind of a ragged wave (see
    :mod:`calfkit_tpu.inference.ragged` for the descriptor vocabulary):
    query ``j`` of row ``b`` attends kv positions
    ``< min(kv_lens[b], q_starts[b] + j + 1)`` — causal within the row's
    own fresh span, bounded by its valid cache length.  Decode rows
    (S=1, start=kv_len=lens) and spec-verify rows (start=kv_len=base_lens)
    reduce to the plain length mask; prefill-chunk rows (start=offset,
    kv_len=offset+chunk against a scratch holding the chunk itself) get
    the within-chunk causal triangle.  One batched einsum pair reads the
    window ONCE for all S queries — the multi-query amortization both
    speculation and chunk absorption rely on.
    """
    W = k_cache.shape[2]
    S = qg.shape[1]
    scale = 1.0 / math.sqrt(qg.shape[-1])
    s1 = _einsum_f32("bskgh,bkwh->bkgsw", qg, k_cache) * scale
    kv_pos = jnp.arange(W, dtype=jnp.int32)[None, None, :]  # [1, 1, W]
    limit = jnp.minimum(
        kv_lens[:, None], q_starts[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :] + 1
    )  # [B, S]
    valid = kv_pos < limit[:, :, None]  # [B, S, W]
    s1 = jnp.where(valid[:, None, None, :, :], s1, -1e30)
    m1 = jnp.max(s1, axis=-1, keepdims=True)
    m1 = jnp.maximum(m1, -1e29)  # fresh/padding rows stay finite
    p1 = jnp.exp(s1 - m1).astype(k_cache.dtype)
    z1 = jnp.sum(p1.astype(jnp.float32), axis=-1, keepdims=True)
    o1 = _einsum_f32("bkgsw,bkwh->bkgsh", p1, v_cache)
    return o1, m1, z1


def ragged_attention_xla(
    q: jax.Array,  # [B, S, H, hd] ragged queries (padded to the wave max)
    k_cache: jax.Array,  # [B, K, W, hd]
    v_cache: jax.Array,
    q_starts: jax.Array,  # [B]
    kv_lens: jax.Array,  # [B]
) -> jax.Array:
    """Normalized ragged attention → [B, S, H, hd]: the single-source
    closure of :func:`ragged_attention_source` (rows with no second
    source — plain cache reads).  Queries past a row's true q_len are
    padding; their output is garbage the caller must ignore (the same
    beyond-valid-length law the decode ring relies on)."""
    B, S, H, hd = q.shape
    K = k_cache.shape[1]
    qg = q.reshape(B, S, K, H // K, hd)
    o, m, z = ragged_attention_source(qg, k_cache, v_cache, q_starts, kv_lens)
    out = o / jnp.maximum(z, 1e-30)  # [B, K, G, S, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, hd).astype(q.dtype)


def ragged_attention_paged_xla(
    q: jax.Array,  # [B, S, H, hd]
    pool_layer_k: jax.Array,  # [N, K, page, hd] one layer's pages
    pool_layer_v: jax.Array,
    tables: jax.Array,  # [B, Pmax]
    q_starts: jax.Array,  # [B]
    kv_lens: jax.Array,  # [B]
    *,
    wpages: int,
) -> jax.Array:
    """Ragged attention through the block tables (XLA reference): gather
    each row's window, then the shared ragged mask law — mixed decode /
    prefill-chunk / verify rows served against the paged KV cache in one
    call (the Pallas kernel DMAs pages instead of gathering)."""
    return ragged_attention_xla(
        q,
        gather_window_paged(pool_layer_k, tables, wpages),
        gather_window_paged(pool_layer_v, tables, wpages),
        q_starts, kv_lens,
    )


def verify_chunk_source(
    qg: jax.Array,  # [B, S, K, G, hd]
    ring_k: jax.Array,  # [S, B, K, hd] this layer's chunk K (ring layout)
    ring_v: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The verify chunk's self-attention source → (o, m, z): query j
    attends chunk slots 0..j (slot j IS its own token).  Shared by the
    XLA verify path and the Pallas ragged-kernel merge."""
    S = qg.shape[1]
    scale = 1.0 / math.sqrt(qg.shape[-1])
    s2 = _einsum_f32("bskgh,tbkh->bkgst", qg, ring_k) * scale
    causal = (
        jnp.arange(S, dtype=jnp.int32)[None, :]
        <= jnp.arange(S, dtype=jnp.int32)[:, None]
    )  # [S(query), S(chunk slot)]
    s2 = jnp.where(causal[None, None, None, :, :], s2, -1e30)
    m2 = jnp.max(s2, axis=-1, keepdims=True)
    m2 = jnp.maximum(m2, -1e29)
    p2 = jnp.exp(s2 - m2).astype(ring_k.dtype)
    z2 = jnp.sum(p2.astype(jnp.float32), axis=-1, keepdims=True)
    o2 = _einsum_f32("bkgst,tbkh->bkgsh", p2, ring_v)
    return o2, m2, z2


def _verify_merged_attention(
    q: jax.Array,  # [B, S, H, hd] the chunk's queries
    k_cache: jax.Array,  # [B, K, W, hd] main cache window (read-only)
    v_cache: jax.Array,
    ring_k: jax.Array,  # [S, B, K, hd] this layer's chunk K
    ring_v: jax.Array,
    base_lens: jax.Array,  # [B]
) -> jax.Array:
    """Multi-query merged attention for the verify step (XLA path).

    Source 1 is the main cache read through the shared ragged law
    (:func:`ragged_attention_source` with start = kv_len = base_lens —
    everything in the cache precedes every query, so the ragged mask
    reduces to the plain length mask).  Source 2 is the chunk itself with
    a causal within-chunk mask (:func:`verify_chunk_source`).  Merged
    with the shared logsumexp law; one batched einsum pair reads the
    window ONCE for all S queries (the per-token window read is what
    speculation amortizes).
    """
    B, S, H, hd = q.shape
    K = k_cache.shape[1]
    G = H // K
    qg = q.reshape(B, S, K, G, hd)

    o1, m1, z1 = ragged_attention_source(
        qg, k_cache, v_cache, base_lens, base_lens
    )
    o2, m2, z2 = verify_chunk_source(qg, ring_k, ring_v)
    out = logsumexp_merge((o1, m1, z1), (o2, m2, z2))  # [B, K, G, S, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, hd).astype(q.dtype)


def verify_step_ring(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [B, S] fed tokens
    kv_cache: tuple[jax.Array, jax.Array],  # window-sliced, READ-ONLY here
    base_lens: jax.Array,  # [B]
    attn_impl: str = "xla",
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """Speculative verify over the dense cache layout → (logits [B, S, V],
    chunk ring [L, S, B, K, hd] ×2 for :func:`consolidate_ring`)."""
    k_pages, v_pages = kv_cache
    S = tokens.shape[1]

    def attn_source(i, q, rk, rv, extra):
        k_page, v_page = extra
        if attn_impl.startswith("pallas"):
            # host/interim fallback: the single-query merged kernel applied
            # per chunk position — ring slot validity (0..t) IS the
            # within-chunk causal mask, so t=j gives query j's semantics
            # exactly.  A true multi-query kernel (the ragged-paged-
            # attention direction, PAPERS.md arXiv:2604.15464) would read
            # the window once instead of S times; this keeps the Pallas
            # lane correct until that kernel lands.
            from calfkit_tpu.inference.pallas_attention import (
                verify_attention_pallas,
            )

            return verify_attention_pallas(
                q, k_page, v_page, rk, rv, base_lens,
                interpret=attn_impl == "pallas_interpret",
            )
        return _verify_merged_attention(q, k_page, v_page, rk, rv, base_lens)

    return _verify_step_with_ring(
        params, config, tokens, base_lens, k_pages.dtype, attn_source,
        (k_pages, v_pages),
    )


def verify_step_ring_paged(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [B, S]
    pool: tuple[jax.Array, jax.Array],  # [L, N, K, page, hd] READ-ONLY here
    tables: jax.Array,  # [B, Pmax]
    base_lens: jax.Array,  # [B]
    wpages: int,  # static: window bucket in pages
    attn_impl: str = "xla",
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """Speculative verify reading KV through the block tables → (logits,
    chunk ring for :func:`consolidate_ring_paged`)."""
    pool_k, pool_v = pool

    def attn_source(i, q, rk, rv, extra):
        if attn_impl.startswith("pallas"):
            from calfkit_tpu.inference.pallas_attention import (
                verify_attention_paged_pallas,
            )

            return verify_attention_paged_pallas(
                q, pool_k, pool_v, i, tables, rk, rv, base_lens,
                wpages=wpages, interpret=attn_impl == "pallas_interpret",
            )
        kl = lax.dynamic_index_in_dim(pool_k, i, 0, keepdims=False)
        vl = lax.dynamic_index_in_dim(pool_v, i, 0, keepdims=False)
        return _verify_merged_attention(
            q,
            gather_window_paged(kl, tables, wpages),
            gather_window_paged(vl, tables, wpages),
            rk, rv, base_lens,
        )

    return _verify_step_with_ring(
        params, config, tokens, base_lens, pool_k.dtype, attn_source, None
    )


def consolidate_ring(
    kv_cache: tuple[jax.Array, jax.Array],  # [L, B, K, S, hd] (donated)
    ring: tuple[jax.Array, jax.Array],  # [L, T, B, K, hd]
    base_lens: jax.Array,  # [B] where each row's ring tokens begin
) -> tuple[jax.Array, jax.Array]:
    """Write the dispatch's ring tokens into the main cache — per-row dense
    contiguous chunks, once per dispatch (amortizing what a per-step scatter
    would pay 'steps' times).  Rows whose requests already retired write
    garbage BEYOND their valid length — harmless, masked by seq_lens and
    overwritten by the next prefill on that slot.  Under overlapped
    execution a row that retired in the still-in-flight previous dispatch
    arrives here FROZEN (the engine's done-mask chain stops its ``lens``
    advancing), so its garbage writes repeat at one fixed in-row offset —
    the same beyond-valid-length law, never another row's data."""
    k_pages, v_pages = kv_cache
    ring_k, ring_v = ring

    def write(pages: jax.Array, r: jax.Array) -> jax.Array:
        # r: [L, T, B, K, hd] -> [B, L, K, T, hd]
        chunk = jnp.transpose(r, (2, 0, 3, 1, 4)).astype(pages.dtype)
        # pages: [L, B, K, S, hd] -> vmap rows on axis 1
        def one(row_pages, row_chunk, off):
            return lax.dynamic_update_slice(
                row_pages, row_chunk, (0, 0, off, 0)
            )

        return jax.vmap(one, in_axes=(1, 0, 0), out_axes=1)(
            pages, chunk, base_lens
        )

    return write(k_pages, ring_k), write(v_pages, ring_v)


def _insert_chunk(
    cache: jax.Array,  # [B, K, Smax, hd]
    chunk: jax.Array,  # [B, S, K, hd]
    offsets: jax.Array,  # [B]
) -> jax.Array:
    """Per-row dynamic_update_slice at each sequence's write offset."""
    chunk = jnp.swapaxes(chunk, 1, 2)  # -> [B, K, S, hd]

    def one(row_cache, row_chunk, off):
        return lax.dynamic_update_slice(
            row_cache, row_chunk.astype(row_cache.dtype), (0, off, 0)
        )

    return jax.vmap(one)(cache, chunk, offsets)


def make_empty_cache(
    config: ModelConfig, batch: int, max_seq: int, dtype: Any = None
) -> tuple[jax.Array, jax.Array]:
    dtype = dtype or jnp.dtype(config.dtype)
    shape = (config.n_layers, batch, config.n_kv_heads, max_seq, config.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


# --------------------------------------------------------------------------- #
# paged KV cache (block-table indirection; see inference/paged.py)
# --------------------------------------------------------------------------- #


def make_page_pool(
    config: ModelConfig, num_pages: int, page_size: int, dtype: Any = None
) -> tuple[jax.Array, jax.Array]:
    """KV page pool [L, N, K, page, hd]; page 0 is the trash page."""
    dtype = dtype or jnp.dtype(config.dtype)
    shape = (
        config.n_layers, num_pages, config.n_kv_heads, page_size,
        config.head_dim,
    )
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def gather_window_paged(
    pool_layer: jax.Array,  # [N, K, page, hd] one layer's pages
    tables: jax.Array,  # [B, Pmax] int32 block tables
    wpages: int,  # static: pages per attention window
) -> jax.Array:
    """Materialize each row's window from its pages → [B, K, wp·page, hd].

    The XLA fallback read path: one gather per (layer, step) — correct
    everywhere, but doubles attention HBM traffic vs the Pallas paged
    kernel, which DMAs pages in place.
    """
    B = tables.shape[0]
    page = pool_layer.shape[2]
    gathered = pool_layer[tables[:, :wpages]]  # [B, wp, K, page, hd]
    gathered = jnp.transpose(gathered, (0, 2, 1, 3, 4))
    return gathered.reshape(B, pool_layer.shape[1], wpages * page, -1)


def decode_step_ring_paged(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [B, 1]
    pool: tuple[jax.Array, jax.Array],  # [L, N, K, page, hd] READ-ONLY here
    tables: jax.Array,  # [B, Pmax] block tables
    ring: tuple[jax.Array, jax.Array],  # [L, T, B, K, hd]
    t: jax.Array,  # scalar step index
    base_lens: jax.Array,  # [B]
    wpages: int,  # static: window bucket in pages
    attn_impl: str = "xla",
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """One decode step reading KV through the block tables.

    Shares the transformer body with :func:`decode_step_ring`; only the
    main-cache read differs.  The pool is a scan *invariant* (closed over,
    indexed per layer), never a carry — its bytes move once per read, not
    per scan round-trip.
    """
    pool_k, pool_v = pool

    def attn_source(i, q, rk, rv, extra):
        if attn_impl.startswith("pallas"):
            from calfkit_tpu.inference.pallas_attention import (
                merged_paged_decode_attention_pallas,
            )

            return merged_paged_decode_attention_pallas(
                q, pool_k, pool_v, i, tables, rk, rv, base_lens, t,
                wpages=wpages, interpret=attn_impl == "pallas_interpret",
            )
        kl = lax.dynamic_index_in_dim(pool_k, i, 0, keepdims=False)
        vl = lax.dynamic_index_in_dim(pool_v, i, 0, keepdims=False)
        return _merged_decode_attention(
            q,
            gather_window_paged(kl, tables, wpages),
            gather_window_paged(vl, tables, wpages),
            rk, rv, base_lens, t,
        )

    return _decode_step_with_ring(
        params, config, tokens, ring, t, base_lens, attn_source, None
    )


def consolidate_ring_paged(
    pool: tuple[jax.Array, jax.Array],  # [L, N, K, page, hd] (donated)
    ring: tuple[jax.Array, jax.Array],  # [L, T, B, K, hd]
    tables: jax.Array,  # [B, Pmax]
    base_lens: jax.Array,  # [B]
    active: jax.Array,  # [B] bool — inactive rows scatter to the trash page
) -> tuple[jax.Array, jax.Array]:
    """Write the dispatch's ring tokens through the block tables.

    One scatter per dispatch.  Inactive rows are redirected to page 0 (the
    trash page): a retired slot's pages may already belong to a NEW request,
    so letting its stale row write through its old table entries would
    corrupt a neighbor — the dense layout tolerated garbage-beyond-length,
    the paged layout must not.  Overlapped execution leans on the same
    redirect: a row that retired inside the previous, still-in-flight
    dispatch reaches this one masked inactive (device-side done chain),
    so its writes land in the trash page even though the host hasn't
    freed its pages yet (one-dispatch-late retirement frees them only
    after this dispatch lands).
    """
    pool_k, pool_v = pool
    ring_k, ring_v = ring
    T = ring_k.shape[1]
    page = pool_k.shape[3]

    pos = base_lens[:, None] + jnp.arange(T)[None, :]  # [B, T]
    logical = pos // page  # which table entry
    pmax = tables.shape[1]
    in_range = logical < pmax  # a dispatch can overshoot a retiring row's cap
    page_ids = jnp.take_along_axis(
        tables, jnp.minimum(logical, pmax - 1), axis=1
    )  # [B, T]
    page_ids = jnp.where(active[:, None] & in_range, page_ids, 0)
    offsets = pos % page  # [B, T]

    # advanced indexing: pool[:, idx, :, off] with idx/off of shape [B, T] —
    # the index arrays are NON-adjacent, so numpy semantics move their
    # broadcast dims to the FRONT: values must be [B, T, L, K, hd]
    def write(pool_side: jax.Array, r: jax.Array) -> jax.Array:
        vals = jnp.transpose(r, (2, 1, 0, 3, 4)).astype(pool_side.dtype)
        return pool_side.at[:, page_ids, :, offsets].set(vals)

    return write(pool_k, ring_k), write(pool_v, ring_v)


def write_prefill_pages(
    pool: tuple[jax.Array, jax.Array],  # [L, N, K, page, hd] (donated)
    scratch: tuple[jax.Array, jax.Array],  # [L, R, K, P, hd] prefill K/V
    page_ids: jax.Array,  # [R, P // page] int32 destination pages
) -> tuple[jax.Array, jax.Array]:
    """Scatter whole prefill pages into the pool (page-granular writes)."""
    pool_k, pool_v = pool
    sk, sv = scratch
    L, R, K, P, hd = sk.shape
    page = pool_k.shape[3]
    npg = P // page

    def write(pool_side: jax.Array, s: jax.Array) -> jax.Array:
        # [L, R, K, np*page, hd] -> [L, R, np, K, page, hd] -> [L, R*np, ...]
        blocks = s.reshape(L, R, K, npg, page, hd).transpose(0, 1, 3, 2, 4, 5)
        blocks = blocks.reshape(L, R * npg, K, page, hd).astype(pool_side.dtype)
        return pool_side.at[:, page_ids.reshape(-1)].set(blocks)

    return write(pool_k, sk), write(pool_v, sv)
