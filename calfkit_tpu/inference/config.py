"""Model/runtime configuration for the local inference backend.

Pure dataclasses — importable without jax (the Worker/CLI read these before
any device work happens).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace


@dataclass(frozen=True)
class ModelConfig:
    """A Llama-family decoder architecture description."""

    name: str = "debug"
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 22
    n_heads: int = 32
    n_kv_heads: int = 4
    d_ff: int = 5632
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 2048
    dtype: str = "bfloat16"
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def param_count(self) -> int:
        """Approximate parameter count (for memory planning)."""
        embed = self.vocab_size * self.d_model * (1 if self.tie_embeddings else 2)
        per_layer = (
            # attention: q, k, v, o
            self.d_model * self.n_heads * self.head_dim
            + 2 * self.d_model * self.n_kv_heads * self.head_dim
            + self.n_heads * self.head_dim * self.d_model
            # mlp: gate, up, down
            + 3 * self.d_model * self.d_ff
            # norms
            + 2 * self.d_model
        )
        return embed + self.n_layers * per_layer + self.d_model


@dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knobs (off unless ``RuntimeConfig.speculative``
    is set).

    Decode is memory-bandwidth-bound: a normal decode step reads every
    weight to emit ONE token per request.  Speculation drafts ``k``
    candidate tokens cheaply, then a single **verify** dispatch scores all
    k+1 positions against the KV cache — the full weight-read is amortized
    over every accepted token.  Greedy output is token-exact vs
    non-speculative greedy; sampled output keeps the target-model
    distribution via rejection sampling (``sampler.spec_accept_slots``).

    Two drafters behind one seam (:mod:`calfkit_tpu.inference.spec`):

    - ``draft is None`` → **n-gram prompt lookup**: propose the
      continuation of the most recent earlier occurrence of the sequence
      tail within prompt + generated history.  No extra weights, no extra
      device work — the agent-serving workload (tool-call JSON, repeated
      instructions, quoted context) is exactly where it hits.
    - ``draft`` set → a second, smaller **draft model** proposes greedily
      from its own KV cache; loaded through the same init/loader/sharding
      path as the target (pass ``draft_params`` to the engine for real
      checkpoints).
    """

    k: int = 4  # drafted tokens per verify wave (verify scores k+1)
    # n-gram lookup: longest/shortest tail length to match (longer tails
    # first: more context, fewer false continuations)
    ngram_max: int = 3
    ngram_min: int = 1
    # the draft-model seam: a second, smaller architecture.  None → n-gram.
    draft: "ModelConfig | None" = None


@dataclass(frozen=True)
class RuntimeConfig:
    """Serving-engine knobs (reference analog: the model config block the
    TPU build adds to the provider, SURVEY.md §5 config notes)."""

    max_batch_size: int = 32
    max_seq_len: int = 2048
    # "dense" = [L, B, K, max_seq, hd] per-slot rows (fastest when B×S fits
    # HBM); "paged" = block-table pool, memory ∝ requested footprints — the
    # layout that fits 128 concurrent 8B streams on one 16 GB chip
    kv_layout: str = "dense"
    page_size: int = 64  # tokens per KV page (pallas paged-attention block)
    max_pages_per_seq: int = 0  # 0 → derived from max_seq_len
    # total pages in the paged pool (incl. the reserved trash page);
    # 0 → max_batch_size × pages_per_seq + 1 (no oversubscription)
    num_kv_pages: int = 0
    tp: int = 1  # tensor-parallel degree (mesh 'tp' axis size)
    dp: int = 1  # data/batch-parallel replicas of the serving engine
    decode_steps_per_dispatch: int = 8  # tokens generated per scheduler tick
    prefill_chunk: int = 512  # prompts pad/bucket to multiples of this
    # admission-wave width cap: more requests per prefill dispatch fills a
    # drained batch in fewer device round trips (burst TTFT), at the cost
    # of a larger prefill scratch (wave x bucket KV) and one extra jit
    # variant per power-of-two step.  Waves stay power-of-two sized.
    max_prefill_wave: int = 8
    # interleave long-prompt prefills with decode: an admission advances one
    # prefill_chunk per scheduler pass instead of blocking decode for the
    # whole bucket (vLLM-style chunked prefill; inter-token latency of
    # active streams stays bounded by one chunk + one tick)
    chunked_prefill: bool = False
    attention_impl: str = "auto"  # auto | xla | pallas | pallas_interpret
    # long-context lane: prompts that cannot fit a short-lane slot
    # (len >= max_seq_len) are served via sequence-parallel ring prefill
    # over an `sp` mesh of ALL the engine's devices + context-parallel
    # decode against the still-sharded prefix (greedy; one request at a
    # time — the whole mesh cooperates on it)
    long_context: bool = False
    long_new_cap: int = 512  # max new tokens a long request may generate
    long_max_prompt: int = 0  # prompt-length ceiling; 0 → 8 x max_seq_len
    # long-lane budget negotiation: by default a request whose
    # max_new_tokens exceeds long_new_cap FAULTS with a typed error (the
    # caller's budget is a contract, not a suggestion); True restores the
    # explicit opt-in behavior of clamping to the cap with a warning
    long_clamp_new_tokens: bool = False
    # decode attention window buckets (each is one jit specialization);
    # sparse buckets = few compiles, dense = tighter HBM reads
    window_buckets: tuple[int, ...] = (256, 1024, 4096, 16384)
    compilation_cache_dir: str | None = "~/.cache/calfkit_tpu_xla"
    # automatic prefix caching (vLLM-APC analog): requests whose prompt
    # shares a full-page-aligned prefix with an earlier request reuse its
    # KV pages instead of re-prefilling them — the agent-serving win
    # (same instructions/history re-sent every turn).  Requires
    # kv_layout="paged" AND chunked_prefill=True (reuse seeds the chunk
    # lane's scratch and starts at the reused offset).
    prefix_cache: bool = False
    # speculative decoding: None = off (zero change to the decode path);
    # a SpecConfig turns every decode tick into draft-k + one batched
    # verify dispatch scoring k+1 positions per sequence (see SpecConfig)
    speculative: "SpecConfig | None" = None
    # overlapped execution (double-buffered decode dispatch): launch decode
    # dispatch N+1 BEFORE syncing dispatch N's token block, so host-side
    # fan-out / stop scanning / admission prep run while the device is
    # busy and the inter-dispatch device-idle bubble goes to ~zero.  Stop
    # and generation-bound detection move onto the device as a per-row
    # done mask; a row that retires mid-block rides exactly one extra
    # in-flight dispatch (its pad tokens are discarded, its slot/pages
    # free only after that dispatch lands — one-dispatch-late, never
    # early).  False = the lockstep reference path (sync-then-fan-out),
    # byte-identical token streams either way.
    overlap_dispatch: bool = True
    # ragged unified prefill+decode waves (ISSUE 6; the Ragged Paged
    # Attention design, arXiv:2604.15464): the scheduler's admission lane
    # and decode lane collapse into ONE — each tick enqueues a single
    # fused dispatch carrying the active decode rows AND the inflight
    # admission wave's next prefill chunk, so a half-empty decode wave
    # absorbs prefill work in the compute it would otherwise idle.
    # Engages when chunked_prefill=True (the chunk lane is the absorption
    # substrate) and overlap_dispatch=True (ragged launches ride the
    # double-buffered path); otherwise the engine runs the legacy
    # bifurcated schedule, which is also the byte-identical parity oracle
    # (ragged_waves=False).
    ragged_waves: bool = True
    # token budget per ragged dispatch: decode contributes
    # active_rows x decode_steps_per_dispatch query tokens, an absorbed
    # chunk contributes wave_rows x prefill_chunk.  Bounds per-dispatch
    # latency (absorbed prefill stretches the fused dispatch) AND caps
    # admission-wave width at formation (occupancy-driven admission).
    # 0 = auto: max_batch_size x steps + max_prefill_wave x chunk — a
    # budget that never second-guesses the existing admission bounds;
    # set explicitly to trade absorption for steadier inter-token latency.
    ragged_token_budget: int = 0
    # device-side retirement needs each request's stop-token set as a
    # fixed-shape row: the per-slot table holds this many entries.  A
    # short-lane request with more stop tokens than this is rejected when
    # device-side retirement is in use (overlap_dispatch or speculative);
    # the lockstep host path (overlap_dispatch=False, no speculation)
    # keeps scanning arbitrary-size sets on the host.
    max_stop_tokens: int = 8
    # overload protection (ISSUE 5): per-lane bound on QUEUED (not yet
    # admitted) requests — at the bound, generate() sheds the submit with
    # a typed EngineOverloadedError instead of letting queue wait grow
    # silently.  Applied per lane (short `_pending`+carry, long
    # `_long_pending`).  0 = unbounded (the pre-ISSUE-5 behavior).
    max_pending: int = 0
    # per-request token-delivery bound: a consumer that stops draining its
    # stream accumulates whole dispatch-blocks in GenRequest.out forever —
    # past this many undrained queue items the scheduler stall-cancels the
    # request through the ordinary cancellation path (delivery_stalled
    # counter; the consumer sees a typed EngineOverloadedError when it
    # finally resumes).  0 = unbounded.
    max_out_blocks: int = 0
    # engine wedge watchdog (ISSUE 9): with work pending, no dispatch
    # landing for this many seconds (on the cancellation.wall_clock seam)
    # declares the engine WEDGED — the BENCH r05 "hung device grant"
    # state, where the decode thread blocks inside a device sync forever
    # and the scheduler loop with it.  Tripping dumps the flight
    # recorder, flips readiness (and the heartbeat advert) false, and
    # faults every pending request with a typed RETRIABLE
    # EngineWedgedError so callers fail over instead of burning their
    # deadlines.  If a landing ever arrives after the trip, the engine
    # un-wedges and resumes serving.  0 = off (the default: a first
    # dispatch legitimately blocks for a whole XLA compile, which can
    # take minutes on cold caches — enable with a threshold comfortably
    # above your worst compile time, or after warmup).
    watchdog_stall_s: float = 0.0
    # flight recorder: capacity (events) of the engine's in-memory ring
    # journal of scheduler events (admission, waves, page alloc/free,
    # spec/overlap dispatches, retirement, faults).  Rounds up to a power
    # of two; dumps to JSONL on engine fault / SIGUSR2 / the /flightrec
    # endpoint; appends are O(1) lock-free (< the 2% telemetry bar, see
    # OBS_OVERHEAD.json).  0 disables recording entirely.
    flightrec_events: int = 4096
    # capacity observatory (ISSUE 19): capacity (samples) of the engine's
    # occupancy timeline ring — one numeric sample per dispatch landing
    # (pages in use/free, prefix residency, active/pending, tokens per
    # dispatch, analytic HBM bytes/token).  Rounds up to a power of two;
    # dumps to JSONL next to flight-recorder dumps and serves the
    # /capacity endpoint; appends are O(1) lock-free.  0 (the default)
    # disables the sampler entirely — page ATTRIBUTION (the ledger behind
    # stats_snapshot()["capacity"] and the advert's headroom fields) is
    # always on for paged engines: it rides the existing alloc/free/evict
    # sites at O(1) and stays under the 2% bar (OBS_OVERHEAD.json).
    capacity_samples: int = 0
    # weight-only quantization: "int8" halves decode HBM traffic and fits
    # Llama-3-8B on one 16 GB chip; "int4" (packed nibbles, group-128
    # scales) halves the weight stream again (~4 GB for 8B — margin for
    # KV pages / batch width); None = native dtype
    quantization: str | None = None

    def pages_per_seq(self) -> int:
        if self.max_pages_per_seq:
            return self.max_pages_per_seq
        return -(-self.max_seq_len // self.page_size)

    def pool_pages(self) -> int:
        """Total pages in the paged pool (page 0 is the trash page)."""
        if self.num_kv_pages:
            return self.num_kv_pages
        return self.max_batch_size * self.pages_per_seq() + 1


# --------------------------------------------------------------------------- #
# presets
# --------------------------------------------------------------------------- #

PRESETS: dict[str, ModelConfig] = {
    # tiny config for unit tests / CI — compiles in seconds on CPU
    "debug": ModelConfig(
        name="debug",
        vocab_size=512,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=256,
    ),
    # BASELINE config 2: TinyLlama-1.1B (HF: TinyLlama/TinyLlama-1.1B-Chat)
    "tinyllama-1.1b": ModelConfig(
        name="tinyllama-1.1b",
        vocab_size=32000,
        d_model=2048,
        n_layers=22,
        n_heads=32,
        n_kv_heads=4,
        d_ff=5632,
        rope_theta=10000.0,
        max_seq_len=2048,
    ),
    # BASELINE config 5 / north star: Llama-3-8B (HF: meta-llama/Meta-Llama-3-8B)
    "llama-3-8b": ModelConfig(
        name="llama-3-8b",
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        rope_theta=500000.0,
        max_seq_len=8192,
    ),
}


def preset(name: str, **overrides: object) -> ModelConfig:
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg
