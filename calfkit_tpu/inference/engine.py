"""The continuous-batching inference engine.

This is the boundary object between the two communication tiers (SURVEY.md
§2.4): Kafka partitions feed requests in; token streams come out.  Design:

- a fixed pool of ``max_batch_size`` slots backed by ONE device-resident KV
  cache [L, B, S, K, hd]; admission = prefill into a free slot's rows;
- decode runs for ALL active slots together: one jitted dispatch generates
  ``decode_steps_per_dispatch`` tokens per slot via ``lax.scan`` (host syncs
  once per dispatch, not per token);
- prefill is per-request, bucketed to ``prefill_chunk`` multiples so each
  bucket compiles once; a prefill never blocks the decode cadence for more
  than one tick (new work is admitted between decode dispatches —
  continuous batching, not static batching);
- caches are donated through jit, so memory stays at one cache copy;
- everything device-side is static-shape; per-request stop conditions (eos,
  max_new_tokens) are applied host-side on the freshly synced token block.

The engine is model-agnostic over :mod:`calfkit_tpu.inference.model`'s
functional forward and owns the jit specializations.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import logging
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from calfkit_tpu import cancellation, leases, qos
from calfkit_tpu.effects import hotpath
from calfkit_tpu.inference import ragged as ragged_math
from calfkit_tpu.exceptions import (
    DeadlineExceededError,
    EngineOverloadedError,
    EngineWedgedError,
    InferenceError,
    RunOrphanedError,
)
from calfkit_tpu.inference import model as M
from calfkit_tpu.inference.config import ModelConfig, RuntimeConfig
from calfkit_tpu.observability import capacity, flightrec
from calfkit_tpu.observability.metrics import (
    INTER_TOKEN_BUCKETS_MS,
    REGISTRY,
    MetricsRegistry,
)
from calfkit_tpu.inference.sampler import (
    SamplingParams,
    retire_mask_slots,
    sample_slots,
    spec_accept_slots,
)
from calfkit_tpu.inference.sharding import (
    cache_sharding,
    make_mesh,
    param_shardings,
    place_params,
)

logger = logging.getLogger(__name__)

_DONE = object()

_ATTN_PROFILE_CACHE: "tuple[tuple, dict | None] | None" = None


# process-wide active-request aggregation: the shared gauge must report
# the SUM across live engines, not the last dispatching engine's count
# (updated per dispatch; entries removed at engine stop / GC).  The lock
# serializes insert/pop/sum across decode threads and the event loop —
# an unguarded sum() during another engine's first insert would raise
# "dictionary changed size during iteration" INTO the decode tick,
# letting telemetry fault serving.
_ACTIVE_BY_ENGINE: dict[int, int] = {}
_ACTIVE_LOCK = threading.Lock()


def _drop_engine_active(key: int) -> None:
    """Remove one engine from the aggregation AND re-set the gauge —
    shared by stop() and the GC finalizer, so an abandoned engine's last
    count never stays pinned in the exposition."""
    with _ACTIVE_LOCK:
        if _ACTIVE_BY_ENGINE.pop(key, None) is None:
            return
        total = sum(_ACTIVE_BY_ENGINE.values())
    REGISTRY.gauge("calfkit_engine_active_requests").set(total)


def _engine_metrics(
    registry: "MetricsRegistry | None" = None, *, histograms_only: bool = False
) -> dict:
    """The engine's latency instruments, get-or-create from ``registry``
    (default: the process registry — many engines per process share one
    instrument per metric for the /metrics exposition; each engine also
    builds a private ``histograms_only`` set for per-node percentile
    attribution — counters/gauges stay process-level, so a private copy
    of them would just be dead zeros).  Everything observed here is PER
    DISPATCH or PER ADMISSION, never per token: the decode hot path must
    stay allocation-free."""
    reg = registry if registry is not None else REGISTRY
    out: dict = {
        "queue_wait_ms": reg.histogram(
            "calfkit_engine_queue_wait_ms",
            "submit-to-prefill-start wait (ms)",
        ),
        "prefill_ms": reg.histogram(
            "calfkit_engine_prefill_ms",
            "prefill wave latency, admission to landing (ms)",
        ),
        "ttft_ms": reg.histogram(
            "calfkit_engine_ttft_ms",
            "time to first token: submit to first-token emission (ms)",
        ),
        "inter_token_ms": reg.histogram(
            "calfkit_engine_inter_token_ms",
            "per-sequence inter-token latency (dispatch wall / steps, ms)",
            buckets=INTER_TOKEN_BUCKETS_MS,
        ),
        "decode_dispatch_ms": reg.histogram(
            "calfkit_engine_decode_dispatch_ms",
            "one decode/verify dispatch, enqueue to host sync (ms)",
        ),
        "dispatch_gap_ms": reg.histogram(
            "calfkit_engine_dispatch_gap_ms",
            "device-idle bubble: previous dispatch landing to next launch, "
            "zero while a dispatch is already in flight (ms)",
            buckets=INTER_TOKEN_BUCKETS_MS,
        ),
    }
    if histograms_only:
        return out
    out.update(
        decode_tokens=reg.counter(
            "calfkit_engine_decode_tokens_total", "decoded tokens emitted"
        ),
        prefill_tokens=reg.counter(
            "calfkit_engine_prefill_tokens_total", "prompt tokens prefilled"
        ),
        spec_proposed=reg.counter(
            "calfkit_engine_spec_proposed_total",
            "speculative draft tokens offered to verify dispatches",
        ),
        spec_accepted=reg.counter(
            "calfkit_engine_spec_accepted_total",
            "speculative draft tokens accepted by verify dispatches",
        ),
        overlap_wasted_tokens=reg.counter(
            "calfkit_engine_overlap_wasted_tokens_total",
            "pad tokens discarded by one-dispatch-late retirement "
            "(overlapped execution)",
        ),
        active_requests=reg.gauge(
            "calfkit_engine_active_requests",
            "requests holding a slot (summed across the process's engines)",
        ),
    )
    return out


def _host_feature_tag() -> str:
    """Fingerprint of the executing host's CPU feature set, mixed into the
    persistent compilation-cache path.

    XLA:CPU AOT artifacts embed the COMPILE machine's feature list; loading
    one produced on a wider-featured host risks SIGILL (the stale
    ``+amx-fp16`` cache warning in MULTICHIP_r05.json).  Keying the cache
    directory by the host's own features makes cross-host artifact reuse
    structurally impossible — a different machine simply compiles into its
    own subdirectory.
    """
    import hashlib
    import platform

    feats = platform.machine() or "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("flags", "features")):
                    feats += " " + " ".join(
                        sorted(line.split(":", 1)[-1].split())
                    )
                    break
    except OSError:
        pass  # non-Linux: the machine string alone still splits per-arch
    return hashlib.blake2b(feats.encode(), digest_size=6).hexdigest()


def _load_attn_profile() -> dict | None:
    """The attention-impl profile artifact (written by
    scripts/profile_attention.py --out on hardware): per-path winners that
    ``attention_impl="auto"`` resolves with.  Location: $CALFKIT_ATTN_PROFILE,
    else ~/.cache/calfkit_tpu_attn_profile.json.  Cached by (path, mtime)."""
    global _ATTN_PROFILE_CACHE
    import json
    import os

    path = os.environ.get("CALFKIT_ATTN_PROFILE") or os.path.expanduser(
        "~/.cache/calfkit_tpu_attn_profile.json"
    )
    try:
        key = (path, os.stat(path).st_mtime_ns)
    except OSError:
        return None
    if _ATTN_PROFILE_CACHE is not None and _ATTN_PROFILE_CACHE[0] == key:
        return _ATTN_PROFILE_CACHE[1]
    try:
        # blocking-ok: jit-specialization build path — runs once per shape
        # bucket when a new jit is traced (result cached by path+mtime),
        # never per decode tick
        with open(path) as f:
            verdict = json.load(f)
        if not isinstance(verdict, dict):
            verdict = None
    except (OSError, json.JSONDecodeError):
        verdict = None
    _ATTN_PROFILE_CACHE = (key, verdict)
    return verdict


@hotpath
def _deliver_batch(deliveries: "list[tuple[asyncio.Queue, list]]") -> None:
    """Event-loop side of the batched cross-thread token fan-out.

    Each request's whole dispatch-worth of tokens lands as ONE queue item
    (a list, possibly ending in _DONE): one consumer wakeup per dispatch
    instead of one per token — at 32-step dispatches that is 32x less
    event-loop churn on the serving hot path."""
    for queue, items in deliveries:
        queue.put_nowait(items)


def _finalize_wave_math(
    cfg, paged, sampled,
    k, v, sk, sv, last, lens, slots, true_lens, last_logits,
    slot_keys, temp, top_k, top_p,
    seeds, w_temp, w_top_k, w_top_p,
    tables, page_rows, scatter_ids,
):
    """The wave-landing math shared by single-shot and chunked prefill:
    scatter scratch K/V into the cache (rows or pages), install per-slot
    sampling state, scatter the wave's last/lens rows, sample each row's
    first token from its last-position logits.  Runs inside jit (all
    callers trace it) — the last/lens scatter used to run eagerly on the
    host, costing two XLA dispatches PER REQUEST at admission
    (scripts/sched_overhead.py r4 found admission dominating host cost)."""
    R = slots.shape[0]
    P = sk.shape[3]
    if paged:
        k, v = M.write_prefill_pages((k, v), (sk, sv), scatter_ids)
        tables = tables.at[slots].set(page_rows)
    else:
        for r in range(R):  # R is small & static: unrolled row scatter
            k = lax.dynamic_update_slice_in_dim(
                k, lax.dynamic_slice_in_dim(sk, r, 1, axis=1)[:, :, :, :P],
                slots[r], axis=1,
            )
            v = lax.dynamic_update_slice_in_dim(
                v, lax.dynamic_slice_in_dim(sv, r, 1, axis=1)[:, :, :, :P],
                slots[r], axis=1,
            )
    wave_keys = jax.vmap(jax.random.key)(seeds)
    slot_keys = slot_keys.at[slots].set(wave_keys)
    temp = temp.at[slots].set(w_temp)
    top_k = top_k.at[slots].set(w_top_k)
    top_p = top_p.at[slots].set(w_top_p)
    if sampled:
        subs = jax.vmap(jax.random.fold_in)(wave_keys, true_lens)
        firsts = sample_slots(last_logits, subs, w_temp, w_top_k, w_top_p)
    else:
        firsts = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
    last = last.at[slots].set(firsts)
    lens = lens.at[slots].set(true_lens)
    return k, v, tables, last, lens, slot_keys, temp, top_k, top_p, firsts


@dataclass
class GenRequest:
    prompt: list[int]
    max_new_tokens: int
    stop_tokens: frozenset[int]
    sampling: SamplingParams | None = None  # None → engine default
    seed: int | None = None  # None → engine-derived per-admission stream
    # speculative decoding only: prompt + every emitted token, maintained
    # by _record_token — the n-gram drafter matches against it and the
    # draft model catches its KV up from it.  None when speculation is off
    # (the non-spec hot path never pays the append).
    history: "list[int] | None" = None
    # unbounded-ok: delivery growth is bounded by the max_out_blocks
    # stall-cancel in the scheduler (_check_stalls), not by queue maxsize —
    # a maxsize put_nowait would drop tokens mid-stream instead of reaping
    # the stalled consumer whole
    out: asyncio.Queue = field(default_factory=asyncio.Queue)
    pages: list[int] = field(default_factory=list)  # paged-KV reservation
    # prefix caching: reused token count, the shared (cache-owned) page
    # prefix of ``pages``, and the prompt's full-page chain hashes
    reuse_len: int = 0
    shared_pages: list[int] = field(default_factory=list)
    page_hashes: list = field(default_factory=list)
    slot: int = -1
    generated: int = 0
    prefill_ms: float = 0.0
    cancelled: bool = False
    # deadline-aware overload protection (ISSUE 5): the request's absolute
    # wall-clock deadline (epoch seconds via cancellation.wall_clock) —
    # None = undeadlined.  ``expired`` marks a deadline-driven cancel so
    # the consumer's _consume raises a typed DeadlineExceededError instead
    # of ending the stream silently; ``stalled`` marks a max_out_blocks
    # stall-cancel the same way (typed EngineOverloadedError on resume).
    deadline: "float | None" = None
    expired: bool = False
    stalled: bool = False
    # caller liveness lease (ISSUE 10): the CALLER's process lease this
    # run is registered against (None = un-leased, the pre-lease
    # behavior).  ``orphaned`` marks a lease-lapse reap so _raise_terminal
    # raises the typed non-retriable RunOrphanedError — published to the
    # (dead) reply topic for the record, since nobody is listening.
    lease_id: "str | None" = None
    lease_ttl: float = 0.0
    # back-pointer into _lease_heap, nulled at retirement like
    # deadline_entry so the heap never pins a finished request's memory
    lease_entry: "list | None" = None
    orphaned: bool = False
    # multi-tenant QoS (ISSUE 20): the caller's priority class
    # ("interactive" | "batch"), resolved at submit — under overload,
    # batch sheds first, reaps first at equal expiry.  ``shed`` marks a
    # QUEUED request evicted by priority-ordered shedding (an arriving
    # interactive request claimed its place at a full lane) so
    # _raise_terminal raises the typed retriable EngineOverloadedError;
    # ``shed_detail`` carries the (lane, pending, limit) observed at the
    # eviction so the typed fault reports the same detail as a
    # shed-at-submit (the ISSUE 20 drive-by's uniformity law).
    priority: str = "interactive"
    shed: bool = False
    shed_detail: "tuple[str, int, int] | None" = None
    # the dispatch-progress watchdog faulted this request (ISSUE 9): the
    # consumer's _consume raises a typed RETRIABLE EngineWedgedError so
    # the caller fails over to another replica instead of timing out
    wedged: bool = False
    # back-pointer into _deadline_heap so a FINISHED request's entry can
    # be nulled immediately (_drop_deadline) instead of strongly holding
    # the prompt/history/queue until the deadline lazily pops — minutes
    # of dead memory per request under sustained load otherwise
    deadline_entry: "list | None" = None
    # the request's trace/correlation id (the tracing layer's trace_id —
    # client-minted equal to the correlation id), attached to every
    # flight-recorder event so ``ck timeline <correlation-id>`` can
    # reconstruct this request's lifecycle from a dump.  Precomputed
    # string: journal appends never format.
    corr: "str | None" = None
    # the logical run this request serves (ISSUE 19): the node kernel's
    # run-identity contextvar (x-mesh-run) captured at submit, so the
    # page ledger can attribute HBM by run, not just by attempt.  None =
    # un-linked (direct engine use, pre-run emitters).  Precomputed
    # string, like corr: ledger appends never format.
    run: "str | None" = None
    started_at: float = field(default_factory=time.perf_counter)
    # the request's live _retire_heap entry ([bound, seq, request] list);
    # cleared at retirement so the heap stops pinning this object's
    # prompt/queue memory (r3 advisor finding)
    heap_entry: Any = None


@dataclass
class EngineStats:
    prefill_tokens: int = 0
    decode_tokens: int = 0
    decode_dispatches: int = 0
    decode_time_s: float = 0.0
    occupancy_sum: float = 0.0
    # occupancy distribution: dispatch counts per quartile of max_batch_size
    # (diagnoses WHERE a low mean comes from: ramp-up, tail, or admission
    # starvation — the round-2 bench's 0.365 mean needs this split)
    occupancy_hist: list = field(default_factory=lambda: [0, 0, 0, 0])
    # dispatch lengths actually used (adaptive shortening visibility)
    short_dispatches: int = 0
    long_requests: int = 0  # served via the sequence-parallel lane
    long_dispatches: int = 0  # sp-lane decode dispatches (whole-mesh units)
    prefix_hits: int = 0  # admissions that reused cached prefix pages
    prefix_reused_tokens: int = 0  # prompt tokens NOT re-prefilled
    # speculative decoding: drafts offered to verify dispatches, and how
    # many were accepted (each accepted draft is a token the engine did
    # NOT pay a full weight-read dispatch for)
    spec_proposed: int = 0
    spec_accepted: int = 0
    spec_emitted: int = 0  # tokens emitted by verify dispatches (device)
    spec_rows: int = 0  # Σ over verify dispatches of active rows
    # overlapped execution: pad tokens discarded because their row retired
    # (or cancelled) while the dispatch that generated them was already in
    # flight — the price of one-dispatch-late retirement, bounded by
    # retired rows x steps_per_dispatch
    overlap_wasted_tokens: int = 0
    # overload protection (ISSUE 5): requests refused at submit by the
    # max_pending bound; requests whose deadline passed (at submit, in
    # queue, or while active); consumer-cancelled requests actually
    # reaped; cancels that arrived via the mesh `cancel` record
    # (cancel_correlation) — a subset of cancelled_requests; and requests
    # stall-cancelled by the max_out_blocks delivery bound
    shed_requests: int = 0
    expired_requests: int = 0
    cancelled_requests: int = 0
    cancel_propagated: int = 0
    delivery_stalled: int = 0
    # caller liveness (ISSUE 10): runs abandoned because their CALLER's
    # lease lapsed (queued or active — the server-side orphan reaper),
    # surfaced as the ORPHANS column of `ck stats`
    orphaned_requests: int = 0
    # ragged unified waves (ISSUE 6): prefill chunk tokens absorbed into
    # decode dispatches (slack compute that would otherwise idle), and
    # how many dispatches actually carried both kinds of work.  The
    # occupancy accounting above counts absorbed chunk rows as dispatch
    # participants — mean_occupancy IS the unified-wave fill metric.
    prefill_absorbed_tokens: int = 0
    unified_dispatches: int = 0
    # engine wedge watchdog (ISSUE 9): how many times the dispatch-
    # progress watchdog declared the engine wedged, and how many requests
    # it faulted with the typed retriable EngineWedgedError (so callers
    # failed over instead of burning their deadlines)
    watchdog_trips: int = 0
    watchdog_faulted: int = 0
    # capacity observatory (ISSUE 19): pages reclaimed from the prefix
    # cache under allocation pressure, and admissions whose page alloc
    # came up short on the first try (evictable shortfall or not) — the
    # advert's density-pressure signals, windowed like every counter
    prefix_evictions: int = 0
    alloc_stalls: int = 0
    # multi-tenant QoS (ISSUE 20): the per-class split of the shed and
    # expiry counters above (shed_requests/expired_requests stay the
    # totals).  The advert carries these so RoutingPolicy can tie-break
    # on interactive pressure and `ck stats` can show WHO degradation
    # actually hit — the shed-fairness gate law (zero interactive sheds
    # while any batch request is sheddable) is only auditable with the
    # split visible.
    interactive_shed: int = 0
    batch_shed: int = 0
    interactive_expired: int = 0
    batch_expired: int = 0
    # EWMA of decode-dispatch latency (ms) — the advert's tiebreak signal
    # for many-router coherence (ISSUE 10 satellite): N independent
    # routers seeing identical queue depths between heartbeat beats stop
    # herding when ties break on which replica is actually dispatching
    # faster.  A fold, not a counter: it never enters _COUNTER_FIELDS /
    # window deltas.
    dispatch_ewma_ms: float = 0.0
    # snapshot_and_delta state: the previous window's counter values +
    # timestamp.  Single-consumer by design (the heartbeat advert) — two
    # delta readers would steal each other's intervals.
    _window: Any = field(default=None, repr=False, compare=False)

    _COUNTER_FIELDS = (
        "prefill_tokens", "decode_tokens", "decode_dispatches",
        "decode_time_s", "occupancy_sum", "short_dispatches",
        "long_requests", "long_dispatches", "prefix_hits",
        "prefix_reused_tokens", "spec_proposed", "spec_accepted",
        "spec_emitted", "spec_rows", "overlap_wasted_tokens",
        "shed_requests", "expired_requests", "cancelled_requests",
        "cancel_propagated", "delivery_stalled", "orphaned_requests",
        "prefill_absorbed_tokens", "unified_dispatches",
        "watchdog_trips", "watchdog_faulted",
        "prefix_evictions", "alloc_stalls",
        "interactive_shed", "batch_shed",
        "interactive_expired", "batch_expired",
    )

    # EWMA smoothing for dispatch_ewma_ms: ~5-dispatch memory — fresh
    # enough to react inside one heartbeat interval, smooth enough that
    # one slow compile-bearing dispatch doesn't whipsaw the tiebreak
    EWMA_ALPHA = 0.2

    def note_dispatch_ewma(self, sample_ms: float) -> None:
        """Fold one dispatch's wall latency into the EWMA (hot path: one
        multiply-add).  The first sample primes the fold directly — a
        zero start would under-report for the whole warm-up."""
        prev = self.dispatch_ewma_ms
        if prev == 0.0:
            self.dispatch_ewma_ms = sample_ms
        else:
            a = self.EWMA_ALPHA
            self.dispatch_ewma_ms = a * sample_ms + (1.0 - a) * prev

    def counters(self) -> dict:
        """Every cumulative counter as a plain dict (occupancy_hist as a
        copied list) — the windowing substrate."""
        out: dict = {f: getattr(self, f) for f in self._COUNTER_FIELDS}
        out["occupancy_hist"] = list(self.occupancy_hist)
        return out

    def snapshot_and_delta(self) -> "tuple[dict, dict]":
        """(cumulative, per-interval delta) since the previous call.

        The delta is what heartbeat adverts should report: per-interval
        rates (``tokens_per_second`` over the interval, occupancy-hist
        increments) instead of lifetime cumulative values that flatten
        toward the mean as uptime grows.  The first call's delta covers
        everything since engine construction."""
        now = time.monotonic()
        cur = self.counters()
        prev, prev_t = self._window or (
            {f: 0 for f in self._COUNTER_FIELDS} | {"occupancy_hist": [0, 0, 0, 0]},
            None,
        )
        delta: dict = {
            f: cur[f] - prev[f] for f in self._COUNTER_FIELDS
        }
        delta["occupancy_hist"] = [
            a - b for a, b in zip(cur["occupancy_hist"], prev["occupancy_hist"])
        ]
        delta["interval_s"] = (
            round(now - prev_t, 3) if prev_t is not None else None
        )
        dt = delta["decode_time_s"]
        delta["tokens_per_second"] = (
            round(delta["decode_tokens"] / dt, 1) if dt > 0 else 0.0
        )
        dd = delta["decode_dispatches"]
        delta["mean_occupancy"] = (
            round(delta["occupancy_sum"] / dd, 4) if dd else 0.0
        )
        self._window = (cur, now)
        return cur, delta

    @property
    def tokens_per_second(self) -> float:
        return self.decode_tokens / self.decode_time_s if self.decode_time_s else 0.0

    @property
    def mean_occupancy(self) -> float:
        if not self.decode_dispatches:
            return 0.0
        return self.occupancy_sum / self.decode_dispatches

    @property
    def acceptance_rate(self) -> float:
        """Fraction of drafted tokens the verify dispatch accepted."""
        if not self.spec_proposed:
            return 0.0
        return self.spec_accepted / self.spec_proposed

    @property
    def mean_tokens_per_dispatch(self) -> float:
        """Tokens PROCESSED per decode dispatch: decode tokens plus the
        prefill chunk tokens the ragged scheduler absorbed into those
        same dispatches.  The axis unified waves move — a bifurcated
        schedule pays a separate device invocation for every chunk this
        counts for free."""
        if not self.decode_dispatches:
            return 0.0
        return (
            self.decode_tokens + self.prefill_absorbed_tokens
        ) / self.decode_dispatches

    @property
    def tokens_per_dispatch(self) -> float:
        """Tokens emitted PER SEQUENCE per verify dispatch — the axis
        speculation moves: 1.0 is the non-speculative ratio (one forward,
        one token), k+1 is full acceptance; every point above 1 is a
        weight read the sequence did not pay for.  Batch-aggregate
        throughput is a different axis (occupancy) — this metric
        deliberately excludes it."""
        if not self.spec_rows:
            return 0.0
        return self.spec_emitted / self.spec_rows


class InferenceEngine:
    def __init__(
        self,
        config: ModelConfig,
        runtime: RuntimeConfig | None = None,
        *,
        params: Any = None,
        mesh: Any = None,
        sampling: SamplingParams | None = None,
        seed: int = 0,
        draft_params: Any = None,  # speculative draft-model weights
    ):
        self.config = config
        self.runtime = runtime or RuntimeConfig()
        self.sampling = sampling or SamplingParams()
        rt = self.runtime
        if rt.compilation_cache_dir:
            # persistent XLA cache: window/prefill specializations compile
            # once per machine, not once per process.  The directory is
            # keyed by the host's CPU features (_host_feature_tag): AOT
            # artifacts from a differently-featured machine must never
            # load here (SIGILL risk — MULTICHIP_r05 postmortem).
            import os

            try:
                jax.config.update(
                    "jax_compilation_cache_dir",
                    os.path.join(
                        os.path.expanduser(rt.compilation_cache_dir),
                        f"host-{_host_feature_tag()}",
                    ),
                )
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
            except Exception:  # noqa: BLE001 - cache is best-effort
                logger.debug("persistent compilation cache unavailable")

        self.mesh = mesh if mesh is not None else make_mesh(tp=rt.tp, dp=rt.dp)
        shardings = param_shardings(config, self.mesh)
        if params is None:
            logger.info(
                "initializing random %s params (%.2fB)", config.name,
                config.param_count / 1e9,
            )
            params = M.init_params(config, jax.random.key(seed))
        if rt.quantization in ("int8", "int4"):
            from calfkit_tpu.inference.quant import (
                align_quant_sharding_keys,
                is_quantized,
                is_quantized4,
                quantize_params,
                quantize_shardings,
            )

            bits = 8 if rt.quantization == "int8" else 4
            wq = params.get("layers", {}).get("wq")
            matching = is_quantized(wq) if bits == 8 else is_quantized4(wq)
            if (is_quantized(wq) or is_quantized4(wq)) and not matching:
                raise ValueError(
                    f"params are pre-quantized at the other bitness than "
                    f"runtime quantization={rt.quantization!r}"
                )
            if not matching:
                # consume: free each full-precision tensor as it quantizes
                # (peak ~1x model size — the 8B random-init path needs this)
                params = quantize_params(params, consume=True, bits=bits)
            shardings = quantize_shardings(shardings, bits=bits)
            if bits == 4:
                shardings = align_quant_sharding_keys(shardings, params)
        elif rt.quantization is not None:
            raise ValueError(f"unsupported quantization {rt.quantization!r}")
        if rt.chunked_prefill and rt.max_seq_len % rt.prefill_chunk:
            # buckets cap at max_seq_len; chunked admission needs every
            # bucket to be a whole number of chunks
            raise ValueError(
                "chunked_prefill requires prefill_chunk to divide "
                f"max_seq_len ({rt.prefill_chunk} vs {rt.max_seq_len})"
            )
        if rt.attention_impl not in ("auto", "xla", "pallas", "pallas_interpret"):
            raise ValueError(
                f"unsupported attention_impl {rt.attention_impl!r} "
                "(auto | xla | pallas | pallas_interpret)"
            )
        if rt.max_prefill_wave < 1:
            raise ValueError("max_prefill_wave must be >= 1")
        if rt.max_prefill_wave & (rt.max_prefill_wave - 1):
            # waves are power-of-two trimmed; a non-power-of-two cap would
            # silently behave as the next power down — reject it loudly
            raise ValueError(
                f"max_prefill_wave must be a power of two "
                f"(got {rt.max_prefill_wave})"
            )
        self._spec = rt.speculative
        self._drafter: Any = None
        if self._spec is not None:
            if self._spec.k < 1:
                raise ValueError(
                    f"speculative.k must be >= 1 (got {self._spec.k})"
                )
            if self._spec.draft is None and draft_params is not None:
                raise ValueError(
                    "draft_params given but speculative.draft is unset"
                )
        elif draft_params is not None:
            raise ValueError("draft_params given but speculation is off")
        self.params = place_params(params, shardings)

        B, S = rt.max_batch_size, rt.max_seq_len
        if rt.kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"unsupported kv_layout {rt.kv_layout!r} (dense | paged)"
            )
        self._paged = rt.kv_layout == "paged"
        if self._paged:
            from calfkit_tpu.inference.paged import PageAllocator
            from calfkit_tpu.inference.sharding import pool_sharding

            if rt.prefill_chunk % rt.page_size:
                raise ValueError(
                    "page_size must divide prefill_chunk "
                    f"({rt.page_size} vs {rt.prefill_chunk})"
                )
            if rt.max_seq_len % rt.page_size:
                # a prefill bucket capped at max_seq_len must still be a
                # whole number of pages (page-granular scatter)
                raise ValueError(
                    "page_size must divide max_seq_len "
                    f"({rt.page_size} vs {rt.max_seq_len})"
                )
            n_pages = rt.pool_pages()
            pool_sh = pool_sharding(config, self.mesh)
            pool_k, pool_v = M.make_page_pool(config, n_pages, rt.page_size)
            self._k = jax.device_put(pool_k, pool_sh)
            self._v = jax.device_put(pool_v, pool_sh)
            self._tables = jnp.zeros((B, rt.pages_per_seq()), jnp.int32)
            self._page_alloc = PageAllocator(n_pages)
            # capacity observatory (ISSUE 19): the page-ownership mirror —
            # maintained O(1) at every alloc/free/evict site below, always
            # on for paged engines (attribution is the headroom advert's
            # substrate; the SAMPLER below is the opt-in part)
            self._ledger = capacity.PageLedger(n_pages - 1)
            self._prefix: Any = None
            if rt.prefix_cache:
                if not rt.chunked_prefill:
                    raise ValueError(
                        "prefix_cache=True requires chunked_prefill=True "
                        "(reuse seeds the chunk lane's scratch)"
                    )
                from calfkit_tpu.inference.paged import PrefixCache

                self._prefix = PrefixCache()
            logger.info(
                "paged KV pool: %d pages x %d tokens (%.2f GB)",
                n_pages, rt.page_size,
                2 * self._k.size * self._k.dtype.itemsize / 1e9,
            )
        else:
            self._prefix = None
            if rt.prefix_cache:
                raise ValueError(
                    "prefix_cache=True requires kv_layout='paged' "
                    "(reuse shares pages between requests)"
                )
            cache_sh = cache_sharding(config, self.mesh, B)
            self._k = jax.device_put(
                jnp.zeros(
                    (config.n_layers, B, config.n_kv_heads, S, config.head_dim),
                    jnp.dtype(config.dtype),
                ),
                cache_sh,
            )
            self._v = jax.device_put(jnp.zeros_like(self._k), cache_sh)
        self._last = jnp.zeros((B,), jnp.int32)
        self._lens = jnp.zeros((B,), jnp.int32)
        self._host_lens = np.zeros((B,), np.int64)  # host mirror for windows
        if rt.max_stop_tokens < 1:
            raise ValueError("max_stop_tokens must be >= 1")
        # device-side retirement inputs (overlapped execution): each slot's
        # stop tokens as a fixed-shape row (-1 padded) and the absolute
        # cache length at which the row hits its hard generation bound —
        # min(prompt + max_new - 1, max_seq - 2), so bound-steps-remaining
        # is just hard_end - lens ON DEVICE (always exact, even for a
        # dispatch launched before the previous one's tokens reached the
        # host).  Written at activation, shipped per dispatch like the
        # active mask.
        self._stop_np = np.full((B, rt.max_stop_tokens), -1, np.int32)
        self._hard_end = np.zeros((B,), np.int32)
        # device copies of the two arrays above, re-uploaded only when an
        # activation rewrites them — the launch path must not pay a
        # host→device transfer per dispatch for admission-time constants
        self._retire_dev: "tuple[Any, Any] | None" = None
        self._done_zero = jnp.zeros((B,), jnp.bool_)
        # the launched-but-not-landed decode dispatch (overlap mode only):
        # device handles for its outputs, the slot->request snapshot it
        # was launched with, and the slots whose resource frees are
        # deferred to its landing
        self._pend: "dict | None" = None
        self._last_sync_t: "float | None" = None
        # per-slot sampling state: one decode dispatch serves mixed settings
        # (row-wise knobs are data, not jit specializations)
        self._slot_keys = jax.random.split(jax.random.key(seed + 2), B)
        self._temp = jnp.zeros((B,), jnp.float32)
        self._top_k = jnp.zeros((B,), jnp.int32)
        self._top_p = jnp.ones((B,), jnp.float32)
        self._admissions = 0  # per-request default seed stream

        self._free: list[int] = list(range(B))
        self._active: dict[int, GenRequest] = {}
        # bound-retirement horizon tracking: a min-heap of
        # [absolute decode-clock step at which the request hits a bound,
        # tiebreak, request] so _retirement_near is O(log n) amortized
        # instead of an O(active) scan on the decode thread every dispatch.
        # Pushes happen on the event loop (activation), peeks/pops on the
        # decode thread — the lock covers both.  Early retirements
        # (stop token / cancel) null the entry's request slot via
        # _untrack_retirement so the heap never pins retired-request
        # memory; nulled entries pop lazily, with a compaction pass when
        # they outnumber the live ones.
        self._retire_heap: list[list] = []
        self._retire_lock = threading.Lock()
        self._retire_seq = itertools.count()
        self._retire_stale = 0
        self._decode_clock = 0
        self._cancel_dirty = False  # at least one .cancelled flag is set
        # mesh cancels whose candidate snapshot lost the race with the
        # decode thread (see cancel_correlation): re-matched on the next
        # scheduler pass, where nothing mutates the queues concurrently
        self._deferred_cancels: set[str] = set()
        # deadline enforcement: min-heap of [deadline_epoch, seq, request]
        # peeked once per scheduler pass (O(1) when nothing expired; pops
        # only on actual expiry).  Event-loop-only — submit and reap both
        # run there, so no lock.  Finished requests' entries pop lazily
        # (liveness re-checked at pop time).
        self._deadline_heap: list[list] = []
        self._deadline_seq = itertools.count()
        # caller liveness (ISSUE 10): min-heap of [lease_expiry_epoch,
        # seq, request] — the orphan reaper's O(1)-peek sweep, shaped
        # exactly like the deadline heap (event-loop-only, lazy pops).
        # A popped entry whose lease was REFRESHED since registration is
        # re-pushed at its new expiry, so sustained heartbeats cost one
        # push per TTL per run, not per pass.
        self._lease_heap: list[list] = []
        self._lease_seq = itertools.count()
        # released-lease sweep cursor: a clean caller close must reap
        # NOW, not at the registered expiry — one int compare per pass
        self._lease_release_gen = leases.release_generation()
        # chaos seam (tests/_chaos.py): when set, called with a point name
        # ("tick" per scheduler pass, "dispatch" per decode tick) — an
        # exception it raises crosses the dispatch loop like any real
        # engine fault (journal dump + teardown)
        self._chaos: Any = None
        self._inflight: dict | None = None  # chunked-prefill wave in flight
        # requests whose (non-chunked) admission prefill is running in
        # to_thread: otherwise they live only in a local during the JIT
        # compile + prefill — exactly when an early cancel or deadline
        # check most needs to see them.  Flags set here are honored at
        # activation (_activate_wave sheds cancelled corpses).
        self._admitting: list[GenRequest] = []
        self._carry: list[GenRequest] = []  # wave-trimmed, ahead of the queue
        # unbounded-ok: growth is bounded by the max_pending admission shed
        # in generate() (_shed_if_full), typed rejection instead of maxlen
        # silently evicting queued callers
        self._pending: deque[GenRequest] = deque()
        # long-context lane (sequence-parallel; one request at a time)
        # unbounded-ok: bounded by the same max_pending shed (long lane)
        self._long_pending: deque[GenRequest] = deque()
        self._long: dict | None = None  # active long request's device state
        self._long_inflight: dict | None = None  # chunked long prefill
        self._sp_mesh_cache: Any = None
        # ragged unified waves (ISSUE 6): effective only where the fused
        # dispatch has both of its substrates — the chunk lane to absorb
        # from and the overlap launch path to ride; anything else runs
        # the legacy bifurcated schedule (which doubles as the parity
        # oracle at ragged_waves=False)
        self._ragged = bool(
            rt.ragged_waves and rt.chunked_prefill and rt.overlap_dispatch
        )
        self._ragged_budget = ragged_math.token_budget(
            rt.ragged_token_budget, B, rt.decode_steps_per_dispatch,
            rt.prefill_chunk, rt.max_prefill_wave,
        )
        self._wake = asyncio.Event()
        self._task: asyncio.Task[None] | None = None
        self._running = False
        # engine wedge watchdog (ISSUE 9): a separate event-loop task —
        # the serve loop itself blocks inside asyncio.to_thread when a
        # device grant wedges, which is exactly the state the watchdog
        # exists to detect.  ``_progress_at`` is stamped (wall_clock seam,
        # so the chaos virtual clock drives it) at every dispatch/wave
        # LANDING; with work pending and no stamp for watchdog_stall_s
        # the engine is declared wedged: journal dump, readiness false,
        # every pending request faulted typed-retriable.  A later landing
        # un-wedges (the stuck requests were already cancelled; the
        # ordinary reap frees their resources).
        self._wedged = False
        self._wedged_at = 0.0
        self._progress_at = cancellation.wall_clock()
        self._watchdog_task: asyncio.Task[None] | None = None
        self.stats = EngineStats()
        # flight recorder: the ring journal every scheduler decision point
        # appends to (admission, waves, page alloc/free, spec/overlap
        # dispatches, deferred retirement, faults).  Appends are O(1)
        # lock-free; the ring dumps to JSONL on engine fault, SIGUSR2, or
        # the /flightrec endpoint.  flightrec_events=0 makes append a
        # single attribute check.
        self._journal = flightrec.FlightRecorder(
            rt.flightrec_events, label=config.name
        )
        # capacity observatory (ISSUE 19): the occupancy timeline ring —
        # one sample per dispatch landing, flightrec's ring discipline
        # (capacity_samples=0 makes append a single attribute check).
        # Dense engines get a pool-less ledger so the snapshot/advert
        # keys exist with zeros everywhere.
        if not self._paged:
            self._ledger = capacity.PageLedger(0)
        self._sampler = capacity.CapacitySampler(
            rt.capacity_samples, label=config.name, ledger=self._ledger
        )
        # one precomputed bool so the per-dispatch guard is a single
        # attribute read (capacity_samples=0 must stay effectively free)
        self._capacity_on = self._sampler.capacity > 0
        # the sampler's analytic HBM roofline constants, precomputed once
        # (bench's _perf_model formula; mean context = half the window)
        self._hbm_constants = capacity.hbm_constants(
            config, rt.quantization
        )
        self._hbm_ctx = rt.max_seq_len / 2.0
        # mesh cancel fan-out: a `cancel` record arriving at any node in
        # the process reaches this engine's request abandonment
        cancellation.register_cancel_target(self)
        # latency telemetry: process-registry instruments + the sync
        # cursors that turn cumulative stats into counter increments
        self.metrics = _engine_metrics()
        # per-ENGINE latency histograms: the advert's percentiles must
        # attribute to THIS engine, not blend every engine in the process
        # (the process-registry instruments above stay shared for the
        # /metrics exposition; both are observed, each O(1))
        self._own_registry = MetricsRegistry()
        self.latency = _engine_metrics(self._own_registry, histograms_only=True)
        self._counted = {
            "decode_tokens": 0, "prefill_tokens": 0,
            "spec_proposed": 0, "spec_accepted": 0,
            "overlap_wasted_tokens": 0,
        }
        self._counted_lock = threading.Lock()
        # self-cleaning gauge aggregation: an engine abandoned without
        # stop() must not pin its last active count into the process
        # gauge (stop() also clears eagerly and re-sets the gauge)
        import weakref

        weakref.finalize(self, _drop_engine_active, id(self))

        self._decode_jits: dict[tuple, Any] = {}  # (window, steps, ...)
        self._prefill_jits: dict[tuple, Any] = {}
        if self._spec is not None:
            from calfkit_tpu.inference.spec import build_drafter

            self._drafter = build_drafter(
                self._spec, rt, self.mesh,
                draft_params=draft_params, seed=seed + 3,
            )
            logger.info(
                "speculative decoding on: %s drafter, k=%d",
                "draft-model" if self._spec.draft is not None else "ngram",
                self._spec.k,
            )

    # ------------------------------------------------------------ jit build
    def _resolved_attn_impl(
        self, path: str = "decode", fallback: "str | None" = None
    ) -> str:
        """Resolve ``attention_impl`` for one jit path (``prefill`` /
        ``decode`` / ``paged_decode`` / ``ragged`` / ``paged_ragged``).

        "auto" is EVIDENCE-BASED (VERDICT r3 item 8): it reads the profile
        artifact ``scripts/profile_attention.py --out`` writes on hardware
        and flips to the per-path winner, but only when the artifact's
        platform matches the live backend (a TPU verdict must not steer a
        CPU run and vice versa).  No artifact, or no verdict for this path
        → the ``fallback`` path's winner (the ragged multi-query paths
        fall back to their legacy single-query twin, so a pre-ragged
        artifact keeps steering), else XLA, the safe default.
        "pallas"/"pallas_interpret" opt in explicitly everywhere."""
        impl = self.runtime.attention_impl
        if impl != "auto":
            return impl
        verdict = _load_attn_profile()
        if not verdict:
            return "xla"
        try:
            platform = jax.devices()[0].platform
        except Exception:  # noqa: BLE001 - backend probe must not break jit build
            return "xla"
        if verdict.get("platform") != platform:
            return "xla"
        winners = verdict.get("winners") or {}
        winner = winners.get(path)
        if winner is None and fallback is not None:
            winner = winners.get(fallback)
        return winner if winner in ("xla", "pallas", "pallas_interpret") else "xla"

    def _window_bucket(self, needed: int) -> int:
        """Smallest configured window ≥ needed (cap max_seq): the decode
        attention scan only reads this prefix of the cache, and each bucket
        is one compile."""
        cap = self.runtime.max_seq_len
        for w in self.runtime.window_buckets:
            if needed <= w <= cap:
                return w
        return cap

    def _decode_jit(
        self, window: int, steps: int | None = None, sampled: bool = False
    ) -> Any:
        if self._paged:
            return self._decode_jit_paged(window, steps, sampled)
        steps = steps or self.runtime.decode_steps_per_dispatch
        fn = self._decode_jits.get((window, steps, sampled))
        if fn is not None:
            return fn
        fn = jax.jit(
            self._decode_fn_dense(window, steps, sampled),
            donate_argnums=(1, 2),
        )
        self._decode_jits[(window, steps, sampled)] = fn
        return fn

    def _decode_fn_dense(self, window: int, steps: int, sampled: bool) -> Any:
        """The dense decode dispatch BODY (untraced): shared verbatim by
        the standalone decode jit and the fused ragged-wave jit, so the
        two compile the identical subgraph (ragged-on parity is structural,
        not coincidental)."""
        cfg = self.config
        attn_impl = self._resolved_attn_impl("decode")

        def decode(params, k, v, last, lens, active, done_prev,
                   stop_table, hard_end, slot_keys, temp, top_k, top_p):
            # ring-buffer decode: the main cache is READ-ONLY during the
            # scan; fresh K/V goes to a dense ring, consolidated once below.
            # The attention window is sliced ONCE per dispatch (a loop
            # constant), so per-step reads cover only live prefixes.
            # ``done_prev`` is the PREVIOUS dispatch's device-side done
            # mask: under overlapped execution this dispatch launches
            # before the host has seen the previous block, and a row that
            # retired there must be frozen here by pure device dataflow.
            active = active & jnp.logical_not(done_prev)
            B = last.shape[0]
            kw = k[:, :, :, :window]
            vw = v[:, :, :, :window]
            ring = (
                jnp.zeros(
                    (cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim),
                    k.dtype,
                ),
                jnp.zeros(
                    (cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim),
                    v.dtype,
                ),
            )

            def step(carry, t):
                ring, last = carry
                logits, ring = M.decode_step_ring(
                    params, cfg, last[:, None], (kw, vw), ring, t, lens,
                    attn_impl=attn_impl,
                )
                if sampled:
                    # per-(request, position) streams: deterministic for a
                    # given seed regardless of batch composition / slot reuse
                    # (+1: position ``lens`` itself was the prefill's draw)
                    subs = jax.vmap(jax.random.fold_in)(slot_keys, lens + t + 1)
                    nxt = sample_slots(logits[:, -1], subs, temp, top_k, top_p)
                else:
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                nxt = jnp.where(active, nxt, last)
                return (ring, nxt), nxt

            (ring, last), toks = lax.scan(
                step, (ring, last), jnp.arange(steps)
            )
            k, v = M.consolidate_ring((k, v), ring, lens)
            new_lens = jnp.where(active, lens + steps, lens)
            # device-side retirement: classify the fresh block against each
            # row's stop table and hard bound, so the NEXT dispatch can
            # launch (consuming ``done``) before any host sync of this one
            n_valid, done = retire_mask_slots(
                toks.T, stop_table, hard_end - lens, active
            )
            return k, v, last, new_lens, toks, n_valid, done  # toks [steps, B]

        return decode

    def _decode_jit_paged(
        self, window: int, steps: int | None, sampled: bool
    ) -> Any:
        """Decode dispatch reading/writing KV through the block tables."""
        steps = steps or self.runtime.decode_steps_per_dispatch
        page = self.runtime.page_size
        wpages = -(-window // page)
        fn = self._decode_jits.get((wpages, steps, sampled, "paged"))
        if fn is not None:
            return fn
        fn = jax.jit(
            self._decode_fn_paged(wpages, steps, sampled),
            donate_argnums=(1, 2),
        )
        self._decode_jits[(wpages, steps, sampled, "paged")] = fn
        return fn

    def _decode_fn_paged(self, wpages: int, steps: int, sampled: bool) -> Any:
        """The paged decode dispatch body (untraced) — see
        :meth:`_decode_fn_dense` for why the body builder is separate."""
        cfg = self.config
        attn_impl = self._resolved_attn_impl("paged_decode")

        def decode(params, k, v, tables, last, lens, active, done_prev,
                   stop_table, hard_end, slot_keys, temp, top_k, top_p):
            # rows that retired in the still-in-flight previous dispatch
            # are frozen out here (and their consolidation writes routed
            # to the trash page) by the device-side done-mask chain
            active = active & jnp.logical_not(done_prev)
            B = last.shape[0]
            ring = (
                jnp.zeros(
                    (cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim),
                    k.dtype,
                ),
                jnp.zeros(
                    (cfg.n_layers, steps, B, cfg.n_kv_heads, cfg.head_dim),
                    v.dtype,
                ),
            )

            def step(carry, t):
                ring, last = carry
                logits, ring = M.decode_step_ring_paged(
                    params, cfg, last[:, None], (k, v), tables, ring, t,
                    lens, wpages=wpages, attn_impl=attn_impl,
                )
                if sampled:
                    subs = jax.vmap(jax.random.fold_in)(slot_keys, lens + t + 1)
                    nxt = sample_slots(logits[:, -1], subs, temp, top_k, top_p)
                else:
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                nxt = jnp.where(active, nxt, last)
                return (ring, nxt), nxt

            (ring, last), toks = lax.scan(
                step, (ring, last), jnp.arange(steps)
            )
            k2, v2 = M.consolidate_ring_paged(
                (k, v), ring, tables, lens, active
            )
            new_lens = jnp.where(active, lens + steps, lens)
            n_valid, done = retire_mask_slots(
                toks.T, stop_table, hard_end - lens, active
            )
            return k2, v2, last, new_lens, toks, n_valid, done

        return decode

    def _verify_jit(self, window: int, S: int, sampled: bool) -> Any:
        """The speculative VERIFY dispatch: feed [last, d_0..d_{S-2}] per
        row, score all S positions in one forward against the cache,
        accept a ragged per-row prefix (``sampler.spec_accept_slots``),
        consolidate the chunk's K/V, and advance each row's length by its
        own ``emitted`` — ragged acceptance needs no physical rollback
        because rejected slots land beyond the advanced length and the
        next wave's chunk overwrites them (the same garbage-beyond-length
        law the decode ring already relies on)."""
        if self._paged:
            return self._verify_jit_paged(window, S, sampled)
        key = ("verify", window, S, sampled)
        fn = self._decode_jits.get(key)
        if fn is not None:
            return fn
        cfg = self.config
        # the verify dispatch runs the RAGGED multi-query kernel (one
        # window read for all S positions) — "auto" resolves it on the
        # ragged profile rows, falling back to the legacy decode verdict
        attn_impl = self._resolved_attn_impl("ragged", fallback="decode")

        def verify(params, k, v, last, lens, active, drafts, ndraft,
                   stop_table, hard_end, slot_keys, temp, top_k, top_p):
            kw = k[:, :, :, :window]
            vw = v[:, :, :, :window]
            tokens = jnp.concatenate([last[:, None], drafts], axis=1)
            logits, ring = M.verify_step_ring(
                params, cfg, tokens, (kw, vw), lens, attn_impl=attn_impl
            )
            out_toks, emitted = spec_accept_slots(
                logits, drafts, ndraft, lens, slot_keys, temp, top_k,
                top_p, sampled=sampled,
            )
            emitted = jnp.where(active, emitted, 0)
            k, v = M.consolidate_ring((k, v), ring, lens)
            idx = jnp.clip(emitted - 1, 0, S - 1)
            new_last = jnp.where(
                active,
                jnp.take_along_axis(out_toks, idx[:, None], axis=1)[:, 0],
                last,
            )
            n_valid, done = retire_mask_slots(
                out_toks, stop_table, hard_end - lens, active,
                emitted=emitted,
            )
            return (
                k, v, new_last, lens + emitted, out_toks, emitted,
                n_valid, done,
            )

        fn = jax.jit(verify, donate_argnums=(1, 2))
        self._decode_jits[key] = fn
        return fn

    def _verify_jit_paged(self, window: int, S: int, sampled: bool) -> Any:
        page = self.runtime.page_size
        wpages = -(-window // page)
        key = ("verify", wpages, S, sampled, "paged")
        fn = self._decode_jits.get(key)
        if fn is not None:
            return fn
        cfg = self.config
        attn_impl = self._resolved_attn_impl(
            "paged_ragged", fallback="paged_decode"
        )

        def verify(params, k, v, tables, last, lens, active, drafts,
                   ndraft, stop_table, hard_end, slot_keys, temp, top_k,
                   top_p):
            tokens = jnp.concatenate([last[:, None], drafts], axis=1)
            logits, ring = M.verify_step_ring_paged(
                params, cfg, tokens, (k, v), tables, lens,
                wpages=wpages, attn_impl=attn_impl,
            )
            out_toks, emitted = spec_accept_slots(
                logits, drafts, ndraft, lens, slot_keys, temp, top_k,
                top_p, sampled=sampled,
            )
            emitted = jnp.where(active, emitted, 0)
            # inactive rows scatter to the trash page; writes past a
            # row's reservation hit its table row's trash padding —
            # shared (prefix-cache) pages are never touched because the
            # chunk starts at lens >= prompt_len, past every registered
            # page (the same invariant plain decode relies on)
            k2, v2 = M.consolidate_ring_paged((k, v), ring, tables, lens, active)
            idx = jnp.clip(emitted - 1, 0, S - 1)
            new_last = jnp.where(
                active,
                jnp.take_along_axis(out_toks, idx[:, None], axis=1)[:, 0],
                last,
            )
            n_valid, done = retire_mask_slots(
                out_toks, stop_table, hard_end - lens, active,
                emitted=emitted,
            )
            return (
                k2, v2, new_last, lens + emitted, out_toks, emitted,
                n_valid, done,
            )

        fn = jax.jit(verify, donate_argnums=(1, 2))
        self._decode_jits[key] = fn
        return fn

    def _short_steps(self) -> int:
        """Dispatch length while a waiting admission could actually unblock:
        a new request's time-to-prefill is bounded by one SHORT dispatch
        instead of a full one (TTFT lever; never longer than a full tick)."""
        steps = self.runtime.decode_steps_per_dispatch
        return min(steps, max(4, steps // 4))

    def _retirement_bound(self, request: GenRequest) -> int:
        """Decode steps until the request hits a hard stop bound."""
        remaining = request.max_new_tokens - request.generated
        seq_room = self.runtime.max_seq_len - 1 - (
            len(request.prompt) + request.generated
        )
        return min(remaining, seq_room)

    def _track_retirement(self, request: GenRequest) -> None:
        """Register an activated request's bound-retirement horizon."""
        with self._retire_lock:
            entry = [
                self._decode_clock + self._retirement_bound(request),
                next(self._retire_seq),
                request,
            ]
            request.heap_entry = entry
            heapq.heappush(self._retire_heap, entry)

    def _untrack_retirement(self, request: GenRequest) -> None:
        """Drop the heap's reference to a retired request NOW (the entry
        itself pops lazily): a retired request must not stay pinned —
        prompt list, token queue and all — until its original bound
        surfaces at the heap top (r3 advisor finding).  Compacts the heap
        once nulled entries outnumber live ones, so sustained early
        retirement (stop tokens, cancels) keeps the heap O(active)."""
        entry = request.heap_entry
        if entry is None:
            return
        request.heap_entry = None
        with self._retire_lock:
            entry[2] = None
            self._retire_stale += 1
            if self._retire_stale * 2 > len(self._retire_heap):
                self._retire_heap = [
                    e for e in self._retire_heap if e[2] is not None
                ]
                heapq.heapify(self._retire_heap)
                self._retire_stale = 0

    def _retirement_near(self, horizon: int) -> bool:
        """Will any active request hit a stop bound within ``horizon`` steps?
        (Shortening ticks while nothing can retire just multiplies dispatch
        overhead — slots only free on retirement.)  O(log n) amortized: the
        heap top is the earliest bound; entries nulled by early retirement
        (stop token / cancel) pop lazily here.  A nulled entry[2] is THE
        staleness marker — every retirement path for a tracked request
        runs _untrack_retirement, so no other invariant is needed."""
        with self._retire_lock:
            heap = self._retire_heap
            while heap and heap[0][2] is None:
                heapq.heappop(heap)
                self._retire_stale = max(0, self._retire_stale - 1)
            return bool(heap) and heap[0][0] <= self._decode_clock + horizon

    def _prefill_jit(self, bucket: int, rows: int, sampled: bool = False) -> Any:
        """Batched prefill: R admissions run as one [R, bucket] forward on a
        scratch cache, then scatter into the slot rows (dense) or the
        reserved pages (paged) — one dispatch per admission WAVE, not per
        request.  The wave's per-slot sampling state (keys/temp/top_k/top_p)
        and, when paged, the block-table rows are scattered in the same
        dispatch."""
        paged = self._paged
        fn = self._prefill_jits.get((bucket, rows, sampled))
        if fn is not None:
            return fn
        cfg = self.config
        attn_impl = self._resolved_attn_impl("prefill")

        def prefill(
            params, k, v, last, lens, tokens, slots, true_lens,
            slot_keys, temp, top_k, top_p,  # [B] engine state
            seeds, w_temp, w_top_k, w_top_p,  # [R] wave values
            tables=None, page_rows=None, scatter_ids=None,  # paged only
        ):
            # tokens: [R, bucket]; slots/true_lens: [R]
            R, P = tokens.shape
            scratch = (
                jnp.zeros((cfg.n_layers, R, cfg.n_kv_heads, P, cfg.head_dim), k.dtype),
                jnp.zeros((cfg.n_layers, R, cfg.n_kv_heads, P, cfg.head_dim), v.dtype),
            )
            pos = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (R, P))
            logits, (sk, sv) = M.forward(
                params, cfg, tokens, pos, scratch,
                jnp.full((R,), P, jnp.int32), attn_impl=attn_impl,
            )
            idx = jnp.clip(true_lens - 1, 0, P - 1)
            last_logits = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1
            )[:, 0]
            return _finalize_wave_math(
                cfg, paged, sampled,
                k, v, sk, sv, last, lens, slots, true_lens, last_logits,
                slot_keys, temp, top_k, top_p,
                seeds, w_temp, w_top_k, w_top_p,
                tables, page_rows, scatter_ids,
            )

        fn = jax.jit(prefill, donate_argnums=(1, 2, 3, 4))
        self._prefill_jits[(bucket, rows, sampled)] = fn
        return fn

    # ------------------------------------------------- chunked prefill jits
    def _chunk_jit(self, chunk: int, rows: int) -> Any:
        """One prefill CHUNK: forward [R, chunk] at a data offset into the
        wave's scratch cache.  One compile per (chunk, R) regardless of how
        long prompts get — the offset is data."""
        fn = self._prefill_jits.get(("chunk", chunk, rows))
        if fn is not None:
            return fn
        fn = jax.jit(self._chunk_fn(chunk), donate_argnums=(1, 2))
        self._prefill_jits[("chunk", chunk, rows)] = fn
        return fn

    def _chunk_fn(self, chunk: int) -> Any:
        """The prefill-chunk body (untraced): shared verbatim by the
        standalone chunk jit and the fused ragged-wave jit (same
        structural-parity argument as :meth:`_decode_fn_dense`).  The
        chunk is a RAGGED row kind — q_len=chunk queries at data offset
        ``start`` against a scratch holding the chunk itself (the
        per-row positions/lens ARE the (kind, start, q_len, kv_len)
        descriptor, serialized as arrays)."""
        cfg = self.config
        attn_impl = self._resolved_attn_impl("prefill")

        def chunk_step(params, sk, sv, tokens_chunk, offset):
            R = tokens_chunk.shape[0]
            pos = offset + jnp.broadcast_to(
                jnp.arange(chunk, dtype=jnp.int32), (R, chunk)
            )
            lens = jnp.full((R,), offset + chunk, jnp.int32)
            logits, (sk, sv) = M.forward(
                params, cfg, tokens_chunk, pos, (sk, sv), lens,
                attn_impl=attn_impl,
            )
            return sk, sv, logits  # logits [R, chunk, V]

        return chunk_step

    def _ragged_jit(
        self, window: int, steps: int, sampled: bool, chunk: int, rows: int
    ) -> Any:
        """THE unified prefill+decode wave dispatch (ISSUE 6): one jitted
        invocation that advances the active decode rows by ``steps``
        tokens AND the inflight admission wave by one prefill chunk —
        the ragged batch of arXiv:2604.15464's design, expressed as one
        XLA program (one launch, one retirement-mask chain, one host
        sync) instead of the bifurcated admission-dispatch + decode-
        dispatch pair.  Both halves trace the SAME body builders as their
        standalone jits, so ragged-on output is structurally identical to
        ragged-off."""
        page = self.runtime.page_size
        wkey = -(-window // page) if self._paged else window
        key = ("ragged", wkey, steps, sampled, chunk, rows)
        fn = self._decode_jits.get(key)
        if fn is not None:
            return fn
        chunk_fn = self._chunk_fn(chunk)
        if self._paged:
            decode_fn = self._decode_fn_paged(wkey, steps, sampled)

            def ragged_paged(
                params, k, v, tables, last, lens, active, done_prev,
                stop_table, hard_end, slot_keys, temp, top_k, top_p,
                sk, sv, tokens_chunk, offset,
            ):
                sk, sv, logits = chunk_fn(params, sk, sv, tokens_chunk, offset)
                out = decode_fn(
                    params, k, v, tables, last, lens, active, done_prev,
                    stop_table, hard_end, slot_keys, temp, top_k, top_p,
                )
                return (*out, sk, sv, logits)

            fn = jax.jit(ragged_paged, donate_argnums=(1, 2, 14, 15))
        else:
            decode_fn = self._decode_fn_dense(window, steps, sampled)

            def ragged_dense(
                params, k, v, last, lens, active, done_prev,
                stop_table, hard_end, slot_keys, temp, top_k, top_p,
                sk, sv, tokens_chunk, offset,
            ):
                sk, sv, logits = chunk_fn(params, sk, sv, tokens_chunk, offset)
                out = decode_fn(
                    params, k, v, last, lens, active, done_prev,
                    stop_table, hard_end, slot_keys, temp, top_k, top_p,
                )
                return (*out, sk, sv, logits)

            fn = jax.jit(ragged_dense, donate_argnums=(1, 2, 13, 14))
        self._decode_jits[key] = fn
        return fn

    def _seed_scratch_jit(self, bucket: int, n_pages: int, rows: int) -> Any:
        """Fresh chunk-lane scratch with every row's first ``n_pages``
        pages gathered from the paged pool (prefix-cache reuse; ids is
        [rows, n_pages]).  One compile per (bucket, n_pages, rows) —
        reuse lengths are page-aligned, so the variant count is bounded
        by bucket/page times the power-of-two wave widths."""
        key = ("seed", bucket, n_pages, rows)
        fn = self._prefill_jits.get(key)
        if fn is not None:
            return fn
        cfg = self.config
        page = self.runtime.page_size

        def seed(pool_k, pool_v, ids):
            def gather(pool_side):
                g = pool_side[:, ids]  # [L, R, n, K, page, hd]
                L, R, n, K, ps, hd = g.shape
                return g.transpose(0, 1, 3, 2, 4, 5).reshape(
                    L, R, K, n * ps, hd
                )

            shape = (
                cfg.n_layers, rows, cfg.n_kv_heads, bucket, cfg.head_dim
            )
            sk = jnp.zeros(shape, pool_k.dtype)
            sv = jnp.zeros(shape, pool_v.dtype)
            sk = sk.at[:, :, :, : n_pages * page].set(gather(pool_k))
            sv = sv.at[:, :, :, : n_pages * page].set(gather(pool_v))
            return sk, sv

        fn = jax.jit(seed)
        self._prefill_jits[key] = fn
        return fn

    def _finalize_jit(self, bucket: int, rows: int, sampled: bool) -> Any:
        """The chunked wave's landing: scatter the finished scratch into the
        cache (rows or pages), install sampling state, sample first tokens
        from the LAST chunk's logits (same-bucket admission ⇒ every row's
        final position lives in the final chunk)."""
        fn = self._prefill_jits.get(("final", bucket, rows, sampled))
        if fn is not None:
            return fn
        cfg = self.config
        paged = self._paged
        chunk = min(self.runtime.prefill_chunk, bucket)

        def finalize(
            k, v, sk, sv, last, lens, slots, true_lens, last_chunk_logits,
            slot_keys, temp, top_k, top_p,
            seeds, w_temp, w_top_k, w_top_p,
            tables=None, page_rows=None, scatter_ids=None,
        ):
            # logits index local to the final chunk
            idx = jnp.clip(true_lens - 1 - (bucket - chunk), 0, chunk - 1)
            last_logits = jnp.take_along_axis(
                last_chunk_logits, idx[:, None, None], axis=1
            )[:, 0]
            return _finalize_wave_math(
                cfg, paged, sampled,
                k, v, sk, sv, last, lens, slots, true_lens, last_logits,
                slot_keys, temp, top_k, top_p,
                seeds, w_temp, w_top_k, w_top_p,
                tables, page_rows, scatter_ids,
            )

        # donate the cache (k/v alias their outputs); sk/sv have NO
        # same-shaped output to alias into, so donating them only emits
        # "donated buffers were not usable" warnings — peak HBM at landing
        # (cache + scratch) already equals the chunk-step peak either way
        fn = jax.jit(finalize, donate_argnums=(0, 1, 4, 5))
        self._prefill_jits[("final", bucket, rows, sampled)] = fn
        return fn

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._loop = asyncio.get_running_loop()
        # SIGUSR2 dumps every live journal (best-effort: non-main-thread
        # or signal-less platforms simply skip; recording still works)
        flightrec.install_sigusr2()
        self._task = self._loop.create_task(self._serve(), name="inference-engine")
        if self.runtime.watchdog_stall_s > 0:
            self._progress_at = cancellation.wall_clock()
            self._watchdog_task = self._loop.create_task(
                self._watchdog(), name="inference-engine-watchdog"
            )

    async def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._watchdog_task is not None:
            self._watchdog_task.cancel()
            try:
                await self._watchdog_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._watchdog_task = None
        if self._task is not None:
            try:
                await asyncio.wait_for(self._task, timeout=30)
            except asyncio.TimeoutError:
                self._task.cancel()
            self._task = None
        self._finish_all()
        # a stopped engine must not pin a stale count in the process gauge
        _drop_engine_active(id(self))

    def _finish_all(self) -> None:
        """Terminate every waiter: active slots AND still-queued requests
        (a queued request left without _DONE hangs its generate() forever)."""
        if self._pend is not None:
            # abandon the in-flight dispatch; its deferred frees must
            # still run or the slots/pages leak into the next start()
            self._free_deferred(self._pend)
            self._pend = None
        for request in list(self._active.values()):
            request.out.put_nowait(_DONE)
        self._active.clear()
        for request in self._carry:
            request.out.put_nowait(_DONE)
        self._carry.clear()
        if self._inflight is not None:
            for request in self._inflight["wave"]:
                request.out.put_nowait(_DONE)
            self._inflight = None
        while self._pending:
            self._pending.popleft().out.put_nowait(_DONE)
        if self._long is not None:
            self._long["request"].out.put_nowait(_DONE)
            self._long = None
        if self._long_inflight is not None:
            self._long_inflight["request"].out.put_nowait(_DONE)
            self._long_inflight = None
        while self._long_pending:
            self._long_pending.popleft().out.put_nowait(_DONE)

    # -------------------------------------------------------------- submit
    async def generate(
        self,
        prompt: list[int],
        *,
        max_new_tokens: int = 256,
        stop_tokens: frozenset[int] = frozenset(),
        sampling: SamplingParams | None = None,
        seed: int | None = None,
        corr: str | None = None,
        run: str | None = None,
        deadline: float | None = None,
        lease: "tuple[str, float] | None" = None,
        priority: "str | None" = None,
    ) -> AsyncIterator[int]:
        """Submit a prompt; yields generated token ids as they decode.

        ``sampling``/``seed`` override the engine defaults for this request
        only — requests with different settings share decode dispatches
        (row-wise sampling state).  Abandoning the iterator cancels the
        request: its slot is reclaimed at the next scheduler tick.
        ``corr`` tags the request's flight-recorder events with its
        trace/correlation id (``ck timeline``'s join key).  ``run`` is
        the logical run id (x-mesh-run), when present — the capacity
        ledger attributes the request's HBM pages to it (ISSUE 19).

        ``deadline`` is the request's ABSOLUTE wall-clock deadline (epoch
        seconds on :func:`calfkit_tpu.cancellation.wall_clock`): an
        already-expired submit raises :class:`DeadlineExceededError`
        immediately, and a queued or active request whose deadline passes
        is reaped through the cancellation path (the stream then raises
        the same typed error).  With ``RuntimeConfig.max_pending`` set, a
        submit that finds its lane's queue full is SHED with a typed
        :class:`EngineOverloadedError` — O(1), before any device work.

        ``lease`` is the CALLER's liveness lease ``(lease_id, ttl_s)``
        (ISSUE 10): the run registers against it, and the orphan reaper
        abandons it — queued or active, slot/pages/prefix refs freed
        through the ordinary retirement path — once the caller's
        heartbeats lapse past the TTL (typed :class:`RunOrphanedError`
        on the stream).  A lease already lapsed at submit is refused
        before any device work, like an expired deadline.

        ``priority`` is the caller's QoS class (ISSUE 20):
        ``"interactive"`` | ``"batch"``; anything else (including None)
        resolves to the mesh default.  Under overload batch-class work
        degrades FIRST: an interactive submit at a full lane evicts a
        queued batch request (oldest lease beat first) instead of being
        shed, and the deadline/orphan reapers take batch before
        interactive at equal expiry.
        """
        req_priority = qos.resolve_priority(priority)
        if not self._running:
            raise InferenceError("engine not started")
        if self._wedged:
            # fast typed rejection while wedged: admitting work behind a
            # hung device grant would only grow the pile the watchdog
            # just faulted — callers should be failing over
            self.stats.watchdog_faulted += 1
            raise EngineWedgedError(
                "engine is wedged (no dispatch progress for "
                f"{self.runtime.watchdog_stall_s:.1f}s with work pending); "
                "retry against another replica",
                stalled_s=self.runtime.watchdog_stall_s,
            )
        if deadline is not None:
            overdue = cancellation.wall_clock() - deadline
            if overdue >= 0:
                # expired on arrival: record the fault fast — admitting it
                # would burn prefill + decode dispatches for a dead caller
                self.stats.expired_requests += 1
                self._count_expired_class(req_priority)
                self._journal.append(
                    flightrec.EV_EXPIRE, corr, -1, int(overdue * 1000)
                )
                raise DeadlineExceededError(
                    f"request expired {overdue:.3f}s before admission"
                )
        if lease is not None and leases.lease_lapsed(lease[0]):
            # orphaned on arrival: the caller was already gone when this
            # submit reached the engine — admitting it would burn a full
            # prefill+decode for nobody (the EXPIRE-at-submit twin)
            self.stats.orphaned_requests += 1
            self._journal.append(flightrec.EV_ORPHAN, corr, -1, 0)
            raise RunOrphanedError(
                "caller lease lapsed before admission",
                lease_id=lease[0],
            )
        long_lane = len(prompt) >= self.runtime.max_seq_len
        if long_lane and not self.runtime.long_context:
            raise InferenceError(
                f"prompt of {len(prompt)} tokens exceeds max_seq_len "
                f"{self.runtime.max_seq_len} "
                "(enable RuntimeConfig(long_context=True) to serve it via "
                "the sequence-parallel lane)"
            )
        if long_lane and len(prompt) > self._long_max_prompt():
            raise InferenceError(
                f"prompt of {len(prompt)} tokens exceeds long_max_prompt "
                f"{self._long_max_prompt()}"
            )
        request = GenRequest(
            prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            stop_tokens=stop_tokens,
            sampling=sampling,
            seed=seed,
            corr=corr,
            run=run,
            deadline=deadline,
            priority=req_priority,
        )
        if lease is not None:
            request.lease_id, request.lease_ttl = lease
        self._journal.append(
            flightrec.EV_SUBMIT, corr, -1, len(request.prompt), max_new_tokens
        )
        if self._drafter is not None and not long_lane:
            # drafters read prompt + emitted history (the long lane decodes
            # through its own sp dispatch and never speculates)
            request.history = list(prompt)
        if long_lane:
            if max_new_tokens > self.runtime.long_new_cap:
                # the carried fresh cache is statically sized by the cap,
                # so the budget CANNOT be honored — fault by default (the
                # caller's token budget is a contract; silently shrinking
                # it corrupted downstream accounting) unless the caller
                # explicitly negotiated clamping via the config flag
                if not self.runtime.long_clamp_new_tokens:
                    raise InferenceError(
                        f"long-context request asked for {max_new_tokens} "
                        f"new tokens but long_new_cap is "
                        f"{self.runtime.long_new_cap}; lower "
                        f"max_new_tokens, raise RuntimeConfig.long_new_cap, "
                        "or opt in to clamping with "
                        "RuntimeConfig(long_clamp_new_tokens=True)"
                    )
                request.max_new_tokens = self.runtime.long_new_cap
                logger.warning(
                    "long request clamped to long_new_cap=%d new tokens "
                    "(long_clamp_new_tokens=True)",
                    self.runtime.long_new_cap,
                )
            if not self._effective_sampling(request).is_greedy:
                # covers a non-greedy ENGINE default too, not just
                # per-request settings
                logger.warning(
                    "long-context lane decodes greedily; sampling settings "
                    "are ignored for this request"
                )
            self._shed_if_full("long", len(self._long_pending), request)
            self._long_pending.append(request)
            self._submit_deadline(request)
            self._submit_lease(request)
            self._wake.set()
            inner = self._consume(request)
            try:
                async for item in inner:
                    yield item
            finally:
                await inner.aclose()
            return
        if (
            self.runtime.overlap_dispatch or self._spec is not None
        ) and len(stop_tokens) > self.runtime.max_stop_tokens:
            # device-side retirement scans a fixed-shape per-slot stop
            # table; silently truncating the set would MISS stops — fault
            raise InferenceError(
                f"request has {len(stop_tokens)} stop tokens but device-side"
                f" retirement caps the per-slot table at max_stop_tokens="
                f"{self.runtime.max_stop_tokens}; raise "
                "RuntimeConfig.max_stop_tokens (or set "
                "overlap_dispatch=False with speculation off for the "
                "host-side lockstep path)"
            )
        if self._paged:
            # reject what the pool could NEVER serve — re-queueing it would
            # wait (and starve everything behind it) forever
            reserve = self._reserve_pages(request, self._bucket_of(len(prompt)))
            usable = self._page_alloc.num_pages - 1
            if reserve > usable:
                raise InferenceError(
                    f"request needs {reserve} KV pages but the pool only has "
                    f"{usable}; lower max_new_tokens or raise num_kv_pages"
                )
        # the short-lane count includes _admitting (requests parked in the
        # chunked-admission window): they hold queue slots and page
        # reservations exactly like _pending entries, and excluding them
        # let a wave-heavy engine under-report pending in its shed replies
        self._shed_if_full(
            "short",
            len(self._pending) + len(self._carry) + len(self._admitting),
            request,
        )
        self._pending.append(request)
        self._submit_deadline(request)
        self._submit_lease(request)
        self._wake.set()
        inner = self._consume(request)
        try:
            async for item in inner:
                yield item
        finally:
            # aclose() on OUR iterator must cancel NOW, not whenever the
            # asyncgen finalizer gets around to collecting the inner one
            await inner.aclose()

    # ------------------------------------------------- overload protection
    def _count_shed_class(self, priority: str) -> None:
        if qos.class_rank(priority):
            self.stats.batch_shed += 1
        else:
            self.stats.interactive_shed += 1

    def _count_expired_class(self, priority: str) -> None:
        if qos.class_rank(priority):
            self.stats.batch_expired += 1
        else:
            self.stats.interactive_expired += 1

    @hotpath
    def _shed_victim(self, lane: str) -> "GenRequest | None":
        """Priority-ordered shed selection (ISSUE 20): the QUEUED
        batch-class request to evict so an arriving interactive request
        can take its place at a full lane.  Lease-aware ordering: among
        batch candidates, the one whose caller lease has the OLDEST
        beat sheds first — a leased-but-silent caller is the weakest
        claim on the queue, an actively-beating one the strongest.
        Un-leased (or never-beaten) requests read age 0.0 = most alive,
        so they shed last among batch.  Only queued entries are
        candidates — evicting an ACTIVE slot would discard paid prefill
        work.  None = no batch request queued (the incoming request
        sheds instead, whatever its class)."""
        queued = (
            self._long_pending
            if lane == "long"
            else (*self._carry, *self._pending, *self._admitting)
        )
        victim: "GenRequest | None" = None
        victim_age = -1.0
        for r in queued:
            if r.cancelled or not qos.class_rank(r.priority):
                continue
            age = leases.lease_age(r.lease_id)
            age = 0.0 if age is None else age
            if age > victim_age:
                victim, victim_age = r, age
        return victim

    def _shed_queued(
        self, victim: GenRequest, lane: str, pending: int, limit: int
    ) -> None:
        """Evict one queued batch request through the ordinary
        cancellation path: the reap frees its place, the consumer's
        _raise_terminal surfaces the same typed retriable
        EngineOverloadedError (with the same lane/pending/limit detail)
        a shed-at-submit would have."""
        victim.shed = True
        victim.shed_detail = (lane, pending, limit)
        victim.cancelled = True
        self._cancel_dirty = True
        self.stats.shed_requests += 1
        self._count_shed_class(victim.priority)
        self._journal.append(
            flightrec.EV_SHED, victim.corr, -1, pending, limit
        )
        self._wake.set()

    def _shed_if_full(
        self, lane: str, pending: int, request: GenRequest
    ) -> None:
        """Bounded admission (ISSUE 5), priority-ordered (ISSUE 20):
        when the lane's queue is at ``max_pending``, batch-class work
        sheds FIRST — an interactive submit evicts a queued batch
        request (oldest lease beat first) and is admitted in its place;
        only when no batch request is sheddable is the incoming request
        itself refused with a typed, retriable error.  Still O(queued)
        at worst and only on the full-lane path — the un-loaded submit
        stays the ISSUE 5 O(1) check — and the gate law holds
        structurally: an interactive request is never shed while any
        batch request is sheddable."""
        limit = self.runtime.max_pending
        if not limit or pending < limit:
            return
        if not qos.class_rank(request.priority):
            victim = self._shed_victim(lane)
            if victim is not None:
                self._shed_queued(victim, lane, pending, limit)
                return  # admitted in the victim's place
        self.stats.shed_requests += 1
        self._count_shed_class(request.priority)
        self._journal.append(
            flightrec.EV_SHED, request.corr, -1, pending, limit
        )
        raise EngineOverloadedError(
            f"{lane} lane has {pending} queued requests (max_pending="
            f"{limit}); retry with backoff or add capacity",
            lane=lane, pending=pending, limit=limit,
        )

    @hotpath
    def _reap_order(self, request: GenRequest, seq: int) -> "tuple[int, int]":
        """Class-weighted reap tiebreak (ISSUE 20): the heap-entry key
        between expiry and the request.  At EQUAL expiry (common under
        the sim's quantized clock, and whenever a storm's arrivals share
        a deadline) the batch-class entry sorts FIRST, so both reapers
        take batch before interactive — degradation stays ordered even
        at the reap.  Expiry itself is untouched: class never reaps a
        request before its actual deadline/lapse."""
        return (1 - qos.class_rank(request.priority), seq)

    def _submit_deadline(self, request: GenRequest) -> None:
        """Register a deadlined request for the scheduler's expiry reap."""
        if request.deadline is None:
            return
        entry = [
            request.deadline,
            self._reap_order(request, next(self._deadline_seq)),
            request,
        ]
        request.deadline_entry = entry
        heapq.heappush(self._deadline_heap, entry)

    def _drop_deadline(self, request: GenRequest) -> None:
        """A finished request must not linger in the deadline heap until
        its deadline lazily pops: null the entry's request slot so the
        heap holds no strong reference to the dead prompt/history."""
        entry = request.deadline_entry
        if entry is not None:
            entry[2] = None
            request.deadline_entry = None

    def _request_live(self, request: GenRequest) -> bool:
        """Is this request still queued or holding engine resources?
        (Identity scan — only runs when a deadline actually expired.)"""
        if request.slot != -1:
            return True
        if self._long is not None and self._long["request"] is request:
            return True
        if (
            self._long_inflight is not None
            and self._long_inflight["request"] is request
        ):
            return True
        return any(
            r is request
            for r in (
                *self._carry, *self._pending, *self._long_pending,
                *self._admitting,
            )
        )

    @hotpath
    def _check_deadlines(self) -> None:
        """Reap queued AND active requests whose deadline passed, through
        the ordinary cancellation path (so overlap's one-dispatch-late
        retirement semantics hold unchanged).  O(1) per scheduler pass
        when nothing expired: one heap peek."""
        heap = self._deadline_heap
        if not heap:
            return
        now = cancellation.wall_clock()
        if heap[0][0] > now:
            return
        while heap and heap[0][0] <= now:
            _, _, request = heapq.heappop(heap)
            if (
                request is None  # finished: _drop_deadline nulled the entry
                or request.cancelled
                or not self._request_live(request)
            ):
                continue  # finished or already being reaped: lazy entry
            request.expired = True
            request.cancelled = True
            self._cancel_dirty = True
            self.stats.expired_requests += 1
            self._count_expired_class(request.priority)
            self._journal.append(
                flightrec.EV_EXPIRE, request.corr, request.slot,
                int((now - request.deadline) * 1000),
            )

    # ------------------------------------------------- orphan reaper
    # (ISSUE 10) The server-side half of failure recovery: a run whose
    # CALLER's liveness lease lapsed is abandoned through the ordinary
    # cancellation path — same reap, same one-dispatch-late retirement,
    # same slot/page/prefix accounting — with a typed, NON-retriable
    # ``mesh.orphaned`` terminal.  Precedence law (shared with
    # _raise_terminal; pinned in tests): wedged > expired > orphaned >
    # shed > stalled > plain cancel — exactly ONE typed error per run, checked
    # in the same order on both schedulers (ragged and bifurcated reap
    # through the same _reap_cancelled/_consume pair).

    @hotpath
    def _submit_lease(self, request: GenRequest) -> None:
        """Register a leased request for the orphan sweep (heap-shaped
        like _submit_deadline; un-leased requests cost nothing)."""
        if request.lease_id is None:
            return
        expiry = leases.lease_expiry(request.lease_id)
        if expiry is None:
            # never-beaten lease: grant a full TTL from now (the submit
            # itself is proof of life — the kernel stamps admission, but
            # direct engine callers may not)
            expiry = cancellation.wall_clock() + request.lease_ttl
        entry = [
            expiry, self._reap_order(request, next(self._lease_seq)), request,
        ]
        request.lease_entry = entry
        heapq.heappush(self._lease_heap, entry)

    @hotpath
    def _drop_lease(self, request: GenRequest) -> None:
        """Null a finished request's lease entry (the heap entry itself
        pops lazily) — mirrors _drop_deadline's memory law."""
        entry = request.lease_entry
        if entry is not None:
            entry[2] = None
            request.lease_entry = None

    @hotpath
    def _check_orphans(self) -> None:
        """Reap queued AND active runs whose caller lease lapsed.  O(1)
        per scheduler pass when no registered expiry has arrived: one
        heap peek.  A popped entry whose lease was refreshed by a newer
        beat is re-pushed at the new expiry — heartbeats keep a live
        caller's runs off the reap for one push per TTL, not per pass."""
        heap = self._lease_heap
        if not heap:
            return
        now = cancellation.wall_clock()
        gen = leases.release_generation()
        if gen != self._lease_release_gen:
            # a lease was RELEASED somewhere (clean caller close): its
            # runs must orphan NOW, ahead of their registered expiry —
            # one O(registered) sweep per release event, not per pass
            self._lease_release_gen = gen
            for entry in heap:
                request = entry[2]
                if (
                    request is not None
                    and not request.cancelled
                    and leases.lease_lapsed(request.lease_id, now)
                ):
                    entry[0] = now  # surfaces in the pop loop below
            heapq.heapify(heap)
        if heap[0][0] > now:
            return
        while heap and heap[0][0] <= now:
            entry = heapq.heappop(heap)
            request = entry[2]
            if (
                request is None  # finished: _drop_lease nulled the entry
                or request.cancelled
                or not self._request_live(request)
            ):
                continue
            expiry = leases.lease_expiry(request.lease_id)
            if expiry is None:
                expiry = entry[0] + request.lease_ttl
            if expiry > now:
                # the caller beat since registration: re-arm at the
                # fresh expiry and keep serving
                fresh = [
                    expiry,
                    self._reap_order(request, next(self._lease_seq)),
                    request,
                ]
                request.lease_entry = fresh
                heapq.heappush(heap, fresh)
                continue
            request.orphaned = True
            request.cancelled = True
            self._cancel_dirty = True
            self.stats.orphaned_requests += 1
            # clamp: a RELEASED lease reads expiry -inf (lapsed forever)
            self._journal.append(
                flightrec.EV_ORPHAN, request.corr, request.slot,
                int(min(now - expiry, 86400.0) * 1000),
            )

    def _check_stalls(self) -> None:
        """Bound per-request token delivery: a consumer that stopped
        draining its stream (``max_out_blocks`` undrained queue items)
        is stall-cancelled through the ordinary cancellation path — its
        accumulated blocks free with the request instead of growing
        forever."""
        bound = self.runtime.max_out_blocks
        if not bound:
            return
        stalled = [
            r for r in self._active.values()
            if not r.cancelled and r.out.qsize() > bound
        ]
        if self._long is not None:
            r = self._long["request"]
            if not r.cancelled and r.out.qsize() > bound:
                stalled.append(r)
        for request in stalled:
            request.stalled = True
            request.cancelled = True
            self._cancel_dirty = True
            self.stats.delivery_stalled += 1
            self._journal.append(
                flightrec.EV_CANCEL, request.corr, request.slot,
                request.out.qsize(),
            )

    # ------------------------------------------------- wedge watchdog
    def _note_progress(self) -> None:
        """A dispatch/wave actually LANDED (device produced output and the
        host observed it) — the watchdog's progress signal.  Called from
        the decode thread and the serve loop; a bare float store, so no
        lock.  Reads the wall_clock seam: the chaos virtual clock drives
        wedge detection deterministically."""
        self._progress_at = cancellation.wall_clock()

    def _watchdog_requests(self) -> "list[GenRequest]":
        """Every request the engine currently owes an outcome: active
        slots, queued lanes, mid-admission prefills, the inflight chunked
        wave, and the long lane."""
        out: list[GenRequest] = [
            *self._active.values(), *self._carry, *self._pending,
            *self._admitting, *self._long_pending,
        ]
        if self._inflight is not None:
            out.extend(self._inflight["wave"])
        if self._long is not None:
            out.append(self._long["request"])
        if self._long_inflight is not None:
            out.append(self._long_inflight["request"])
        return out

    def _work_pending(self) -> bool:
        return bool(
            self._active or self._pending or self._carry
            or self._admitting or self._inflight is not None
            or self._pend is not None or self._long is not None
            or self._long_inflight is not None or self._long_pending
        )

    async def _watchdog(self) -> None:
        """Dispatch-progress watchdog (ISSUE 9): its OWN task because the
        state it detects — a device grant that never returns — blocks the
        serve loop inside asyncio.to_thread, so no in-loop check can ever
        run.  Polls on real time; measures the stall on the wall_clock
        seam (deterministic under the chaos virtual clock)."""
        threshold = self.runtime.watchdog_stall_s
        interval = max(0.01, min(threshold / 4.0, 0.25))
        while self._running:
            await asyncio.sleep(interval)
            now = cancellation.wall_clock()
            if self._wedged:
                if self._progress_at > self._wedged_at:
                    # the grant came back: resume serving.  The faulted
                    # requests were flagged cancelled at the trip, so the
                    # ordinary reap frees their slots/pages on the very
                    # pass that just landed.
                    self._wedged = False
                    logger.warning(
                        "engine un-wedged: a dispatch landed after the "
                        "watchdog tripped; serving resumes"
                    )
                continue
            if not self._work_pending():
                # idle is not a stall: re-anchor so the next submit starts
                # its stall clock from now, not from the last busy period
                self._progress_at = now
                continue
            if now - self._progress_at >= threshold:
                self._trip_wedge(now - self._progress_at)

    def _trip_wedge(self, stalled_s: float) -> None:
        """Declare the engine wedged: journal + dump the flight recorder
        (the postmortem IS the decision sequence that led here), flip the
        readiness signal, and fault every owed request with the typed
        RETRIABLE EngineWedgedError so callers fail over NOW instead of
        burning the rest of their deadlines.  Requests are also flagged
        cancelled: if the wedge ever clears, the ordinary cancellation
        reap reclaims their slots/pages — nothing is freed here, because
        an in-flight dispatch may still write through them."""
        self._wedged = True
        self._wedged_at = cancellation.wall_clock()
        self.stats.watchdog_trips += 1
        requests = self._watchdog_requests()
        self._journal.append(
            flightrec.EV_WEDGE, None, -1, int(stalled_s * 1000),
            len(requests),
        )
        try:
            path = self._journal.dump(reason="wedge")
            logger.error(
                "engine WEDGED: no dispatch landing for %.1fs with %d "
                "request(s) pending; flight-recorder dump: %s",
                stalled_s, len(requests), path,
            )
        except Exception:  # noqa: BLE001 - the dump must never mask the fault
            logger.exception("flight-recorder wedge dump failed")
        faulted = 0
        for request in requests:
            if request.wedged:
                continue
            request.wedged = True
            request.cancelled = True
            faulted += 1
            # wake the consumer NOW — the serve loop that normally
            # delivers _DONE is the thing that is stuck
            request.out.put_nowait(_DONE)
        self.stats.watchdog_faulted += faulted
        self._cancel_dirty = True
        self._wake.set()

    def _note_cancel(self, request: GenRequest) -> None:
        """One cancelled request drained from any lane or queue: the
        journal line + counter.  Expiry- and stall-driven cancels were
        already recorded (EV_EXPIRE at the deadline reap, EV_CANCEL at
        the stall flag) and have their own counters — they ride the same
        drain but must not double-count as consumer cancels."""
        self._drop_deadline(request)
        self._drop_lease(request)
        if (
            request.expired or request.stalled or request.wedged
            or request.orphaned or request.shed
        ):
            # wedge-faulted requests were journaled/counted at the trip;
            # orphans at the reaper's EV_ORPHAN; priority-shed victims
            # at _shed_queued's EV_SHED
            return
        self._journal.append(flightrec.EV_CANCEL, request.corr, request.slot)
        self.stats.cancelled_requests += 1

    def cancel_correlation(self, corr: str) -> int:
        """Abandon every request tagged ``corr`` — the mesh ``cancel``
        record's fan-out target (see :mod:`calfkit_tpu.cancellation`; the
        engine registers itself at construction).  Event-loop context;
        returns how many requests were newly flagged.  The scheduler's
        next pass reaps them through the ordinary cancellation path.

        The decode thread concurrently retires slots out of ``_active``
        (flag-only protocol: every other reader runs on the serve loop,
        never alongside the decode tick — this is the one foreign-task
        scan), so the snapshot retries around a mid-iteration resize and,
        if the race persists, defers the match to the scheduler pass
        rather than ever dropping the cancel."""
        if not corr:
            return 0
        for _ in range(4):
            try:
                candidates: list[GenRequest] = [
                    *self._active.values(), *self._carry, *self._pending,
                    *self._long_pending, *self._admitting,
                ]
                break
            except RuntimeError:
                continue
        else:
            self._deferred_cancels.add(corr)
            self._wake.set()
            return 0
        if self._inflight is not None:
            candidates += self._inflight["wave"]
        if self._long is not None:
            candidates.append(self._long["request"])
        if self._long_inflight is not None:
            candidates.append(self._long_inflight["request"])
        matched = 0
        for request in candidates:
            if request.corr == corr and not request.cancelled:
                request.cancelled = True
                matched += 1
        if matched:
            self.stats.cancel_propagated += matched
            self._cancel_dirty = True
            self._wake.set()
        return matched

    def _raise_terminal(self, request: GenRequest) -> None:
        """Typed stream endings: an engine-initiated cancel must surface
        as a typed error at the consumer, not a silent short stream.

        THE precedence law (ISSUE 10 satellite; pinned for BOTH
        schedulers in tests — the ragged and bifurcated lanes share this
        one copy, so agreement is structural): **wedged > expired >
        orphaned > shed > stalled** — a run that is simultaneously
        several of these faults with exactly ONE typed error.  Wedged
        first because a live caller must fail over, not eat a dead-end
        fault; expired before orphaned because the deadline is the
        caller's own contract while orphanhood is the server's inference
        about the caller; a priority shed (ISSUE 20) after the
        non-retriable causes — a victim that also expired/orphaned has a
        truer, terminal cause, and surfacing the retriable shed instead
        would invite a retry for a spent budget; stalled last — a
        stalled consumer that also expired/orphaned/shed already has a
        truer cause."""
        if request.wedged:
            # checked FIRST: a wedged request may also look expired by the
            # time its consumer resumes, but the watchdog faulted it so
            # the caller would fail over — the retriable code must win
            raise EngineWedgedError(
                "engine wedged while this request was pending "
                f"({request.generated} tokens delivered); "
                "retry against another replica",
                stalled_s=self.runtime.watchdog_stall_s,
            )
        if request.expired:
            raise DeadlineExceededError(
                f"request deadline passed after {request.generated} "
                "generated tokens"
            )
        if request.orphaned:
            raise RunOrphanedError(
                "caller lease lapsed; the run was reaped after "
                f"{request.generated} generated tokens",
                lease_id=request.lease_id or "",
            )
        if request.shed:
            # priority-ordered shedding (ISSUE 20): this queued
            # batch-class request was evicted to admit interactive work
            # at a full lane — the same typed RETRIABLE code (and the
            # same lane/pending/limit detail) as a shed-at-submit, so
            # callers back off identically whichever side of the queue
            # the shed landed on
            lane, pending, limit = request.shed_detail or (
                "short", 0, self.runtime.max_pending or 0
            )
            raise EngineOverloadedError(
                f"queued batch-class request was shed from the {lane} "
                f"lane to admit interactive work (pending={pending}, "
                f"max_pending={limit}); retry with backoff",
                lane=lane, pending=pending, limit=limit,
            )
        if request.stalled:
            raise EngineOverloadedError(
                "token delivery stalled past max_out_blocks="
                f"{self.runtime.max_out_blocks}; request was cancelled",
                lane="delivery",
                pending=request.out.qsize(),
                limit=self.runtime.max_out_blocks,
            )

    async def _consume(self, request: GenRequest) -> AsyncIterator[int]:
        """Drain a queued request's tokens; abandoning the iterator flags
        cancellation for the scheduler to reap (both lanes share this)."""
        done = False
        try:
            while True:
                item = await request.out.get()
                if item is _DONE:
                    done = True
                    self._raise_terminal(request)
                    return
                if type(item) is list:  # one dispatch's token block
                    for token in item:
                        if token is _DONE:
                            done = True
                            self._raise_terminal(request)
                            return
                        yield token
                    continue
                yield item
        finally:
            if not done:
                request.cancelled = True
                self._cancel_dirty = True
                self._wake.set()

    # ------------------------------------------------------------ scheduler
    async def _serve(self) -> None:
        try:
            while self._running:
                if self._chaos is not None:
                    self._chaos("tick")
                self._drain_deferred_cancels()
                self._check_deadlines()
                self._check_orphans()
                self._check_stalls()
                self._reap_cancelled()
                if self._ragged:
                    # ragged unified waves: ONE scheduler lane — the pass
                    # forms/advances the admission wave and the decode
                    # rows through a single fused dispatch per tick
                    progressed = await self._ragged_pass()
                    progressed |= await self._advance_long()
                    if not progressed:
                        self._wake.clear()
                        if (
                            not self._pending and not self._carry
                            and not self._long_pending and self._long is None
                        ):
                            await self._wake.wait()
                    continue
                if self.runtime.chunked_prefill:
                    progressed = await self._admit_chunked()
                else:
                    progressed = await self._admit()
                progressed |= await self._advance_long()
                if self._active:
                    await asyncio.to_thread(
                        self._spec_decode_tick
                        if self._drafter is not None
                        else self._decode_tick
                    )
                elif self._pend is not None:
                    # every participant retired/cancelled while a dispatch
                    # was still in flight: land it (discarding pad tokens)
                    # so the deferred slot/page frees actually happen
                    await asyncio.to_thread(self._drain_decode)
                elif not progressed and self._inflight is None:
                    self._wake.clear()
                    if (
                        not self._pending and not self._carry
                        and not self._long_pending and self._long is None
                    ):
                        await self._wake.wait()
        except Exception as exc:  # noqa: BLE001
            logger.exception("inference engine scheduler crashed")
            # atomicity-ok: the crash rail parks the loop's own run flag —
            # stop() writing False concurrently is the same terminal state
            self._running = False
            # fault postmortem: the ring holds the exact decision sequence
            # that led here — dump it next to the traceback.  Strictly
            # fail-open: a broken journal writer must never mask the
            # original fault or block the teardown below.
            try:
                self._journal.append(
                    flightrec.EV_FAULT, None, -1, 0, 0, repr(exc)
                )
                path = self._journal.dump(reason="fault")
                logger.error("flight-recorder fault dump: %s", path)
            except Exception:  # noqa: BLE001
                logger.exception("flight-recorder fault dump failed")
            self._finish_all()

    def _drain_deferred_cancels(self) -> None:
        """Re-run cancel matches that lost the snapshot race (serve-loop
        context: the decode tick is not in flight, so the snapshot cannot
        fail again; a pathological re-defer lands in the fresh set and
        retries next pass instead of spinning)."""
        if not self._deferred_cancels:
            return
        pending, self._deferred_cancels = list(self._deferred_cancels), set()
        for corr in pending:
            self.cancel_correlation(corr)

    def _reap_cancelled(self) -> None:
        """Drain cancelled requests: active slots AND still-queued entries.

        Runs on the event loop between device dispatches (the decode thread
        also mutates ``_active``, so cancellation itself only sets a flag).
        Queued entries must be drained here too — leaving them in place
        would keep ``_pending`` non-empty and turn the idle wait in
        ``_serve`` into a busy spin with no suspension point.

        A chunked inflight wave whose members ALL cancelled is aborted
        outright (slots + page reservations released, remaining chunks
        skipped); partially-cancelled waves finish their flight and shed
        the cancelled members at activation.

        The dirty flag keeps this O(1) on the ordinary pass: the full
        scan over active/carry/pending/long only runs after some consumer
        actually set a ``cancelled`` flag since the last reap.
        """
        if not self._cancel_dirty:
            return
        self._cancel_dirty = False
        if self._inflight is not None and all(
            r.cancelled for r in self._inflight["wave"]
        ):
            for request in self._inflight["wave"]:
                self._note_cancel(request)
                if request.slot != -1:
                    self._retire_slot(request)
                request.out.put_nowait(_DONE)
            self._inflight = None
        for request in list(self._active.values()):
            if request.cancelled:
                self._note_cancel(request)
                self._retire_slot(request)
                request.out.put_nowait(_DONE)
        if any(r.cancelled for r in self._carry):
            kept = []
            for request in self._carry:
                if request.cancelled:
                    self._note_cancel(request)
                    request.out.put_nowait(_DONE)
                else:
                    kept.append(request)
            self._carry = kept
        if any(r.cancelled for r in self._pending):
            kept_q: deque[GenRequest] = deque()  # unbounded-ok: rebuild of the shed-bounded queue
            for request in self._pending:
                if request.cancelled:
                    self._note_cancel(request)
                    request.out.put_nowait(_DONE)
                else:
                    kept_q.append(request)
            self._pending = kept_q
        if self._long is not None and self._long["request"].cancelled:
            self._note_cancel(self._long["request"])
            self._long["request"].out.put_nowait(_DONE)
            self._long = None
        if any(r.cancelled for r in self._long_pending):
            kept_l: deque[GenRequest] = deque()  # unbounded-ok: rebuild of the shed-bounded queue
            for request in self._long_pending:
                if request.cancelled:
                    self._note_cancel(request)
                    request.out.put_nowait(_DONE)
                else:
                    kept_l.append(request)
            self._long_pending = kept_l

    def _next_pending(self) -> GenRequest | None:
        while self._carry or self._pending:
            request = (
                self._carry.pop(0) if self._carry else self._pending.popleft()
            )
            if request.cancelled:
                self._note_cancel(request)
                request.out.put_nowait(_DONE)
                continue
            return request
        return None

    def _peek_pending(self) -> GenRequest | None:
        for request in (*self._carry, *self._pending):
            if not request.cancelled:
                return request
        return None

    def _reserve_pages(self, request: GenRequest, bucket: int) -> int:
        """Pages a request needs for its whole life: the prefill writes whole
        bucket pages, decode grows to (prompt + max_new), capped by the
        sequence limit."""
        from calfkit_tpu.inference.paged import pages_needed

        rt = self.runtime
        total = min(
            len(request.prompt) + request.max_new_tokens + 1, rt.max_seq_len
        )
        return min(
            max(
                pages_needed(bucket, rt.page_size),
                pages_needed(total, rt.page_size),
            ),
            rt.pages_per_seq(),
        )

    def _plan_prefix_reuse(self, request: GenRequest, bucket: int) -> int:
        """Longest cached, alignment-safe prompt prefix for ``request``
        (0 when caching is off or nothing matches).  Sets reuse_len /
        shared_pages / page_hashes on the request; recomputed fresh on
        every attempt (a carried-back request must not keep stale pages).

        Alignment: reuse must be whole PAGES (sharing granularity) and a
        whole number of CHUNKS (the chunk lane resumes at the reused
        offset), and at least the final chunk always recomputes (the
        first token samples from the last chunk's logits)."""
        request.reuse_len = 0
        request.shared_pages = []
        if self._prefix is None:
            return 0
        rt = self.runtime
        ps = rt.page_size
        if not request.page_hashes:  # prompt is immutable: hash ONCE
            from calfkit_tpu.inference.paged import chain_hashes

            request.page_hashes = chain_hashes(request.prompt, ps)
        if not request.page_hashes:
            return 0
        matched = self._prefix.lookup(request.page_hashes)
        if not matched:
            return 0
        chunk = min(rt.prefill_chunk, bucket)
        align = ps * chunk // math.gcd(ps, chunk)
        candidate = min(
            len(matched) * ps,
            len(request.prompt) - 1,  # never reuse the final position
            bucket - chunk,           # at least one chunk recomputes
        )
        reuse = (candidate // align) * align
        if reuse <= 0:
            return 0
        request.reuse_len = reuse
        request.shared_pages = matched[: reuse // ps]
        return reuse

    def _drop_reuse_plan(self, request: GenRequest) -> None:
        """Undo a formation-time acquisition for a request that will NOT
        be served this pass (alloc failure / wave trim) — re-admission
        replans from scratch."""
        if self._prefix is not None and request.shared_pages:
            self._journal.append(
                flightrec.EV_PREFIX_REL, request.corr, request.slot,
                len(request.shared_pages),
            )
            self._prefix.release(request.shared_pages)
            self._ledger.release(request.shared_pages)
        request.reuse_len = 0
        request.shared_pages = []

    def _alloc_with_eviction(
        self, slot: int, n: int, corr: "str | None" = None
    ) -> "list[int] | None":
        pages = self._page_alloc.alloc(slot, n)
        if pages is None:
            # density pressure is an advert signal whether or not the
            # cache can cover the shortfall (ISSUE 19)
            self._ledger.note_stall()
            self.stats.alloc_stalls += 1
        if pages is None and self._prefix is not None:
            # idle cache entries are reclaimable capacity, not a leak;
            # the journal records the SHORTFALL (what evict is asked to
            # reclaim), not the whole allocation request — tagged with
            # the REQUESTING owner, so `ck timeline` explains whose
            # admission forced the eviction
            self._journal.append(
                flightrec.EV_PAGE_EVICT, corr, slot,
                n - self._page_alloc.free_pages,
            )
            freed = self._prefix.evict(
                n - self._page_alloc.free_pages, self._page_alloc,
                ledger=self._ledger,
            )
            self.stats.prefix_evictions += freed
            pages = self._page_alloc.alloc(slot, n)
        return pages

    def _bucket_of(self, prompt_len: int) -> int:
        rt = self.runtime
        return min(
            -(-prompt_len // rt.prefill_chunk) * rt.prefill_chunk,
            rt.max_seq_len,
        )

    @hotpath
    def _form_wave(self) -> "tuple[list[GenRequest], int] | None":
        """Scheduling only (no device work): pop a same-bucket wave, assign
        slots (and, when paged, reserve each request's full page footprint —
        admission control, no mid-flight OOM).  None when nothing can be
        admitted right now."""
        if not self._free or self._peek_pending() is None:
            return None

        def bucket_of(req: GenRequest) -> int:
            return self._bucket_of(len(req.prompt))

        wave: list[GenRequest] = [self._next_pending()]
        wave_bucket = bucket_of(wave[0])
        # ragged mode: occupancy-driven admission — the wave may grow only
        # as wide as the token budget lets a dispatch absorb alongside
        # the CURRENT decode load (never below the head; legacy mode
        # returns the batch width and the cap is inert)
        width_cap = self._ragged_wave_cap(wave_bucket)
        head_reuse = self._plan_prefix_reuse(wave[0], wave_bucket)
        if head_reuse:
            # acquire at FORMATION: a later member's _alloc_with_eviction
            # must never reclaim pages an earlier-planned member still
            # needs (acquired pages are not evictable)
            self._prefix.acquire(wave[0].shared_pages)
            self._ledger.acquire(wave[0].shared_pages)
            self._journal.append(
                flightrec.EV_PREFIX_ACQ, wave[0].corr, -1,
                len(wave[0].shared_pages),
            )
        while (
            len(wave) < len(self._free)
            and len(wave) < self.runtime.max_prefill_wave
            and len(wave) < width_cap
            and (peeked := self._peek_pending()) is not None
            and bucket_of(peeked) == wave_bucket
        ):
            # one offset per wave: only requests whose reuse TRIMS to the
            # head's length batch together (an identical-prompt burst —
            # the headline workload — batches fully once page 1 lands)
            planned = self._plan_prefix_reuse(peeked, wave_bucket)
            if head_reuse == 0 and planned != 0:
                break
            if head_reuse > 0:
                if planned < head_reuse:
                    break
                peeked.reuse_len = head_reuse
                peeked.shared_pages = peeked.shared_pages[
                    : head_reuse // self.runtime.page_size
                ]
                self._prefix.acquire(peeked.shared_pages)
                self._ledger.acquire(peeked.shared_pages)
                self._journal.append(
                    flightrec.EV_PREFIX_ACQ, peeked.corr, -1,
                    len(peeked.shared_pages),
                )
            wave.append(self._next_pending())
        # wave sizes are power-of-two so each prefill bucket compiles at
        # most log2(max_prefill_wave)+1 jit variants (R in 1,2,4,...)
        # instead of one per width; trimmed requests go to the FRONT
        # carry list, preserving arrival order
        keep = 1
        while keep * 2 <= len(wave):
            keep *= 2
        for trimmed in wave[keep:]:  # balance formation-time acquisitions
            self._drop_reuse_plan(trimmed)
        self._carry = wave[keep:] + self._carry
        wave = wave[:keep]
        if self._paged:
            # the tail of an unservable wave waits at the queue front
            granted: list[GenRequest] = []
            for i, request in enumerate(wave):
                slot = self._free.pop()
                need = self._reserve_pages(request, wave_bucket)
                shared = request.shared_pages  # acquired at formation
                need -= len(shared)
                pages = self._alloc_with_eviction(slot, need, request.corr)
                if pages is None:
                    self._free.append(slot)
                    # EVERY carried member's acquisition must be undone,
                    # or its refcount leaks and the pages become
                    # unevictable forever
                    for carried in wave[i:]:
                        self._drop_reuse_plan(carried)
                    self._carry = wave[i:] + self._carry
                    break
                request.slot = slot
                request.pages = shared + pages
                self._journal.append(
                    flightrec.EV_PAGE_ALLOC, request.corr, slot,
                    len(request.pages), len(shared),
                )
                self._ledger.alloc(
                    slot, len(pages), request.corr, request.run,
                    capacity.lane_kind(request.history),
                )
                granted.append(request)
            wave = granted
            if not wave:
                return None  # pool exhausted: wait for retirements
            # keep jit variants power-of-two after page trimming too
            keep = 1
            while keep * 2 <= len(wave):
                keep *= 2
            for request in wave[keep:]:
                self._journal.append(
                    flightrec.EV_PAGE_FREE, request.corr, request.slot
                )
                self._page_alloc.free(request.slot)
                self._ledger.free(request.slot)
                self._free.append(request.slot)
                request.slot = -1
                request.pages = []
                self._drop_reuse_plan(request)
            self._carry = wave[keep:] + self._carry
            wave = wave[:keep]
        else:
            for request in wave:
                request.slot = self._free.pop()
        self._journal.append(
            flightrec.EV_WAVE_FORM, None, -1, len(wave), wave_bucket
        )
        return wave, wave_bucket

    def _activate_wave(self, wave: list[GenRequest]) -> None:
        for request in wave:
            # a request can retire DURING its own prefill (first token
            # was a stop, or max_new_tokens == 1): _record_token already
            # freed its slot and set slot = -1 — don't resurrect it
            if request.slot == -1:
                continue
            if request.cancelled:
                # abandoned while its (chunked) admission was in flight:
                # release the slot + pages instead of activating a corpse
                self._note_cancel(request)
                self._retire_slot(request)
                request.out.put_nowait(_DONE)
                continue
            self._active[request.slot] = request
            self._journal.append(
                flightrec.EV_ADMIT, request.corr, request.slot,
                len(request.prompt), request.reuse_len,
            )
            self._track_retirement(request)
            # device-side retirement inputs for the slot: stop-token row
            # (-1 padded; the submit-time cap guarantees it fits whenever
            # a device-authority path will read it) and hard-bound lens
            row = self._stop_np[request.slot]
            row[:] = -1
            stops = sorted(request.stop_tokens)[: row.shape[0]]
            row[: len(stops)] = stops
            self._hard_end[request.slot] = min(
                len(request.prompt) + request.max_new_tokens - 1,
                self.runtime.max_seq_len - 2,
            )
            self._retire_dev = None  # device copies stale: re-upload at launch
            if self._drafter is not None and request.history is not None:
                self._drafter.admit(request.slot, request.prompt)

    async def _admit(self) -> bool:
        admitted = False
        while (formed := self._form_wave()) is not None:
            wave, wave_bucket = formed
            self._admitting = wave
            try:
                await asyncio.to_thread(self._prefill_wave, wave, wave_bucket)
            finally:
                self._admitting = []
            self._activate_wave(wave)
            admitted = True
        return admitted

    # ------------------------------------------------- long-context lane
    # Prompts that cannot fit a short-lane slot are served one at a time:
    # sequence-parallel ring prefill shards the prompt over an `sp` mesh of
    # ALL the engine's devices, and decode runs context-parallel against
    # the still-sharded prefix (``ring_attention.decode_sp_dispatch``).
    # The lane interleaves with short-lane ticks in ``_serve``: one long
    # dispatch per scheduler pass, so short streams' inter-token latency
    # stays bounded while a long request is in flight.

    def _long_max_prompt(self) -> int:
        rt = self.runtime
        return rt.long_max_prompt or 8 * rt.max_seq_len

    def _sp_mesh(self) -> Any:
        if self._sp_mesh_cache is None:
            from jax.sharding import Mesh

            # blocking-ok: host-side Device-object list (mesh topology),
            # not a device array — nothing syncs; cached after first call
            devices = np.asarray(self.mesh.devices).reshape(-1)
            self._sp_mesh_cache = Mesh(devices, ("sp",))
        return self._sp_mesh_cache

    def _long_fresh_cap(self) -> int:
        """Static size of the carried fresh cache — ONE compile for every
        long request regardless of its max_new_tokens."""
        steps = self.runtime.decode_steps_per_dispatch
        return -(-self.runtime.long_new_cap // steps) * steps

    async def _advance_long(self) -> bool:
        if not self.runtime.long_context:
            return False
        if self._long is not None:
            await asyncio.to_thread(self._long_decode_tick)
            return True
        if self._long_inflight is not None:
            await asyncio.to_thread(self._advance_long_prefill)
            return True
        request = None
        while self._long_pending:
            candidate = self._long_pending.popleft()
            if candidate.cancelled:
                self._note_cancel(candidate)
                candidate.out.put_nowait(_DONE)
                continue
            request = candidate
            break
        if request is None:
            return False
        self._journal.append(
            flightrec.EV_ADMIT_LONG, request.corr, -1, len(request.prompt)
        )
        if self.runtime.chunked_prefill:
            # resumable: one chunk per scheduler pass, short decode ticks
            # run between chunks (same latency bound as the short lane)
            self._start_long_inflight(request)
            return True
        self._admitting = [request]
        try:
            await asyncio.to_thread(self._long_prefill, request)
        finally:
            self._admitting = []
        return True

    def _long_padded(self, n: int) -> int:
        """Pad to power-of-two multiples of lcm(sp, prefill_chunk): the
        sequence must divide over sp, and power-of-two bucketing bounds
        the sp-prefill compile count at log(range) shapes."""
        g = math.lcm(self._sp_mesh().shape["sp"], self.runtime.prefill_chunk)
        units = -(-n // g)
        p2 = 1
        while p2 < units:
            p2 *= 2
        return g * p2

    def _install_long_state(
        self, request: GenRequest, prefix: tuple, n: int, first: int,
        started: float,
    ) -> None:
        """Shared landing for both long-prefill paths: emit the first
        token and stage the decode-phase device state."""
        request.prefill_ms = (time.perf_counter() - started) * 1000.0
        self.stats.prefill_tokens += n
        self.stats.long_requests += 1
        self._observe("prefill_ms", request.prefill_ms)
        ttft_ms = (time.perf_counter() - request.started_at) * 1000.0
        self._observe("ttft_ms", ttft_ms)
        # the long lane's wait is everything before its prefill started
        self._observe("queue_wait_ms", max(0.0, ttft_ms - request.prefill_ms))
        if self._emit_long(request, first):
            return
        cfg = self.config
        cap = self._long_fresh_cap()
        fresh_shape = (cfg.n_layers, 1, cfg.n_kv_heads, cap, cfg.head_dim)
        self._long = dict(
            request=request,
            prefix=prefix,
            prefix_len=n,
            fresh=(
                jnp.zeros(fresh_shape, jnp.float32),
                jnp.zeros(fresh_shape, jnp.float32),
            ),
            t=0,
            cap=cap,
            last=jnp.asarray([first], jnp.int32),
        )

    def _long_prefill(self, request: GenRequest) -> None:
        from calfkit_tpu.inference.ring_attention import (
            prefill_sequence_parallel,
        )

        mesh = self._sp_mesh()
        n = len(request.prompt)
        padded = self._long_padded(n)
        tokens = np.zeros((1, padded), np.int32)
        tokens[0, :n] = request.prompt
        started = time.perf_counter()
        last_logits, (k_prefix, v_prefix) = prefill_sequence_parallel(
            self.params, self.config, jnp.asarray(tokens), mesh,
            seq_lens=jnp.asarray([n], jnp.int32),
        )
        first = int(np.asarray(jnp.argmax(last_logits[0])))
        self._install_long_state(
            request, (k_prefix, v_prefix), n, first, started
        )

    def _start_long_inflight(self, request: GenRequest) -> None:
        """Host-side setup of a resumable chunked long prefill: the SAME
        chunk program as the short lane (`_chunk_jit`), running over a
        sequence-sharded scratch sized for the padded prompt — GSPMD
        shards the chunk's attention over `sp` and inserts the collectives.
        Only chunks covering the true prompt run; padding is never
        touched (it stays zero and masked)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        cfg = self.config
        mesh = self._sp_mesh()
        n = len(request.prompt)
        padded = self._long_padded(n)
        chunk = min(self.runtime.prefill_chunk, padded)
        scratch_shape = (
            cfg.n_layers, 1, cfg.n_kv_heads, padded, cfg.head_dim
        )
        sharding = NamedSharding(mesh, P(None, None, None, "sp", None))
        tokens = np.zeros((1, padded), np.int32)
        tokens[0, :n] = request.prompt
        self._long_inflight = dict(
            request=request,
            tokens=tokens,
            true_len=n,
            chunk=chunk,
            n_chunks=-(-n // chunk),  # only chunks covering the prompt
            idx=0,
            # sharded AT CREATION: an eager zeros would materialize the
            # whole padded scratch on one device first — the exact OOM the
            # sp lane exists to avoid
            scratch=(
                jnp.zeros(scratch_shape, self._k.dtype, device=sharding),
                jnp.zeros(scratch_shape, self._k.dtype, device=sharding),
            ),
            started=time.perf_counter(),
        )

    def _advance_long_prefill(self) -> None:
        """One chunk of the inflight long prefill; land on the last."""
        inf = self._long_inflight
        request = inf["request"]
        if request.cancelled:
            self._note_cancel(request)
            self._long_inflight = None
            # runs on the to_thread worker: queue puts marshal to the loop
            self._loop.call_soon_threadsafe(request.out.put_nowait, _DONE)
            return
        chunk, idx = inf["chunk"], inf["idx"]
        sk, sv = inf["scratch"]
        tok_chunk = jnp.asarray(inf["tokens"][:, idx * chunk:(idx + 1) * chunk])
        sk, sv, logits = self._chunk_jit(chunk, 1)(
            self.params, sk, sv, tok_chunk, jnp.int32(idx * chunk)
        )
        inf["scratch"] = (sk, sv)
        inf["idx"] = idx + 1
        if inf["idx"] < inf["n_chunks"]:
            return
        # last prompt-covering chunk: the final valid position lives here
        n = inf["true_len"]
        local = (n - 1) - (inf["n_chunks"] - 1) * chunk
        first = int(np.asarray(jnp.argmax(logits[0, local])))
        self._long_inflight = None
        self._install_long_state(
            request, (sk, sv), n, first, inf["started"]
        )

    @hotpath
    def _long_decode_tick(self) -> None:
        """One long-lane pass.  Overlap mode gives the sp lane the same
        launch-next-then-sync-previous treatment as the short lane: the
        dispatch enqueued this pass runs on the mesh while the previous
        block's tokens fan out, so the lane's per-dispatch sync no longer
        serializes host and device.  A stop token found in the landed
        block abandons the already-launched follow-up (its steps count as
        ``overlap_wasted_tokens``; the per-request fresh cache it wrote
        is discarded with the state, so nothing shared is corrupted)."""
        from calfkit_tpu.inference.ring_attention import decode_sp_dispatch

        state = self._long
        request = state["request"]
        pend = state.pop("pend", None)
        launched: "dict | None" = None
        if state["t"] < state["cap"]:
            steps = min(
                self.runtime.decode_steps_per_dispatch,
                state["cap"] - state["t"],
            )
            started = time.perf_counter()
            toks, last, fresh = decode_sp_dispatch(
                self.params, self.config, state["last"], state["prefix"],
                jnp.asarray([state["prefix_len"]], jnp.int32),
                state["fresh"], state["t"], self._sp_mesh(), steps,
            )
            state["fresh"] = fresh
            state["last"] = last
            state["t"] += steps
            launched = dict(toks=toks, steps=steps, started=started)
        if self.runtime.overlap_dispatch:
            # double-buffered: the block launched THIS pass lands next
            # pass, with its follow-up already in flight
            state["pend"] = launched
            landing = pend
        else:
            landing = launched
        if landing is None:
            return  # first overlapped pass: launch only
        block = self._sync_host(landing["toks"])[0]  # host sync per dispatch
        now = time.perf_counter()
        start = landing["started"]
        last_sync = state.get("synced_at")
        if last_sync is not None and last_sync > start:
            start = last_sync  # exclusive wall (see _land_decode)
        state["synced_at"] = now
        # NOT decode_dispatches: that counter is mean_occupancy's
        # denominator, and a long dispatch uses the whole mesh, not slots
        self._note_progress()  # sp-lane landing: watchdog progress too
        self.stats.long_dispatches += 1
        self.stats.decode_time_s += now - start
        done = False
        for token in block:
            done = self._emit_long(request, int(token))
            if done:
                break
        inflight = state.get("pend")
        if done:
            if inflight is not None:
                # one-dispatch-late retirement, long-lane edition: the
                # pre-launched follow-up block is all pad now
                self.stats.overlap_wasted_tokens += inflight["steps"]
            self._drop_deadline(request)
            self._drop_lease(request)
            self._long = None
        elif state["t"] >= state["cap"] and inflight is None:
            self._drop_deadline(request)
            self._drop_lease(request)
            self._loop.call_soon_threadsafe(request.out.put_nowait, _DONE)
            self._long = None

    def _emit_long(self, request: GenRequest, token: int) -> bool:
        """Record one long-lane token (runs on the to_thread worker);
        returns True when the request retired."""
        items: list = []
        done = self._record_token(request, token, items, long=True)
        if items:
            self._loop.call_soon_threadsafe(
                _deliver_batch, [(request.out, items)]
            )
        return done

    # ------------------------------------------------------- device work
    def _effective_sampling(self, request: GenRequest) -> SamplingParams:
        return request.sampling if request.sampling is not None else self.sampling

    def _wave_arrays(self, wave: list[GenRequest], bucket: int) -> dict:
        """Host-side array prep shared by single-shot and chunked prefill."""
        R = len(wave)
        tokens = np.zeros((R, bucket), np.int32)
        true_lens = np.zeros((R,), np.int32)
        slots = np.zeros((R,), np.int32)
        seeds = np.zeros((R,), np.uint32)
        w_temp = np.zeros((R,), np.float32)
        w_top_k = np.zeros((R,), np.int32)
        w_top_p = np.ones((R,), np.float32)
        sampled = False
        for r, request in enumerate(wave):
            tokens[r, : len(request.prompt)] = request.prompt
            true_lens[r] = len(request.prompt)
            slots[r] = request.slot
            self._admissions += 1
            seeds[r] = (
                request.seed if request.seed is not None else self._admissions
            ) & 0xFFFFFFFF
            params = self._effective_sampling(request)
            w_temp[r] = params.temperature
            w_top_k[r] = params.top_k
            w_top_p[r] = params.top_p
            sampled |= not params.is_greedy
        return dict(
            tokens=tokens, true_lens=true_lens, slots=slots, seeds=seeds,
            w_temp=w_temp, w_top_k=w_top_k, w_top_p=w_top_p, sampled=sampled,
        )

    def _sampling_state_args(self, arrays: dict) -> list:
        return [
            self._slot_keys,
            self._temp,
            self._top_k,
            self._top_p,
            jnp.asarray(arrays["seeds"]),
            jnp.asarray(arrays["w_temp"]),
            jnp.asarray(arrays["w_top_k"]),
            jnp.asarray(arrays["w_top_p"]),
        ]

    def _paged_wave_args(self, wave: list[GenRequest], bucket: int) -> list:
        from calfkit_tpu.inference.paged import TRASH_PAGE, table_row

        R = len(wave)
        page = self.runtime.page_size
        pmax = self.runtime.pages_per_seq()
        npg = bucket // page
        page_rows = np.zeros((R, pmax), np.int32)
        scatter_ids = np.zeros((R, npg), np.int32)
        for r, request in enumerate(wave):
            page_rows[r] = table_row(request.pages, pmax)
            # prefill writes whole bucket pages; reservation covers them
            scatter_ids[r] = page_rows[r, :npg]
            if request.reuse_len:
                # reused pages are SHARED read-only: route their scatter
                # writes to the trash page (the scratch region is a copy
                # of what they already hold anyway)
                scatter_ids[r, : request.reuse_len // self.runtime.page_size] = (
                    TRASH_PAGE
                )
        return [self._tables, jnp.asarray(page_rows), jnp.asarray(scatter_ids)]

    def _land_wave(
        self, wave: list[GenRequest], true_lens: np.ndarray,
        firsts: np.ndarray, elapsed_ms: float,
    ) -> None:
        """Host side of the wave landing: stats, host-mirror lens, and the
        first-token emission — batched into ONE event-loop marshal for the
        whole wave.  The device-side last/lens scatter happens inside the
        prefill jit (``_finalize_wave_math``)."""
        deliveries: list[tuple[asyncio.Queue, list]] = []
        self._note_progress()  # a wave landing is watchdog progress
        self._observe("prefill_ms", elapsed_ms)
        self._journal.append(
            flightrec.EV_WAVE_LAND, None, -1, len(wave), int(elapsed_ms)
        )
        now = time.perf_counter()
        for r, request in enumerate(wave):
            if request.slot == -1:
                continue
            request.prefill_ms = elapsed_ms
            self.stats.prefill_tokens += int(true_lens[r])
            # per-request latency attribution: the wave lands the first
            # token, so submit→now IS the TTFT; what precedes the prefill
            # work is queue wait.  O(wave), never per token.
            ttft_ms = (now - request.started_at) * 1000.0
            self._observe("ttft_ms", ttft_ms)
            self._observe("queue_wait_ms", max(0.0, ttft_ms - elapsed_ms))
            # the prompt occupies [0, true_len); decode inserts from true_len
            self._host_lens[request.slot] = int(true_lens[r])
            items: list = []
            self._record_token(request, int(firsts[r]), items)
            if items:
                deliveries.append((request.out, items))
        if deliveries:
            self._loop.call_soon_threadsafe(_deliver_batch, deliveries)

    def _prefill_wave(self, wave: list[GenRequest], bucket: int) -> None:
        R = len(wave)
        arrays = self._wave_arrays(wave, bucket)
        started = time.perf_counter()
        fn = self._prefill_jit(bucket, R, arrays["sampled"])
        args = [
            self.params,
            self._k,
            self._v,
            self._last,
            self._lens,
            jnp.asarray(arrays["tokens"]),
            jnp.asarray(arrays["slots"]),
            jnp.asarray(arrays["true_lens"]),
            *self._sampling_state_args(arrays),
        ]
        if self._paged:
            args += self._paged_wave_args(wave, bucket)
        (
            self._k, self._v, tables, self._last, self._lens,
            self._slot_keys, self._temp, self._top_k, self._top_p, firsts,
        ) = fn(*args)
        if self._paged:
            self._tables = tables
        # sync BEFORE timing: with async dispatch, fn() returns before the
        # device runs — prefill_ms must be real latency, not enqueue time
        firsts = np.asarray(firsts)
        elapsed_ms = (time.perf_counter() - started) * 1000.0
        self._land_wave(wave, arrays["true_lens"], firsts, elapsed_ms)

    # --------------------------------------------------- chunked admission
    async def _admit_chunked(self) -> bool:
        """One scheduler pass of chunked admission: start an inflight wave
        if none, then advance it by ONE chunk (finalizing on the last).  A
        decode tick runs between passes, so active streams' inter-token
        latency is bounded by one chunk instead of a whole bucket.  This
        is the LEGACY (bifurcated) lane — with ragged waves on, the chunk
        instead rides the decode dispatch (:meth:`_ragged_pass`)."""
        if self._inflight is None:
            formed = self._form_wave()
            if formed is None:
                return False
            self._start_inflight_wave(*formed)
        finished = await asyncio.to_thread(self._advance_inflight)
        if finished:
            wave = self._inflight["wave"]
            self._inflight = None
            self._activate_wave(wave)
        return True

    def _start_inflight_wave(
        self, wave: "list[GenRequest]", bucket: int
    ) -> None:
        """Stage a formed wave for chunked advancement: allocate (or
        prefix-seed) the scratch and record the chunk cursor.  Shared by
        the legacy chunked lane and the ragged unified lane."""
        chunk = min(self.runtime.prefill_chunk, bucket)
        cfg = self.config
        R = len(wave)
        scratch_shape = (
            cfg.n_layers, R, cfg.n_kv_heads, bucket, cfg.head_dim
        )
        dtype = self._k.dtype
        reuse = wave[0].reuse_len  # uniform across the wave
        if reuse:
            # seed the scratch with the cached prefix K/V (each row's
            # pages gathered from the pool) and resume the chunk loop
            # at the reused offset — the chunk jit's offset is data,
            # so no new compile per reuse length
            npg_r = reuse // self.runtime.page_size
            ids = np.asarray(
                [request.pages[:npg_r] for request in wave], np.int32
            )
            scratch = self._seed_scratch_jit(bucket, npg_r, R)(
                self._k, self._v, jnp.asarray(ids)
            )
            self.stats.prefix_hits += len(wave)
            self.stats.prefix_reused_tokens += reuse * len(wave)
        else:
            scratch = (
                jnp.zeros(scratch_shape, dtype),
                jnp.zeros(scratch_shape, dtype),
            )
        self._inflight = dict(
            wave=wave, bucket=bucket, chunk=chunk,
            n_chunks=-(-bucket // chunk), idx=reuse // chunk,
            arrays=self._wave_arrays(wave, bucket),
            scratch=scratch,
            started=time.perf_counter(),
        )

    def _advance_inflight(self) -> bool:
        """Run one chunk of the inflight wave in its OWN device invocation
        (the legacy lane, and the ragged lane's fallback when the token
        budget refuses absorption); finalize after the last.  Returns True
        when the wave landed."""
        inf = self._inflight
        chunk = inf["chunk"]
        R = len(inf["wave"])
        idx = inf["idx"]
        sk, sv = inf["scratch"]
        tok_chunk = jnp.asarray(
            inf["arrays"]["tokens"][:, idx * chunk:(idx + 1) * chunk]
        )
        sk, sv, logits = self._chunk_jit(chunk, R)(
            self.params, sk, sv, tok_chunk, jnp.int32(idx * chunk)
        )
        inf["scratch"] = (sk, sv)
        inf["idx"] = idx + 1
        self._journal.append(
            flightrec.EV_PREFILL_CHUNK, None, -1, inf["idx"], inf["n_chunks"]
        )
        if inf["idx"] < inf["n_chunks"]:
            return False
        return self._finalize_inflight(logits)

    def _finalize_inflight(self, logits: Any) -> bool:
        """The chunked wave's landing (last chunk done): finalize jit,
        first-token sync, prefix registration.  One host sync per WAVE —
        shared by the legacy and ragged lanes.  ``logits`` is the final
        chunk's output, passed through (never stored on the inflight
        dict — a [R, chunk, vocab] buffer pinned between ticks would
        double transient logits HBM on large-vocab configs)."""
        inf = self._inflight
        wave, bucket = inf["wave"], inf["bucket"]
        arrays = inf["arrays"]
        R = len(wave)
        sk, sv = inf["scratch"]
        fn = self._finalize_jit(bucket, R, arrays["sampled"])
        args = [
            self._k, self._v, sk, sv, self._last, self._lens,
            jnp.asarray(arrays["slots"]),
            jnp.asarray(arrays["true_lens"]),
            logits,
            *self._sampling_state_args(arrays),
        ]
        if self._paged:
            args += self._paged_wave_args(wave, bucket)
        (
            self._k, self._v, tables, self._last, self._lens,
            self._slot_keys, self._temp, self._top_k, self._top_p, firsts,
        ) = fn(*args)
        if self._paged:
            self._tables = tables
        # blocking-ok: the prefill wave's designated LANDING sync — first
        # tokens must reach the host here for delivery and real TTFT
        # attribution; this is the admission lane's _sync_host analog
        firsts = np.asarray(firsts)  # sync before timing (real latency)
        elapsed_ms = (time.perf_counter() - inf["started"]) * 1000.0
        self._land_wave(wave, arrays["true_lens"], firsts, elapsed_ms)
        if self._prefix is not None:
            for request in wave:
                self._register_prefix_pages(request)
        return True

    # ------------------------------------------------- ragged unified waves
    # (ISSUE 6; arXiv:2604.15464) ONE scheduler lane: each pass enqueues a
    # single fused dispatch that advances the active decode rows AND the
    # inflight admission wave's next prefill chunk.  The last on-TPU bench
    # measured mean_batch_occupancy 0.365 — nearly two thirds of every
    # decode dispatch was idle compute; the ragged wave spends exactly
    # that slack on prefill, under an explicit token budget.

    async def _ragged_pass(self) -> bool:
        """One pass of the unified lane: form a wave when none is in
        flight (width capped by the token budget — occupancy-driven
        admission), then advance decode + chunk through one fused tick.
        Returns False only when there was nothing at all to do."""
        progressed = False
        if self._inflight is None:
            formed = self._form_wave()
            if formed is not None:
                self._start_inflight_wave(*formed)
                progressed = True
        if (
            self._active or self._inflight is not None
            or self._pend is not None
        ):
            finished = await asyncio.to_thread(self._ragged_tick)
            if finished:
                wave = self._inflight["wave"]
                self._inflight = None
                self._activate_wave(wave)
            progressed = True
        return progressed

    @hotpath
    def _ragged_tick(self) -> bool:
        """One tick of the unified lane (decode-thread context): launch
        the fused (or decode-only) dispatch, then land the previous one —
        the same double-buffered shape as :meth:`_decode_tick`, with the
        admission wave riding the launch.  Returns True when the inflight
        wave landed (the serve loop activates it)."""
        if self._drafter is not None:
            # speculation stays lockstep (the host drafter needs landed
            # history to propose), so there is no launch to fuse the
            # chunk into — the wave still rides THIS lane, one scheduler
            # pass, advancing right after the verify sync
            if self._active:
                self._spec_decode_tick()
            if self._inflight is not None:
                return self._advance_inflight()
            return False
        if self._chaos is not None and self._active:
            self._chaos("dispatch")
        pend = self._pend
        finished = False
        if self._active:
            finished = self._launch_ragged()
        else:
            self._pend = None
            if self._inflight is not None:
                finished = self._advance_inflight()
        if pend is not None:
            deliveries = self._land_decode(pend)
            if not self._active:
                # the landing retired every participant: drain the
                # follow-up before a consumer can observe completion
                # (the same invariant _decode_tick keeps)
                self._drain_decode()
            if deliveries:
                self._loop.call_soon_threadsafe(_deliver_batch, deliveries)
        return finished

    def _absorb_fits(self) -> bool:
        """May THIS dispatch absorb the inflight wave's next chunk?  The
        budget arithmetic lives in :mod:`calfkit_tpu.inference.ragged`."""
        inf = self._inflight
        return inf is not None and ragged_math.fits_budget(
            self._ragged_budget, len(self._active),
            self.runtime.decode_steps_per_dispatch,
            len(inf["wave"]), inf["chunk"],
        )

    def _ragged_wave_cap(self, bucket: int) -> int:
        """Admission-width bound at FORMATION time: how many prefill rows
        the budget lets a dispatch absorb alongside the current decode
        load.  Uses the wave's ACTUAL per-dispatch chunk —
        min(prefill_chunk, bucket) — so short-bucket waves are not
        admitted narrower than the budget allows (the same chunk
        ``_absorb_fits`` later charges).  Legacy mode returns the batch
        width (no extra bound)."""
        if not self._ragged:
            return self.runtime.max_batch_size
        return ragged_math.wave_width_cap(
            self._ragged_budget, len(self._active),
            self.runtime.decode_steps_per_dispatch,
            min(self.runtime.prefill_chunk, bucket),
        )

    def _launch_ragged(self) -> bool:
        """Enqueue ONE dispatch for this tick — fused decode+chunk when a
        wave is in flight and the token budget admits it, else plain
        decode (with the over-budget chunk advancing in its own
        invocation so admission never starves).  NO host sync anywhere on
        this path; the fused outputs ride ``self._pend`` to the next
        tick's landing exactly like a plain overlapped launch."""
        inf = self._inflight
        if inf is None or not self._absorb_fits():
            self._launch_decode()
            if inf is not None:
                return self._advance_inflight()
            return False
        args, window, steps, sampled = self._decode_args()
        if steps < self.runtime.decode_steps_per_dispatch:
            self.stats.short_dispatches += 1
        chunk, idx = inf["chunk"], inf["idx"]
        R = len(inf["wave"])
        sk, sv = inf["scratch"]
        tok_chunk = jnp.asarray(
            inf["arrays"]["tokens"][:, idx * chunk:(idx + 1) * chunk]
        )
        self._observe_gap()
        self._journal.append(
            flightrec.EV_DISPATCH_LAUNCH, None, -1, steps, len(self._active)
        )
        self._journal.append(
            flightrec.EV_RAGGED_WAVE, None, -1, len(self._active), R
        )
        started = time.perf_counter()
        (
            self._k, self._v, self._last, self._lens, toks, n_valid, done,
            sk, sv, logits,
        ) = self._ragged_jit(window, steps, sampled, chunk, R)(
            *args, sk, sv, tok_chunk, jnp.int32(idx * chunk)
        )
        inf["scratch"] = (sk, sv)
        inf["idx"] = idx + 1
        self._journal.append(
            flightrec.EV_PREFILL_CHUNK, None, -1, inf["idx"], inf["n_chunks"]
        )
        self.stats.prefill_absorbed_tokens += R * chunk
        self.stats.unified_dispatches += 1
        self._stage_pend(toks, n_valid, done, steps, started, extra_rows=R)
        if inf["idx"] == inf["n_chunks"]:
            return self._finalize_inflight(logits)
        return False

    def _register_prefix_pages(self, request: GenRequest) -> None:
        """After landing: publish the request's freshly-written
        full-prompt pages into the prefix cache.  Ownership transfers
        from the allocator (so retirement can't free shared pages under
        later readers); the owning slot holds a reference until it
        retires.  Decode never writes these pages: its first write lands
        at position prompt_len, which lives past every registered page."""
        if request.slot == -1:  # retired during its own prefill
            return
        ps = self.runtime.page_size
        full = len(request.prompt) // ps
        if len(request.page_hashes) < full:
            # safety net only: _plan_prefix_reuse hashes every planned
            # request, so this recompute should be unreachable — but
            # registration must never index past a stale hash list
            from calfkit_tpu.inference.paged import chain_hashes

            request.page_hashes = chain_hashes(request.prompt, ps)
        reused = len(request.shared_pages)
        fresh: list[int] = []
        fresh_hashes: list = []
        for i in range(reused, full):
            page = request.pages[i]
            if self._prefix.register(request.page_hashes[i], page):
                fresh.append(page)
                fresh_hashes.append(request.page_hashes[i])
            # else: another request registered this chain position first;
            # this duplicate page stays private (slot-held, freed at
            # retire) — but LATER positions must still register: agent
            # fleets share a scaffold/system page 0 across sessions, and
            # stopping at the first collision used to mean only the
            # FIRST session's chain ever entered the cache (every other
            # session re-prefilled its whole prompt forever).  Chain
            # hashing keeps mixed-origin chains content-correct: equal
            # hash ⇒ equal page content ⇒ lookup may stitch them.
        if fresh:
            self._page_alloc.transfer_out(request.slot, fresh)
            self._prefix.acquire(fresh)
            # ownership transition mirrored in the ledger: the fresh
            # pages leave the slot's private count and enter chain
            # ownership at refcount 1 (this request's own reference)
            self._ledger.transfer(request.slot, fresh, fresh_hashes)
            request.shared_pages = request.shared_pages + fresh

    @hotpath
    def _decode_tick(self) -> None:
        """One scheduler tick of the short decode lane.

        Overlapped mode (``runtime.overlap_dispatch``, the default):
        enqueue dispatch N+1 FIRST, then sync + fan out dispatch N — the
        device computes N+1 while the host does N's bookkeeping, so the
        inter-dispatch device-idle bubble collapses to the launch-enqueue
        cost.  Lockstep mode is the reference oracle: launch, sync, fan
        out, with the host as the retirement authority."""
        if self._chaos is not None:
            self._chaos("dispatch")
        if not self.runtime.overlap_dispatch:
            self._decode_tick_lockstep()
            return
        pend = self._pend
        if self._active:
            self._launch_decode()
        else:
            self._pend = None
        if pend is not None:
            deliveries = self._land_decode(pend)
            if not self._active:
                # the landing retired every participant: the dispatch
                # launched moments ago is all zombies.  Land it NOW,
                # before any consumer can observe completion — a caller
                # whose generate() returned must find slots/pages fully
                # accounted (the lockstep invariant, kept under overlap)
                self._drain_decode()
            if deliveries:
                self._loop.call_soon_threadsafe(_deliver_batch, deliveries)

    def _drain_decode(self) -> None:
        """Land an in-flight dispatch whose participants have all retired
        or cancelled (nothing live left to launch for)."""
        pend, self._pend = self._pend, None
        if pend is not None:
            deliveries = self._land_decode(pend)
            if deliveries:
                self._loop.call_soon_threadsafe(_deliver_batch, deliveries)

    def _sync_host(self, arrays: Any) -> Any:
        """THE designated device→host sync point of the dispatch loop —
        scripts/lint_hotpath.py bans blocking syncs everywhere else in the
        overlap-critical functions, so the double-buffering can't silently
        regress to one-sync-per-launch."""
        if isinstance(arrays, tuple):
            # blocking-ok: THE designated sync point (see docstring)
            return tuple(np.asarray(a) for a in arrays)
        # blocking-ok: THE designated sync point (see docstring)
        return np.asarray(arrays)

    def _decode_args(self) -> "tuple[list, int, int, bool]":
        """Assemble one decode dispatch's host-side inputs (shared by the
        overlap launch and the lockstep tick): returns (args, window,
        steps, sampled).  Pure host work — no device sync."""
        active_mask = np.zeros((self.runtime.max_batch_size,), bool)
        needed = 1
        for slot in self._active:
            active_mask[slot] = True
            needed = max(needed, self._host_lens[slot])
        # the ring covers in-dispatch growth; the window only needs to cover
        # what's already in the main cache
        window = self._window_bucket(int(needed))
        # admissions waiting AND a retirement in reach? shorten the dispatch
        # so the freed slot (and the waiter's prefill) isn't gated behind a
        # full tick; under saturation with no retirement near, full ticks
        # keep dispatch overhead amortized
        full = self.runtime.decode_steps_per_dispatch
        # length check only: this runs on the decode thread, and iterating
        # the deque (as _peek_pending does) races event-loop appends
        pending = bool(self._carry) or bool(self._pending)
        steps = (
            self._short_steps()
            if pending and self._retirement_near(full)
            else full
        )
        sampled = any(
            not self._effective_sampling(r).is_greedy
            for r in self._active.values()
        )
        prev = self._pend
        done_prev = prev["done_dev"] if prev is not None else self._done_zero
        stop_table, hard_end = self._retire_args()
        args = [self.params, self._k, self._v]
        if self._paged:
            args.append(self._tables)
        args += [
            self._last,
            self._lens,
            jnp.asarray(active_mask),
            done_prev,
            stop_table,
            hard_end,
            self._slot_keys,
            self._temp,
            self._top_k,
            self._top_p,
        ]
        return args, window, steps, sampled

    def _retire_args(self) -> "tuple[Any, Any]":
        """Device copies of the per-slot stop table + hard-bound lens —
        admission-time constants, re-uploaded only after an activation
        rewrote them (the launch path pays no per-dispatch transfer)."""
        if self._retire_dev is None:
            self._retire_dev = (
                jnp.asarray(self._stop_np), jnp.asarray(self._hard_end)
            )
        return self._retire_dev

    def _observe_gap(self) -> None:
        """The dispatch-gap bubble, observed immediately BEFORE each jit
        enqueue (after args prep — the device is idle through that prep
        too, so observing at tick entry would under-report): zero while a
        dispatch is already in flight (the device never idled), else the
        host-side span since the previous dispatch landed.  Reset across
        idle periods — an empty engine waiting for work is not a bubble."""
        if self._pend is not None:
            self._observe("dispatch_gap_ms", 0.0)
        elif self._last_sync_t is not None:
            self._observe(
                "dispatch_gap_ms",
                (time.perf_counter() - self._last_sync_t) * 1000.0,
            )

    def _launch_decode(self) -> None:
        """Enqueue the next decode dispatch — NO host sync.  The previous
        dispatch's device-side done mask rides in as ``done_prev``, so a
        row that retired in the still-in-flight block is frozen out of
        this one by pure device dataflow (its slot and pages stay held
        until that block lands: one-dispatch-late retirement)."""
        args, window, steps, sampled = self._decode_args()
        if steps < self.runtime.decode_steps_per_dispatch:
            self.stats.short_dispatches += 1
        self._observe_gap()
        self._journal.append(
            flightrec.EV_DISPATCH_LAUNCH, None, -1, steps, len(self._active)
        )
        started = time.perf_counter()
        (
            self._k, self._v, self._last, self._lens, toks, n_valid, done,
        ) = self._decode_jit(window, steps, sampled)(*args)
        self._stage_pend(toks, n_valid, done, steps, started)

    def _stage_pend(
        self, toks: Any, n_valid: Any, done: Any, steps: int,
        started: float, extra_rows: int = 0,
    ) -> None:
        """Record a just-enqueued dispatch as the in-flight pend (host
        lens advance + the landing's snapshot) — ONE copy shared by the
        plain and fused launches, so the two lanes' retirement
        bookkeeping cannot drift.  ``extra_rows`` counts absorbed
        prefill rows (occupancy participants landed with the dispatch)."""
        for slot in self._active:
            self._host_lens[slot] += steps
        self._pend = dict(
            toks_dev=toks,
            n_valid_dev=n_valid,
            done_dev=done,
            steps=steps,
            started=started,
            participants=list(self._active.items()),
            slot_set=set(self._active.keys()),
            deferred=[],
            extra_rows=extra_rows,
        )

    def _land_decode(self, pend: dict) -> "list[tuple[asyncio.Queue, list]]":
        """Host side of a landed dispatch: ONE sync for the token block
        plus the device-computed retirement arrays, then batched fan-out.
        The device is the retirement authority here — ``n_valid`` bounds
        each row's delivery, ``done`` retires it.  Rows whose requests
        retired or cancelled while this dispatch was in flight are pad
        columns: discarded (counted as ``overlap_wasted_tokens``), with
        their deferred slot/page frees released now that nothing in
        flight can touch them.  Returns the deliveries — the CALLER posts
        them, possibly after draining an all-zombie follow-up, so a
        consumer never observes completion before accounting settles."""
        block, n_valid, done = self._sync_host(
            (pend["toks_dev"], pend["n_valid_dev"], pend["done_dev"])
        )
        now = time.perf_counter()
        # exclusive wall: the launch happened before the PREVIOUS sync
        # returned, so clip to the span this dispatch alone occupied —
        # decode_time_s must keep approximating device-busy time, not
        # double-count the overlapped bookkeeping
        start = pend["started"]
        if self._last_sync_t is not None and self._last_sync_t > start:
            start = self._last_sync_t
        self._last_sync_t = now
        steps = pend["steps"]
        # occupancy participants: decode rows PLUS any prefill rows the
        # ragged scheduler absorbed into this dispatch (they hold slots;
        # a bifurcated schedule would have burned a whole extra dispatch
        # on them) — mean_occupancy is the unified-wave fill metric
        self._note_dispatch(
            now - start, steps,
            n_rows=len(pend["participants"]) + pend.get("extra_rows", 0),
        )
        deliveries: list[tuple[asyncio.Queue, list]] = []
        block_cols = np.ascontiguousarray(block.T)  # [B, steps]
        wasted = 0
        for slot, request in pend["participants"]:
            if self._active.get(slot) is not request:
                # one-dispatch-late retirement: the row retired (or its
                # consumer cancelled) while this block was in flight — the
                # whole column is pad, and nothing may reach its queue
                wasted += steps
                continue
            count = int(n_valid[slot])
            items: list = block_cols[slot][:count].tolist()
            request.generated += count
            self.stats.decode_tokens += count
            if done[slot]:
                self._retire_slot(request)
                items.append(_DONE)
            if items:
                deliveries.append((request.out, items))
        if wasted:
            self.stats.overlap_wasted_tokens += wasted
        self._journal.append(
            flightrec.EV_DISPATCH_LAND, None, -1, steps, wasted
        )
        self._free_deferred(pend)
        if not self._active:
            self._last_sync_t = None  # idle boundary, not a bubble
        return deliveries

    def _free_deferred(self, pend: dict) -> None:
        """Release the slots/pages of requests that retired while ``pend``
        was in flight.  Deferred to the landing so an in-flight dispatch
        can never write through a freshly-reallocated page (and shared
        prefix pages stay referenced while a dispatch still reads them)."""
        for slot, shared, corr in pend["deferred"]:
            if self._prefix is not None and shared:
                self._journal.append(
                    flightrec.EV_PREFIX_REL, corr, slot, len(shared)
                )
                self._prefix.release(shared)
                self._ledger.release(shared)
            if self._paged:
                self._journal.append(flightrec.EV_PAGE_FREE, corr, slot)
                self._page_alloc.free(slot)
                self._ledger.free(slot)
            self._free.append(slot)
            self._journal.append(flightrec.EV_SLOT_FREE, corr, slot)

    @hotpath
    def _decode_tick_lockstep(self) -> None:
        """The lockstep reference path: launch, sync, fan out — with the
        HOST as the retirement authority (arbitrary-size stop sets).  The
        overlapped path must produce byte-identical token streams; keep
        this oracle intact."""
        args, window, steps, sampled = self._decode_args()
        self._observe_gap()
        self._journal.append(
            flightrec.EV_DISPATCH_LAUNCH, None, -1, steps, len(self._active)
        )
        started = time.perf_counter()
        self._k, self._v, self._last, self._lens, toks, _n_valid, _done = (
            self._decode_jit(window, steps, sampled)(*args)
        )
        for slot in self._active:
            self._host_lens[slot] += steps
        block = self._sync_host(toks)  # [steps, B] — THE host sync per dispatch
        elapsed = time.perf_counter() - started
        self._last_sync_t = time.perf_counter()
        self._note_dispatch(elapsed, steps)
        self._journal.append(flightrec.EV_DISPATCH_LAND, None, -1, steps, 0)
        if steps < self.runtime.decode_steps_per_dispatch:
            self.stats.short_dispatches += 1
        # fan tokens out with ONE event-loop marshal per dispatch: a
        # call_soon_threadsafe per token costs ~65 us of loop machinery
        # each (scripts/sched_overhead.py found it dominating host cost at
        # bs=128), so bookkeeping runs here on the decode thread and the
        # queue puts cross threads as a single batch.  The common case —
        # no stop token in the block, bound not yet reached — ships the
        # whole column as one C-level tolist() with no per-token Python
        # loop (at bs=128 x steps=32 the per-token loop alone was ~1 ms
        # of the dispatch budget; sched_overhead.py r4).
        deliveries: list[tuple[asyncio.Queue, list]] = []
        block_cols = np.ascontiguousarray(block.T)  # [B, steps]
        for slot, request in list(self._active.items()):
            toks: list = block_cols[slot].tolist()
            # steps until a hard bound — the SAME formula the retire heap
            # predicts with (one authority, no drift)
            bound = max(0, self._retirement_bound(request))
            if not request.stop_tokens or not request.stop_tokens.intersection(toks):
                if bound > steps:
                    request.generated += steps
                    self.stats.decode_tokens += steps
                    deliveries.append((request.out, toks))
                else:
                    # bound falls inside this block: deliver up to it, retire
                    items = toks[:bound]
                    request.generated += bound
                    self.stats.decode_tokens += len(items)
                    self._retire_slot(request)
                    items.append(_DONE)
                    deliveries.append((request.out, items))
                continue
            # a stop token is present: per-token authority loop
            items = []
            for token in toks:
                if self._record_token(request, token, items):
                    break
            if items:
                deliveries.append((request.out, items))
        if not self._active:
            self._last_sync_t = None
        if deliveries:
            self._loop.call_soon_threadsafe(_deliver_batch, deliveries)

    def _note_dispatch(
        self, elapsed: float, clock_steps: int,
        tokens_per_row: float | None = None,
        n_rows: int | None = None,
    ) -> None:
        """Per-dispatch clock + stats shared by the plain decode tick and
        the speculative verify tick — ONE copy of the occupancy/clock
        accounting so the two modes cannot drift.

        ``tokens_per_row`` is the latency denominator when it differs from
        the clock: a verify dispatch advances the clock by 1 but emits
        each row's accepted prefix, so its inter-token latency is wall
        over MEAN EMITTED per row, not wall over 1.  ``n_rows`` pins the
        occupancy numerator to the dispatch's actual participant count
        (under overlap the landing runs after newer admissions changed
        ``_active``)."""
        with self._retire_lock:
            self._decode_clock += clock_steps
        self._note_progress()  # every landed dispatch is watchdog progress
        self.stats.decode_dispatches += 1
        self.stats.decode_time_s += elapsed
        rows = n_rows if n_rows is not None else len(self._active)
        occupancy = rows / self.runtime.max_batch_size
        self.stats.occupancy_sum += occupancy
        self.stats.occupancy_hist[min(3, int(occupancy * 4))] += 1
        # latency telemetry: TWO O(1) observes per dispatch — inter-token
        # latency is dispatch wall over tokens-per-row, never a per-token
        # loop (the hot-path allocation budget is zero)
        denom = tokens_per_row if tokens_per_row else clock_steps
        # capacity timeline (ISSUE 19): one numeric sample per dispatch
        # landing — every input is an O(1) attribute read or two
        # multiply-adds (the analytic HBM roofline), appended lock-free
        if self._capacity_on:
            self._sampler.append(
                self._ledger.pages_in_use,
                self._page_alloc.free_pages if self._paged else 0,
                self._ledger.prefix_resident_pages,
                rows,
                len(self._pending),
                float(denom) * rows,
                capacity.hbm_bytes_per_token(
                    self._hbm_constants, self._hbm_ctx, max(rows, 1)
                ),
            )
        self._observe("decode_dispatch_ms", elapsed * 1000.0)
        # the advert's many-router tiebreak signal (ISSUE 10 satellite):
        # one multiply-add per dispatch, folded here so both lanes and
        # the spec tick feed the same EWMA
        self.stats.note_dispatch_ewma(elapsed * 1000.0)
        self._observe("inter_token_ms", elapsed * 1000.0 / max(1.0, denom))
        self._update_active_gauge()
        self._sync_metric_counters()

    def _update_active_gauge(self) -> None:
        """The process gauge sums across live engines (last-writer-wins
        would let an idle engine zero out a busy one's count).  Called per
        dispatch AND per retirement — without the retirement update an
        idle engine would pin its final in-flight count forever.  The
        running check sits INSIDE the lock so stop()'s pop (which runs
        after _running flips) can never interleave between the check and
        the insert and leave a stale re-inserted entry."""
        with _ACTIVE_LOCK:
            if not self._running:
                return
            _ACTIVE_BY_ENGINE[id(self)] = len(self._active)
            total = sum(_ACTIVE_BY_ENGINE.values())
        self.metrics["active_requests"].set(total)

    def _observe(self, key: str, value: float) -> None:
        """One latency observation, recorded twice (both O(1)): the
        process-shared instrument feeds the /metrics exposition, the
        per-engine one feeds this engine's advert percentiles."""
        self.metrics[key].observe(value)
        self.latency[key].observe(value)

    def _sync_metric_counters(self) -> None:
        """Fold cumulative stats into the process-registry counters as
        increments (called per dispatch + at snapshot time; at most one
        dispatch of lag, O(1) work).  Locked: the decode thread (via
        _note_dispatch) and the event-loop heartbeat (via stats_snapshot)
        both run this — an unlocked read-inc-write would double-count."""
        m, counted, stats = self.metrics, self._counted, self.stats
        with self._counted_lock:
            for key in ("decode_tokens", "prefill_tokens", "spec_proposed",
                        "spec_accepted", "overlap_wasted_tokens"):
                value = getattr(stats, key)
                if value != counted[key]:
                    m[key].inc(value - counted[key])
                    counted[key] = value

    @hotpath
    def _spec_decode_tick(self) -> None:
        """One speculative wave: draft up to k tokens per active request
        (host-side n-gram lookup or the draft model), verify all of them
        plus the next position in ONE target dispatch, emit each row's
        accepted prefix + correction token.  Replaces ``_decode_tick``
        when ``RuntimeConfig.speculative`` is set; everything downstream
        (fan-out batching, deferred frees) is shared.

        Speculation stays LOCKSTEP even when ``overlap_dispatch`` is on:
        the host-side drafter needs the landed tokens of dispatch N to
        propose for N+1, so there is nothing correct to pre-launch.  The
        per-row retirement authority still moves to the device (the
        verify jit returns n_valid/done via the same
        ``sampler.retire_mask_slots``), keeping one classification code
        path across both modes.
        """
        spec = self._spec
        B = self.runtime.max_batch_size
        active_mask = np.zeros((B,), bool)
        max_len = 1
        for slot in self._active:
            active_mask[slot] = True
            max_len = max(max_len, int(self._host_lens[slot]))
        window = self._window_bucket(max_len)
        # wave-width ceiling: k drafts + 1 correction, shrunk so no row's
        # chunk can write past max_seq (a clamped dynamic_update_slice
        # would slide BACKWARD over valid history — unlike the dense
        # decode ring, where overshoot only ever lands beyond a retiring
        # row's valid length)
        cap = max(1, min(spec.k + 1, self.runtime.max_seq_len - max_len))
        # draft FIRST, then size the wave to the longest actual proposal:
        # ticks where the drafter finds nothing dispatch a 1-wide verify
        # (a plain decode step), not a k+1-wide one
        proposals: dict[int, list[int]] = {}
        max_nd = 0
        if cap > 1:
            entries = [
                (slot, request.history)
                for slot, request in self._active.items()
            ]
            for (slot, _), proposal in zip(
                entries, self._drafter.propose(entries)
            ):
                proposal = proposal[: cap - 1]
                proposals[slot] = proposal
                max_nd = max(max_nd, len(proposal))
        S = min(cap, max_nd + 1)
        drafts = np.zeros((B, S - 1), np.int32)
        ndraft = np.zeros((B,), np.int32)
        for slot, proposal in proposals.items():
            drafts[slot, : len(proposal)] = proposal
            ndraft[slot] = len(proposal)
        sampled = any(
            not self._effective_sampling(r).is_greedy
            for r in self._active.values()
        )
        self._observe_gap()  # just before enqueue: drafting is prep too
        self._journal.append(
            flightrec.EV_DISPATCH_LAUNCH, None, -1, S, len(self._active)
        )
        started = time.perf_counter()
        args = [self.params, self._k, self._v]
        if self._paged:
            args.append(self._tables)
        args += [
            self._last,
            self._lens,
            jnp.asarray(active_mask),
            jnp.asarray(drafts),
            jnp.asarray(ndraft),
            *self._retire_args(),
            self._slot_keys,
            self._temp,
            self._top_k,
            self._top_p,
        ]
        (
            self._k, self._v, self._last, self._lens, out_toks, emitted,
            n_valid, done,
        ) = self._verify_jit(window, S, sampled)(*args)
        out_toks, emitted, n_valid, done = self._sync_host(
            (out_toks, emitted, n_valid, done)
        )  # [B, S] + retirement arrays — THE host sync
        elapsed = time.perf_counter() - started
        self._last_sync_t = time.perf_counter()
        # clock: one verify forward ≈ one decode step of wall time; the
        # heap horizon only drives the non-spec short-dispatch lever, so
        # a coarse clock is fine here.  Inter-token latency, however, must
        # divide by what each row actually EMITTED (accepted prefix +
        # correction), or acceptance would inflate the reported latency.
        n_active = len(self._active)
        self._note_dispatch(
            elapsed, 1,
            tokens_per_row=float(emitted.sum()) / n_active if n_active else 1.0,
        )
        # spec stays lockstep, so the verify sync IS the landing: one
        # event carries the wave's draft offer vs what actually emitted
        self._journal.append(
            flightrec.EV_SPEC_TICK, None, -1, int(ndraft.sum()),
            int(emitted.sum()),
        )
        deliveries: list[tuple[asyncio.Queue, list]] = []
        for slot, request in list(self._active.items()):
            count = int(emitted[slot])
            self._host_lens[slot] += count
            self.stats.spec_proposed += int(ndraft[slot])
            self.stats.spec_accepted += count - 1
            self.stats.spec_emitted += count
            self.stats.spec_rows += 1
            # device retirement authority: deliver the classified prefix,
            # retire on the device-computed done flag (same math as
            # _record_token's loop, computed once on device)
            valid = int(n_valid[slot])
            items: list = out_toks[slot, :valid].tolist()
            if request.history is not None:
                request.history.extend(items)
            request.generated += valid
            self.stats.decode_tokens += valid
            if done[slot]:
                self._retire_slot(request)
                items.append(_DONE)
            if items:
                deliveries.append((request.out, items))
        if not self._active:
            self._last_sync_t = None
        if deliveries:
            self._loop.call_soon_threadsafe(_deliver_batch, deliveries)

    def _retire_slot(self, request: GenRequest) -> None:
        """Reclaim a short-lane request's slot + page reservation and drop
        the retire-heap's reference.  Bookkeeping runs BEFORE any _DONE
        signal reaches the consumer: once completion is observable, the
        slot is already free (no window where a finished request still
        occupies ``_active``).

        Overlap: when a launched-but-not-landed dispatch still covers this
        slot, the RESOURCE frees (page reservation, shared-page refcounts,
        the free-list slot) defer to that dispatch's landing — an in-flight
        dispatch must never find its pages re-allocated under it, nor its
        shared prefix pages evicted while it still reads them.  Everything
        observable (``_active``, the retire heap, the gauge) updates now."""
        self._drop_deadline(request)
        self._drop_lease(request)
        self._active.pop(request.slot, None)
        if self._drafter is not None and request.slot != -1:
            self._drafter.retire(request.slot)
        pend = self._pend
        if pend is not None and request.slot in pend["slot_set"]:
            # one-dispatch-late retirement: observable state updates now,
            # resource frees ride to the in-flight dispatch's landing —
            # the journal records BOTH moments (RETIRE_DEFER here, the
            # slot/page frees in _free_deferred)
            self._journal.append(
                flightrec.EV_RETIRE_DEFER, request.corr, request.slot,
                request.generated,
            )
            # the deferred tuple carries the OWNER (corr): the landing's
            # frees must attribute to the request whose pages they are,
            # in the journal and the capacity ledger alike (ISSUE 19)
            pend["deferred"].append(
                (request.slot, request.shared_pages, request.corr)
            )
            request.shared_pages = []
            request.slot = -1
            self._untrack_retirement(request)
            self._update_active_gauge()
            return
        self._journal.append(
            flightrec.EV_RETIRE, request.corr, request.slot, request.generated
        )
        if self._paged:
            if self._prefix is not None and request.shared_pages:
                # shared pages return to the CACHE (refcount), never to
                # the free list while other readers may hold them
                self._journal.append(
                    flightrec.EV_PREFIX_REL, request.corr, request.slot,
                    len(request.shared_pages),
                )
                self._prefix.release(request.shared_pages)
                self._ledger.release(request.shared_pages)
                request.shared_pages = []
            self._journal.append(
                flightrec.EV_PAGE_FREE, request.corr, request.slot
            )
            self._page_alloc.free(request.slot)
            self._ledger.free(request.slot)
        self._free.append(request.slot)
        self._journal.append(
            flightrec.EV_SLOT_FREE, request.corr, request.slot
        )
        request.slot = -1
        self._untrack_retirement(request)
        self._update_active_gauge()

    def _record_token(
        self, request: GenRequest, token: int, items: list, *,
        long: bool = False,
    ) -> bool:
        """THE retirement authority (VERDICT r3 weak #3: this logic used to
        live in three divergent copies).  Every generated token — prefill
        first token, short-lane decode fan-out slow path, long lane — flows
        through here: bump ``generated``, classify stop/exhaustion, reclaim
        the slot on retirement.  Appends deliverable tokens (and the _DONE
        sentinel) to ``items``; the caller owns marshalling ``items`` to
        the event loop.  Returns True when the request retired."""
        request.generated += 1
        hit_stop = token in request.stop_tokens
        if not hit_stop:
            items.append(token)
            self.stats.decode_tokens += 1
            if request.history is not None:  # speculation: drafter context
                request.history.append(token)
        if long:
            # the long lane has no slot and its sequence room is the
            # statically-sized fresh cache, enforced by long_new_cap
            done = hit_stop or request.generated >= request.max_new_tokens
            if done:
                # the short lane's RETIRE rides _retire_slot; the long
                # lane holds no slot, so its retirement is recorded here
                self._journal.append(
                    flightrec.EV_RETIRE, request.corr, -1, request.generated
                )
        else:
            # exhaustion == the retire heap's bound formula reaching zero
            # (one authority: heap prediction and actual retirement agree)
            done = hit_stop or self._retirement_bound(request) <= 0
            if done:
                self._retire_slot(request)
        if done:
            items.append(_DONE)
        return done
