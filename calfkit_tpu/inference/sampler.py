"""Token sampling: greedy / temperature / top-k / top-p, jit-compatible.

All branching on sampling *mode* happens in Python at trace time (the engine
jits one specialization per settings bundle); everything under jit is static
shape, data-parallel over the batch.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → off
    top_p: float = 1.0  # 1 → off


def sample(
    logits: jax.Array,  # [B, V] (last-token logits)
    key: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """→ [B] int32 next tokens."""
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / params.temperature
    if params.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -params.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # smallest set of tokens whose mass ≥ top_p: keep while cum-prev < p
        keep_sorted = (cumulative - probs) < params.top_p
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
