"""Token sampling: greedy / temperature / top-k / top-p, jit-compatible.

Two entry points:

- :func:`sample` — one static ``SamplingParams`` bundle for the whole batch
  (trace-time branching; the cheap path for uniform workloads);
- :func:`sample_slots` — **per-row** temperature/top_k/top_p/key tensors, so
  one continuous-batching decode dispatch serves requests with different
  settings without fragmenting the batch into per-settings jit variants.
  Everything is static-shape; row-wise knobs are data.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → off
    top_p: float = 1.0  # 1 → off

    @property
    def is_greedy(self) -> bool:
        return self.temperature <= 0.0


def sample(
    logits: jax.Array,  # [B, V] (last-token logits)
    key: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """→ [B] int32 next tokens."""
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / params.temperature
    if params.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -params.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # smallest set of tokens whose mass ≥ top_p: keep while cum-prev < p
        keep_sorted = (cumulative - probs) < params.top_p
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_slots(
    logits: jax.Array,  # [B, V] (last-token logits)
    keys: jax.Array,  # [B] stacked typed PRNG keys (one stream per slot)
    temperature: jax.Array,  # [B] f32; <= 0 → greedy for that row
    top_k: jax.Array,  # [B] i32; 0 → off
    top_p: jax.Array,  # [B] f32; >= 1 → off
) -> jax.Array:
    """Per-row sampling → [B] int32 next tokens.

    One descending sort serves both top-k (rank cutoff) and top-p (nucleus
    mass cutoff); rows with filtering off use rank < V / mass < 1 which keep
    everything.  Greedy rows bypass the categorical draw via a final where.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / safe_temp
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
    k_eff = jnp.where(top_k > 0, top_k, V)[:, None]
    keep = ranks < k_eff
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    keep &= (cumulative - probs) < jnp.minimum(top_p, 1.0)[:, None]
    keep |= ranks == 0  # never filter out every token
    threshold = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    filtered = jnp.where(scaled < threshold, -jnp.inf, scaled)
    drawn = jax.vmap(
        lambda k, row: jax.random.categorical(k, row, axis=-1)
    )(keys, filtered).astype(jnp.int32)
    return jnp.where(temperature > 0.0, drawn, greedy)
