"""Token sampling: greedy / temperature / top-k / top-p, jit-compatible.

Two entry points:

- :func:`sample` — one static ``SamplingParams`` bundle for the whole batch
  (trace-time branching; the cheap path for uniform workloads);
- :func:`sample_slots` — **per-row** temperature/top_k/top_p/key tensors, so
  one continuous-batching decode dispatch serves requests with different
  settings without fragmenting the batch into per-settings jit variants.
  Everything is static-shape; row-wise knobs are data.

Speculative decoding adds :func:`spec_accept_slots` — ragged acceptance of
k drafted tokens per row against the verify dispatch's k+1 logit rows:
exact greedy match for greedy rows, rejection sampling (point-mass
proposals) for sampled rows, both against the SAME filtered target
distribution :func:`filtered_logits` defines.

Overlapped execution adds :func:`retire_mask_slots` — device-side
stop-token and generation-bound classification of a freshly generated
token block, so the engine can launch the NEXT decode dispatch before the
host ever sees this one's tokens (the done mask feeds the next dispatch's
row masking without a host round-trip).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → off
    top_p: float = 1.0  # 1 → off

    @property
    def is_greedy(self) -> bool:
        return self.temperature <= 0.0


def sample(
    logits: jax.Array,  # [B, V] (last-token logits)
    key: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """→ [B] int32 next tokens."""
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / params.temperature
    if params.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -params.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # smallest set of tokens whose mass ≥ top_p: keep while cum-prev < p
        keep_sorted = (cumulative - probs) < params.top_p
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def filtered_logits(
    logits: jax.Array,  # [B, V]
    temperature: jax.Array,  # [B] f32
    top_k: jax.Array,  # [B] i32; 0 → off
    top_p: jax.Array,  # [B] f32; >= 1 → off
) -> jax.Array:
    """Temperature-scaled logits with top-k/top-p support filtering applied
    (-inf outside the kept set) → [B, V] f32.

    THE definition of the target distribution: ``sample_slots`` draws from
    it directly, and speculative verification (``spec_accept_slots``) must
    accept/resample against the exact same filtered distribution or sampled
    speculative output would drift off the non-speculative distribution.

    One descending sort serves both top-k (rank cutoff) and top-p (nucleus
    mass cutoff); rows with filtering off use rank < V / mass < 1 which keep
    everything.
    """
    V = logits.shape[-1]
    safe_temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / safe_temp
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
    k_eff = jnp.where(top_k > 0, top_k, V)[:, None]
    keep = ranks < k_eff
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    keep &= (cumulative - probs) < jnp.minimum(top_p, 1.0)[:, None]
    keep |= ranks == 0  # never filter out every token
    threshold = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(scaled < threshold, -jnp.inf, scaled)


def sample_slots(
    logits: jax.Array,  # [B, V] (last-token logits)
    keys: jax.Array,  # [B] stacked typed PRNG keys (one stream per slot)
    temperature: jax.Array,  # [B] f32; <= 0 → greedy for that row
    top_k: jax.Array,  # [B] i32; 0 → off
    top_p: jax.Array,  # [B] f32; >= 1 → off
) -> jax.Array:
    """Per-row sampling → [B] int32 next tokens.

    Greedy rows bypass the categorical draw via a final where.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filtered = filtered_logits(logits, temperature, top_k, top_p)
    drawn = jax.vmap(
        lambda k, row: jax.random.categorical(k, row, axis=-1)
    )(keys, filtered).astype(jnp.int32)
    return jnp.where(temperature > 0.0, drawn, greedy)


def retire_mask_slots(
    toks: jax.Array,  # [B, S] the dispatch's generated tokens, row-major
    stop_table: jax.Array,  # [B, n_stop] i32 per-row stop tokens, -1 padded
    bound: jax.Array,  # [B] i32 steps until the row's hard bound (pre-dispatch)
    active: jax.Array,  # [B] bool rows that actually participated
    emitted: "jax.Array | None" = None,  # [B] valid tokens per row (None → S)
) -> tuple[jax.Array, jax.Array]:
    """Per-row retirement classification → (n_valid [B] i32, done [B] bool).

    THE device-side mirror of the engine's host retirement authority
    (``_record_token``): walk each row's token block, deliver tokens up to
    the first stop token (exclusive) or the hard generation bound
    (max_new_tokens / sequence room), whichever comes first.  ``n_valid``
    is how many of the row's tokens the host should deliver; ``done`` is
    whether the row retired inside this block.

    Computing this ON DEVICE is what makes double-buffered dispatch safe:
    the done mask of dispatch N feeds dispatch N+1's row masking as plain
    device dataflow, so N+1 can launch before any host sync of N — a
    retiring row is frozen out of N+1 without the host in the loop.

    ``emitted`` ragged-limits the scan for speculative verify blocks
    (positions past a row's emitted count are padding, and padding zeros
    must never match a stop token).  Inactive rows report (0, False): a
    done mask must never leak onto a slot the host has since re-admitted.
    """
    B, S = toks.shape
    limit = (
        jnp.full((B,), S, jnp.int32) if emitted is None
        else emitted.astype(jnp.int32)
    )
    pos = jnp.arange(S, dtype=jnp.int32)[None, :]
    within = pos < limit[:, None]
    is_stop = (toks[:, :, None] == stop_table[:, None, :]).any(-1) & within
    stop_any = is_stop.any(axis=1)
    first_stop = jnp.argmax(is_stop, axis=1).astype(jnp.int32)
    n_before = jnp.where(stop_any, first_stop, limit)
    bound = jnp.maximum(bound, 0)
    n_valid = jnp.minimum(n_before, bound)
    done = stop_any | (bound <= limit)
    return jnp.where(active, n_valid, 0), done & active


def spec_accept_slots(
    logits: jax.Array,  # [B, S, V] verify logits (S = k_spec + 1)
    drafts: jax.Array,  # [B, S-1] i32 drafted candidate tokens
    ndraft: jax.Array,  # [B] i32 valid drafts per row (0..S-1)
    base_lens: jax.Array,  # [B] kv length at dispatch start
    keys: jax.Array,  # [B] per-slot PRNG keys
    temperature: jax.Array,  # [B] f32; <= 0 → greedy (exact-match) rows
    top_k: jax.Array,  # [B] i32
    top_p: jax.Array,  # [B] f32
    *,
    sampled: bool = True,  # static: False → all-greedy batch, no RNG work
) -> tuple[jax.Array, jax.Array]:
    """Ragged speculative acceptance → (out_tokens [B, S], emitted [B]).

    Per row: ``logits[:, j]`` is the target model's distribution for the
    token AFTER fed token j (fed tokens are [last, d_0, .., d_{S-2}]).
    Accept the longest prefix of drafts, then emit ONE correction/bonus
    token at the first rejected (or first undrafted) position — so
    ``emitted = accepted + 1`` and ``out_tokens[b, :emitted[b]]`` are the
    row's new tokens, in order.

    - Greedy rows (temperature <= 0): accept d_j iff it equals
      argmax(logits[:, j]); the correction IS the argmax — output is
      token-exact vs non-speculative greedy decode.
    - Sampled rows: standard rejection sampling against the SAME filtered
      distribution ``sample_slots`` uses.  Drafters propose
      deterministically (point-mass q), so d_j is accepted with
      probability p(d_j) and a rejection resamples from the residual
      p with d_j's mass removed — the emitted marginal is exactly p.
      Each position folds the slot key with its absolute token index
      (``base_lens + 1 + j``), the same per-(request, position) stream
      convention as the non-speculative decode path.
    """
    B, S, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
    j = jnp.arange(S - 1, dtype=jnp.int32)[None, :]
    drafted = j < ndraft[:, None]  # [B, S-1]
    if not sampled:
        # all-greedy batch: acceptance is exact match, the correction IS
        # the argmax — no filtering, keys, or categorical draws traced
        acc = (drafts == greedy[:, : S - 1]) & drafted
        corr = greedy
        return _assemble(drafts, acc, corr, B, S)
    flat = filtered_logits(
        logits.reshape(B * S, V),
        jnp.repeat(temperature, S),
        jnp.repeat(top_k, S),
        jnp.repeat(top_p, S),
    ).reshape(B, S, V)
    probs = jax.nn.softmax(flat, axis=-1)  # [B, S, V]

    # per-(row, position) streams: fold the slot key with the absolute
    # index the emitted token would occupy, then split acceptance vs
    # resample randomness off that stream
    pos = base_lens[:, None] + 1 + jnp.arange(S)[None, :]  # [B, S]
    pos_keys = jax.vmap(
        lambda key, row: jax.vmap(lambda p: jax.random.fold_in(key, p))(row)
    )(keys, pos)  # [B, S] keys
    split = jax.vmap(jax.vmap(lambda k: jax.random.split(k, 2)))(pos_keys)
    u = jax.vmap(jax.vmap(lambda k: jax.random.uniform(k)))(
        split[:, :, 0]
    )  # [B, S] acceptance draws
    resample_keys = split[:, :, 1]

    p_draft = jnp.take_along_axis(
        probs[:, : S - 1], drafts[..., None], axis=-1
    )[..., 0]  # [B, S-1]
    acc_sampled = u[:, : S - 1] < p_draft
    acc_greedy = drafts == greedy[:, : S - 1]
    acc = (
        jnp.where(temperature[:, None] > 0.0, acc_sampled, acc_greedy)
        & drafted
    )

    # correction token per position: a REJECTED drafted position resamples
    # from the residual (p with the draft's mass removed — q is a point
    # mass, so residual ∝ p excluding d); an undrafted position draws
    # plainly from p (this covers the bonus token after full acceptance)
    onehot = jax.nn.one_hot(drafts, V, dtype=bool)  # [B, S-1, V]
    residual = jnp.where(onehot, -jnp.inf, flat[:, : S - 1])
    draw = jax.vmap(jax.vmap(jax.random.categorical))
    corr_residual = draw(resample_keys[:, : S - 1], residual).astype(jnp.int32)
    corr_plain = draw(resample_keys, flat).astype(jnp.int32)  # [B, S]
    corr_sampled = jnp.concatenate(
        [
            jnp.where(drafted, corr_residual, corr_plain[:, : S - 1]),
            corr_plain[:, S - 1 :],
        ],
        axis=-1,
    )  # [B, S]
    corr = jnp.where(temperature[:, None] > 0.0, corr_sampled, greedy)
    return _assemble(drafts, acc, corr, B, S)


def _assemble(
    drafts: jax.Array,  # [B, S-1]
    acc: jax.Array,  # [B, S-1] bool per-position acceptance
    corr: jax.Array,  # [B, S] correction/bonus token per position
    B: int,
    S: int,
) -> tuple[jax.Array, jax.Array]:
    """(out_tokens [B, S], emitted [B]): the leading accepted draft prefix
    followed by ONE correction token at the first non-accepted position."""
    prefix = jnp.cumprod(acc.astype(jnp.int32), axis=-1)
    accepted = jnp.sum(prefix, axis=-1).astype(jnp.int32)  # [B] 0..S-1
    i = jnp.arange(S, dtype=jnp.int32)[None, :]
    pad_drafts = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=-1
    )
    out_tokens = jnp.where(
        i < accepted[:, None],
        pad_drafts,
        jnp.where(i == accepted[:, None], corr, 0),
    ).astype(jnp.int32)
    return out_tokens, accepted + 1
