"""JaxLocalModelClient — the ModelClient that replaces remote HTTPS APIs.

This is the seam swap (reference: SURVEY.md §3.3 "THE SEAM THE TPU BACKEND
REPLACES"): `Agent(model=JaxLocalModelClient(...))` and every model turn runs
on the local device mesh through the continuous-batching engine.

Message rendering uses the HF chat template when a checkpoint tokenizer is
available, else a deterministic plain template.  Tool calling rides a JSON
grammar: the model is instructed to emit ``{"tool_name": ..., "args": ...}``
objects; responses are scanned for them (configurable via
``tool_call_parser``).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, AsyncIterator, Callable

from calfkit_tpu.engine.model_client import (
    ModelClient,
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    ResumeOffset,
    StreamEvent,
    TextDelta,
)
from calfkit_tpu.exceptions import InferenceError
from calfkit_tpu.models.capability import ToolDef
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    Usage,
    UserPart,
)
from calfkit_tpu.models.payload import render_parts_as_text
from calfkit_tpu.observability import capacity as _capacity

ToolCallParser = Callable[[str], tuple[str, list[ToolCallOutput]]]

def default_tool_call_parser(text: str) -> tuple[str, list[ToolCallOutput]]:
    """Extract ``{"tool_name": ..., "args": {...}}`` objects (arbitrarily
    nested args) from the text; returns (remaining_text, calls)."""
    decoder = json.JSONDecoder()
    calls: list[ToolCallOutput] = []
    kept: list[str] = []
    i = 0
    while i < len(text):
        start = text.find("{", i)
        if start == -1:
            kept.append(text[i:])
            break
        obj = None
        try:
            obj, consumed = decoder.raw_decode(text, start)
        except ValueError:
            pass
        if isinstance(obj, dict) and isinstance(obj.get("tool_name"), str):
            args = obj.get("args", {})
            calls.append(
                ToolCallOutput(
                    tool_call_id=f"local_{int(time.time()*1000)}_{len(calls)}",
                    tool_name=obj["tool_name"],
                    args=args if isinstance(args, dict) else {},
                )
            )
            kept.append(text[i:start])
            i = consumed
        else:
            kept.append(text[i : start + 1])
            i = start + 1
    return "".join(kept).strip(), calls


def render_messages(
    messages: list[ModelMessage],
    params: ModelRequestParameters,
) -> str:
    """Deterministic chat rendering (the fallback template)."""
    lines: list[str] = []
    system: list[str] = []
    for message in messages:
        if isinstance(message, ModelRequest):
            if message.instructions:
                system.append(message.instructions)
            for part in message.parts:
                if isinstance(part, SystemPart):
                    system.append(part.content)
                elif isinstance(part, UserPart):
                    content = (
                        part.content
                        if isinstance(part.content, str)
                        else render_parts_as_text(part.content)
                    )
                    author = f" ({part.author})" if part.author else ""
                    lines.append(f"<|user|>{author}\n{content}")
                elif isinstance(part, ToolReturnPart):
                    lines.append(
                        f"<|tool_result|> {part.tool_name}: "
                        f"{json.dumps(part.content, default=str)}"
                    )
                elif isinstance(part, RetryPart):
                    lines.append(f"<|user|>\n[retry] {part.content}")
        else:  # ModelResponse
            text = message.text()
            calls = message.tool_calls()
            body = text
            for call in calls:
                args = call.args if isinstance(call.args, str) else json.dumps(call.args)
                body += f'\n{{"tool_name": "{call.tool_name}", "args": {args}}}'
            lines.append(f"<|assistant|>\n{body.strip()}")

    tools = params.all_tools()
    if tools:
        tool_block = "\n".join(
            f"- {t.name}: {t.description}\n  parameters: "
            f"{json.dumps(t.parameters_schema)}"
            for t in tools
        )
        system.append(
            "You can call tools by replying with a JSON object "
            '{"tool_name": "<name>", "args": {...}} on its own line.\n'
            f"Available tools:\n{tool_block}"
        )
    header = f"<|system|>\n{chr(10).join(system)}\n" if system else ""
    return header + "\n".join(lines) + "\n<|assistant|>\n"


class JaxLocalModelClient(ModelClient):
    """Local inference over a JAX device mesh.

    Construction is cheap; device work (param init / checkpoint load,
    engine start) happens on first request or explicit :meth:`start`.
    """

    def __init__(
        self,
        *,
        checkpoint: str | None = None,
        config: Any = None,  # ModelConfig | preset name | None (from ckpt)
        runtime: Any = None,  # RuntimeConfig
        tokenizer: Any = None,
        sampling: Any = None,
        engine: Any = None,  # pre-built InferenceEngine (tests)
        tool_call_parser: ToolCallParser = default_tool_call_parser,
        max_new_tokens: int = 512,
        seed: int = 0,
        draft_checkpoint: str | None = None,  # speculative draft weights
        draft_params: Any = None,
    ):
        self._checkpoint = checkpoint
        self._config_spec = config
        self._runtime = runtime
        self._tokenizer = tokenizer
        self._sampling = sampling
        self._engine = engine
        self._parser = tool_call_parser
        self._max_new_tokens = max_new_tokens
        self._seed = seed
        self._draft_checkpoint = draft_checkpoint
        self._draft_params = draft_params
        self._start_lock: asyncio.Lock | None = None

    @property
    def model_name(self) -> str:
        if self._engine is not None:
            return self._engine.config.name
        if isinstance(self._config_spec, str):
            return self._config_spec
        if self._config_spec is not None:
            return self._config_spec.name
        return self._checkpoint or "jax-local"

    # ------------------------------------------------------------- startup
    async def start(self) -> None:
        def ready() -> bool:
            return (
                self._engine is not None
                and getattr(self._engine, "_running", False)
                and self._tokenizer is not None
            )

        if ready():
            return
        if self._start_lock is None:
            self._start_lock = asyncio.Lock()
        async with self._start_lock:
            if ready():
                return
            if self._engine is None:
                self._engine = await asyncio.to_thread(self._build_engine)
            await self._engine.start()
            if self._tokenizer is None:
                self._tokenizer = self._default_tokenizer()

    def _build_engine(self) -> Any:
        from calfkit_tpu.inference.config import ModelConfig, RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine
        from calfkit_tpu.inference.sharding import make_mesh, param_shardings

        runtime = self._runtime or RuntimeConfig()
        draft_params = self._draft_params
        if self._draft_checkpoint is not None and draft_params is None:
            if runtime.speculative is None or runtime.speculative.draft is None:
                # same loudness as the engine's draft_params validation: a
                # draft checkpoint that silently never loads would leave
                # the user speculating on the wrong drafter
                raise InferenceError(
                    "draft_checkpoint given but RuntimeConfig.speculative"
                    ".draft is unset — set SpecConfig(draft=<ModelConfig>)"
                )
            # the draft model loads through the SAME loader/sharding path
            # as the target (its own, smaller, config)
            from calfkit_tpu.inference.loader import load_params as _load

            draft_cfg = runtime.speculative.draft
            draft_params = _load(
                self._draft_checkpoint,
                draft_cfg,
                param_shardings(
                    draft_cfg, make_mesh(tp=runtime.tp, dp=runtime.dp)
                ),
            )
        params = None
        if self._checkpoint is not None:
            from calfkit_tpu.inference.loader import config_from_hf, load_params
            from calfkit_tpu.inference.tokenizer import HFTokenizer

            config = config_from_hf(self._checkpoint)
            mesh = make_mesh(tp=runtime.tp, dp=runtime.dp)
            shardings = param_shardings(config, mesh)
            if runtime.quantization in ("int8", "int4"):
                from calfkit_tpu.inference.quant import quantize_shardings

                shardings = quantize_shardings(
                    shardings,
                    bits=8 if runtime.quantization == "int8" else 4,
                )
            params = load_params(
                self._checkpoint,
                config,
                shardings,
                quantize=runtime.quantization,
            )
            if self._tokenizer is None:
                self._tokenizer = HFTokenizer(self._checkpoint)
            return InferenceEngine(
                config, runtime, params=params, mesh=mesh,
                sampling=self._sampling, seed=self._seed,
                draft_params=draft_params,
            )
        if isinstance(self._config_spec, str):
            config = preset(self._config_spec)
        elif self._config_spec is not None:
            config = self._config_spec
        else:
            raise InferenceError(
                "JaxLocalModelClient needs a checkpoint path or a config"
            )
        return InferenceEngine(
            config, runtime, sampling=self._sampling, seed=self._seed,
            draft_params=draft_params,
        )

    def _default_tokenizer(self) -> Any:
        from calfkit_tpu.inference.tokenizer import ByteTokenizer

        return ByteTokenizer()

    async def stop(self) -> None:
        if self._engine is not None:
            await self._engine.stop()

    def ready(self) -> "tuple[bool, str]":
        """Readiness probe for ``MetricsServer.set_readiness``: True only
        once the engine is BUILT (weights placed) and its scheduler task
        is running — distinct from liveness (``/healthz``), which is true
        from process start.  Cheap enough to call per scrape."""
        engine = self._engine
        if engine is None:
            return False, "engine not built (weights not loaded)"
        if not getattr(engine, "_running", False):
            return False, "engine not started"
        if getattr(engine, "_wedged", False):
            # the dispatch-progress watchdog tripped (ISSUE 9): the engine
            # is alive but the device grant is hung — /readyz flips false
            # and the heartbeat advert follows, so routers place nothing
            # new here and outstanding placements are declared dead
            return False, (
                "engine wedged: no dispatch progress for "
                f"{engine.runtime.watchdog_stall_s:.1f}s with work pending"
            )
        return True, "engine running"

    def stats_snapshot(self, *, window: bool = False) -> dict:
        """Live serving metrics (for the control-plane engine-stats advert);
        safe before start (zeros) — construction is intentionally cheap.

        ``window=True`` additionally reports per-interval rates since the
        PREVIOUS window=True call (``EngineStats.snapshot_and_delta`` —
        single-consumer by design: the heartbeat advert passes it; ad-hoc
        pollers must not, or they steal the heartbeat's intervals)."""
        engine = self._engine
        if engine is None:
            # engine builds lazily on first request: report the CONFIGURED
            # shape — with the SAME key set as the live branch (zeros for
            # the counters) so control-plane consumers never KeyError on a
            # cold engine
            from calfkit_tpu.inference.config import RuntimeConfig

            runtime = self._runtime or RuntimeConfig()  # mirror _build_engine
            return {
                "model_name": self.model_name,
                "platform": "",
                "tokens_per_second": 0.0,
                "mean_occupancy": 0.0,
                "active_requests": 0,
                "pending_requests": 0,
                "free_slots": runtime.max_batch_size,
                "max_batch_size": runtime.max_batch_size,
                "kv_layout": runtime.kv_layout,
                "prefill_tokens": 0,
                "decode_tokens": 0,
                "decode_dispatches": 0,
                "overlap_dispatch": runtime.overlap_dispatch,
                "overlap_wasted_tokens": 0,
                # ragged unified waves: the EFFECTIVE setting (the flag
                # engages only with chunked prefill + overlap dispatch)
                "ragged_waves": bool(
                    runtime.ragged_waves and runtime.chunked_prefill
                    and runtime.overlap_dispatch
                ),
                "prefill_absorbed_tokens": 0,
                "unified_dispatches": 0,
                "tokens_per_dispatch": 0.0,
                # overload protection: same key set as the live branch
                "max_pending": runtime.max_pending,
                "shed_requests": 0,
                "expired_requests": 0,
                # multi-tenant QoS (ISSUE 20): per-class splits of the
                # shed/expired counters plus per-class queued depth — the
                # routing tiebreak and `ck stats` per-class columns; same
                # key set as the live branch
                "interactive_shed": 0,
                "batch_shed": 0,
                "interactive_expired": 0,
                "batch_expired": 0,
                "interactive_pending": 0,
                "batch_pending": 0,
                "cancelled_requests": 0,
                "cancel_propagated": 0,
                "delivery_stalled": 0,
                # caller liveness (ISSUE 10) + router tiebreak: same key
                # set as the live branch
                "orphaned_requests": 0,
                "dispatch_ewma_ms": 0.0,
                # wedge watchdog (ISSUE 9): same key set as the live branch
                "wedged": False,
                "watchdog_trips": 0,
                "watchdog_faulted": 0,
                "flightrec": {"appended": 0, "dropped": 0, "dumped": 0},
                # capacity observatory (ISSUE 19): same key set as the
                # live branch — the CONFIGURED pool shape, zero occupancy
                "pages_total": (
                    runtime.pool_pages() - 1
                    if runtime.kv_layout == "paged"
                    else 0
                ),
                "pages_in_use": 0,
                "prefix_resident_pages": 0,
                "evictions_window": 0,
                "alloc_stalls": 0,
                "capacity": _capacity.PageLedger(
                    runtime.pool_pages() - 1
                    if runtime.kv_layout == "paged"
                    else 0
                ).breakdown(),
                "capacity_samples": {
                    "appended": 0, "dropped": 0, "dumped": 0,
                },
            }
        import jax

        stats = engine.stats
        rt = engine.runtime
        # multi-tenant QoS (ISSUE 20): per-class QUEUED depth for the
        # advert (cancelled entries excluded — a flagged shed victim
        # still sits in the deque until reaped, and advertising it as
        # depth would double-penalize the replica that just made room)
        queued = [*engine._pending, *engine._carry, *engine._long_pending]
        interactive_pending = sum(
            1 for r in queued if not r.cancelled and r.priority != "batch"
        )
        batch_pending = sum(
            1 for r in queued if not r.cancelled and r.priority == "batch"
        )
        snapshot = {
            "model_name": engine.config.name,
            "platform": jax.devices()[0].platform,
            "tokens_per_second": round(stats.tokens_per_second, 1),
            "mean_occupancy": round(stats.mean_occupancy, 4),
            "active_requests": len(engine._active),
            # admitted but not yet holding a slot: active + pending is the
            # fleet router's queue-depth load signal (ISSUE 7)
            "pending_requests": (
                len(engine._pending) + len(engine._carry)
                + len(engine._long_pending)
            ),
            "free_slots": len(engine._free),
            "max_batch_size": rt.max_batch_size,
            "kv_layout": rt.kv_layout,
            "prefill_tokens": stats.prefill_tokens,
            "decode_tokens": stats.decode_tokens,
            "decode_dispatches": stats.decode_dispatches,
            # overlapped execution: whether double-buffered dispatch is on,
            # and the pad tokens one-dispatch-late retirement discarded
            "overlap_dispatch": rt.overlap_dispatch,
            "overlap_wasted_tokens": stats.overlap_wasted_tokens,
            # ragged unified waves (ISSUE 6): whether the fused
            # prefill+decode lane is live, the chunk tokens it absorbed
            # into decode dispatches, and tokens processed per dispatch
            # (decode + absorbed — the win is measured, not asserted)
            "ragged_waves": engine._ragged,
            "prefill_absorbed_tokens": stats.prefill_absorbed_tokens,
            "unified_dispatches": stats.unified_dispatches,
            "tokens_per_dispatch": round(stats.mean_tokens_per_dispatch, 3),
            # overload protection (ISSUE 5): admission sheds, deadline
            # expiries, reaped consumer cancels (mesh-propagated subset),
            # and max_out_blocks stall-cancels
            "max_pending": rt.max_pending,
            "shed_requests": stats.shed_requests,
            "expired_requests": stats.expired_requests,
            # multi-tenant QoS (ISSUE 20): per-class shed/expired splits
            # and the per-class queued depth computed above
            "interactive_shed": stats.interactive_shed,
            "batch_shed": stats.batch_shed,
            "interactive_expired": stats.interactive_expired,
            "batch_expired": stats.batch_expired,
            "interactive_pending": interactive_pending,
            "batch_pending": batch_pending,
            "cancelled_requests": stats.cancelled_requests,
            "cancel_propagated": stats.cancel_propagated,
            "delivery_stalled": stats.delivery_stalled,
            # caller liveness (ISSUE 10): runs reaped because their
            # caller's lease lapsed — the `ck stats` ORPHANS column
            "orphaned_requests": stats.orphaned_requests,
            # per-dispatch latency EWMA: the advert's many-router
            # tiebreak signal (PowerOfTwoChoices breaks depth ties on it)
            "dispatch_ewma_ms": round(stats.dispatch_ewma_ms, 3),
            # wedge watchdog (ISSUE 9): whether the dispatch-progress
            # watchdog currently declares the engine wedged (the advert's
            # ready flag follows it) plus its lifetime trip/fault counts
            "wedged": engine._wedged,
            "watchdog_trips": stats.watchdog_trips,
            "watchdog_faulted": stats.watchdog_faulted,
            # flight-recorder ring accounting: overflow (dropped) must be
            # an observable signal, never silent truncation
            "flightrec": engine._journal.counts(),
            # capacity observatory (ISSUE 19): the advert's headroom
            # scalars (top-level so **snapshot reaches EngineStatsRecord)
            # + the full by-owner/by-chain attribution breakdown and the
            # sampler's ring accounting.  evictions_window is refined to
            # the heartbeat interval below when window=True.
            "pages_total": engine._ledger.pages_total,
            "pages_in_use": engine._ledger.pages_in_use,
            "prefix_resident_pages": engine._ledger.prefix_resident_pages,
            "evictions_window": stats.prefix_evictions,
            "alloc_stalls": stats.alloc_stalls,
            "capacity": engine._ledger.breakdown(),
            "capacity_samples": engine._sampler.counts(),
        }
        try:
            # latency percentiles ride the advert for free: the registry's
            # fixed-bucket histograms already hold them.  Best-effort —
            # metrics must never fault the heartbeat.
            engine._sync_metric_counters()
            m = engine.latency  # per-ENGINE histograms: node-attributable
            snapshot["latency_ms"] = {
                name: round(m[hist].percentile(q), 3)
                for hist, label in (
                    ("ttft_ms", "ttft"),
                    ("inter_token_ms", "inter_token"),
                    ("queue_wait_ms", "queue_wait"),
                    ("prefill_ms", "prefill"),
                    ("dispatch_gap_ms", "dispatch_gap"),
                )
                for q, name in ((0.5, f"{label}_p50"), (0.99, f"{label}_p99"))
            }
            # per-interval rates since the previous heartbeat (the
            # windowing story for occupancy_hist + counters) — consumed
            # only when the single designated consumer asks
            if window:
                snapshot["window"] = engine.stats.snapshot_and_delta()[1]
                # the advert's eviction signal is PER-INTERVAL (lifetime
                # cumulative flattens toward the mean as uptime grows)
                snapshot["evictions_window"] = snapshot["window"].get(
                    "prefix_evictions", 0
                )
        except Exception:  # noqa: BLE001 - telemetry stays best-effort
            pass
        if rt.speculative is not None:
            snapshot["speculative"] = {
                "k": rt.speculative.k,
                "drafter": (
                    "draft-model" if rt.speculative.draft is not None
                    else "ngram"
                ),
                "spec_proposed": stats.spec_proposed,
                "spec_accepted": stats.spec_accepted,
                "acceptance_rate": round(stats.acceptance_rate, 4),
                "tokens_per_dispatch": round(stats.tokens_per_dispatch, 3),
            }
        if engine._paged:
            snapshot["free_pages"] = engine._page_alloc.free_pages
            if engine._prefix is not None:
                snapshot["prefix_cached_pages"] = engine._prefix.size
                snapshot["prefix_hits"] = stats.prefix_hits
                snapshot["prefix_reused_tokens"] = stats.prefix_reused_tokens
        try:  # accelerator memory pressure, where the backend reports it
            mem = jax.local_devices()[0].memory_stats() or {}
            if "bytes_in_use" in mem:
                snapshot["hbm_gb_in_use"] = round(
                    mem["bytes_in_use"] / 1e9, 3
                )
        except Exception:  # noqa: BLE001 - stats stay best-effort
            pass
        return snapshot

    # ------------------------------------------------------------- request
    async def request(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> ModelResponse:
        async for event in self.request_stream(messages, settings, params):
            if isinstance(event, ResponseDone):
                return event.response
        raise InferenceError("stream ended without a terminal response")

    async def request_stream(
        self,
        messages: list[ModelMessage],
        settings: ModelSettings | None = None,
        params: ModelRequestParameters | None = None,
    ) -> AsyncIterator[StreamEvent]:
        await self.start()
        params = params or ModelRequestParameters()
        settings = settings or ModelSettings()
        tokenizer = self._tokenizer
        prompt_text = render_messages(messages, params)
        prompt = [tokenizer.bos_id, *tokenizer.encode(prompt_text)]
        max_new = settings.max_tokens or self._max_new_tokens

        # decode-from-offset resume (ISSUE 10): the delivered prefix of a
        # failed-over stream enters as PREFILL — appended to the prompt,
        # so the survivor's prefix cache absorbs the shared prompt pages
        # and the chunk lane prefills only the continuation — and decode
        # produces ONLY the remaining budget.  The caller-side ledger
        # then dedupes nothing, because nothing is re-generated; under
        # greedy decode the continuation is byte-exact with an unkilled
        # run (round-trip tokenizers; BPE re-tokenization drift is
        # documented in docs/robustness.md).
        resume_tokens: list[int] = []
        prior = ""
        if settings.resume_text:
            resume_tokens = list(tokenizer.encode(settings.resume_text))
            prior = tokenizer.decode(resume_tokens)
            prompt = prompt + resume_tokens
            max_new = max(0, max_new - len(resume_tokens))

        def terminal(full_text: str, generated_tokens: int) -> ResponseDone:
            # ONE terminal builder for both exits (the resumed
            # spent-budget short-circuit below and the normal tail):
            # parser gating, parts assembly, and usage accounting must
            # not fork.  Resume usage semantics (documented in
            # docs/robustness.md): output_tokens counts what THIS
            # engine generated — a resumed run's delivered prefix is
            # input (it entered via prefill and was billed as output by
            # the attempt that generated it), so summing attempts never
            # double-counts the answer.
            remaining, calls = (
                self._parser(full_text)
                if params.tool_defs or params.output_tool
                else (full_text, [])
            )
            parts: list[Any] = []
            if remaining:
                parts.append(TextOutput(text=remaining))
            parts.extend(calls)
            return ResponseDone(
                ModelResponse(
                    parts=parts,
                    usage=Usage(
                        input_tokens=len(prompt),
                        output_tokens=generated_tokens,
                    ),
                    model_name=self.model_name,
                )
            )

        if settings.resume_text and max_new <= 0:
            # the delivered prefix already spent the whole token budget:
            # nothing to decode — the resumed stream is just its terminal
            yield ResumeOffset(len(prior))
            yield terminal(prior, 0)
            return

        # per-request sampling: each provided knob overrides that knob of
        # the engine default (top_p alone must NOT force greedy by zeroing
        # temperature); the engine batches mixed settings row-wise
        sampling = None
        if (
            settings.temperature is not None
            or settings.top_p is not None
            or settings.top_k is not None
        ):
            from calfkit_tpu.inference.sampler import SamplingParams

            base = self._engine.sampling
            temperature = (
                settings.temperature
                if settings.temperature is not None
                else base.temperature
            )
            if temperature <= 0.0 and settings.temperature is None and (
                settings.top_p is not None or settings.top_k is not None
            ):
                # filtering was requested but the default is greedy: sample
                # at T=1 so top_p/top_k actually apply
                temperature = 1.0
            sampling = SamplingParams(
                temperature=temperature,
                top_k=settings.top_k if settings.top_k is not None else base.top_k,
                top_p=settings.top_p if settings.top_p is not None else base.top_p,
            )
        stops = [s for s in settings.stop_sequences if s]
        # stop sequences cut host-side on decoded text; hold back enough of
        # the tail that a sequence spanning an emission boundary is never
        # already streamed when it completes
        holdback = max((len(s) for s in stops), default=1) - 1

        def first_stop(text: str) -> int:
            hits = [i for s in stops if (i := text.find(s)) != -1]
            return min(hits) if hits else -1

        # trace spans: the node kernel (or any caller) that set the trace
        # contextvar gets engine.generate with prefill/decode children;
        # untraced callers pay one contextvar read
        from calfkit_tpu.observability.trace import TRACER, current_context

        trace_parent = current_context.get()
        gen_span = prefill_span = decode_span = None
        if trace_parent is not None:
            gen_span = TRACER.start_span(
                "engine.generate",
                parent=trace_parent,
                kind="engine",
                emitter=f"engine/{self.model_name}",
                attrs={
                    "model": self.model_name,
                    "prompt_tokens": len(prompt),
                    "max_new_tokens": max_new,
                },
            )
            prefill_span = TRACER.start_span(
                "engine.prefill", parent=gen_span.context, kind="engine",
                emitter=gen_span.emitter,
            )

        started = time.perf_counter()
        generated: list[int] = []
        # a resumed stream's deltas begin past the already-delivered
        # prefix: everything before ``emitted`` chars is prefill, never
        # re-emitted (the ResumeOffset event tells consumers so)
        emitted = len(prior)
        stopped_at = -1
        ttft_ms = 0.0
        _EMIT_EVERY = 4  # re-decode cadence: bounds detokenize cost
        # the delivery's mesh deadline rides the same contextvar channel as
        # the trace: the node kernel set it from x-mesh-deadline, so the
        # engine enforces the caller's ABSOLUTE budget (reject expired at
        # admission, reap on expiry) with no per-layer arithmetic; the
        # caller's liveness lease (ISSUE 10) rides the identical channel
        # so the engine registers this run for the orphan reaper
        from calfkit_tpu import leases, qos
        from calfkit_tpu.cancellation import current_deadline

        if resume_tokens:
            yield ResumeOffset(len(prior))
        token_stream = self._engine.generate(
            prompt,
            max_new_tokens=max_new,
            stop_tokens=frozenset({tokenizer.eos_id}),
            sampling=sampling,
            seed=settings.seed,
            # the flight recorder joins on the same id the trace does, so
            # ``ck timeline <correlation-id>`` works from any log line
            corr=trace_parent.trace_id if trace_parent is not None else None,
            # run identity (ISSUE 19): the node kernel's x-mesh-run
            # contextvar, so the page ledger attributes HBM by run
            run=_capacity.current_run.get(),
            deadline=current_deadline.get(),
            lease=leases.current_lease.get(),
            # priority class (ISSUE 20): the node kernel's x-mesh-priority
            # contextvar — generate() resolves None/corrupt to the default
            # class via the one degradation law (qos.resolve_priority)
            priority=qos.current_priority.get(),
        )
        stream_exc: BaseException | None = None
        try:
            async for token in token_stream:
                generated.append(token)
                if len(generated) == 1:
                    # the first token IS the TTFT moment — right after
                    # prefill; the decode phase starts here
                    ttft_ms = (time.perf_counter() - started) * 1000.0
                    if prefill_span is not None:
                        prefill_span.end(ttft_ms=round(ttft_ms, 3))
                        prefill_span = None
                        decode_span = TRACER.start_span(
                            "engine.decode", parent=gen_span.context,
                            kind="engine", emitter=gen_span.emitter,
                        )
                # the first token is emitted immediately; later ones batch
                # on the re-decode cadence
                if len(generated) % _EMIT_EVERY and len(generated) != 1:
                    continue
                # emit only the prefix that can't change: a trailing
                # replacement char may be a multi-byte sequence completing
                # (resume: the full text includes the prefilled prefix so
                # stop sequences spanning the resume boundary still cut)
                text = tokenizer.decode(resume_tokens + generated).rstrip("�")
                if stops:
                    stopped_at = first_stop(text)
                    if stopped_at != -1:
                        break
                    text = text[: len(text) - holdback] if holdback else text
                if len(text) > emitted:
                    yield TextDelta(text[emitted:])
                    emitted = len(text)
        except BaseException as exc:
            # captured locally, NOT via sys.exc_info() in the finally:
            # exc_info also reports exceptions merely being HANDLED in an
            # enclosing frame (this generator's frames resume inside the
            # consumer's stack), which would mark clean streams as errors
            stream_exc = exc
            raise
        finally:
            # a break above abandons the stream; close NOW (not at GC) so
            # the engine reclaims the slot at its next tick
            await token_stream.aclose()
            # span status tells the truth about HOW the stream ended: an
            # in-flight exception (engine fault) is error, a consumer
            # abandoning the generator is cancelled, a break/return is ok
            status = (
                None if stream_exc is None
                else "cancelled"
                if isinstance(stream_exc, (GeneratorExit, asyncio.CancelledError))
                else "error"
            )
            if prefill_span is not None:  # zero tokens: no decode phase
                prefill_span.end(status=status)
            if decode_span is not None:
                decode_span.end(
                    status=status, generated_tokens=len(generated)
                )
            if gen_span is not None:
                gen_span.end(
                    status=status,
                    generated_tokens=len(generated),
                    ttft_ms=round(ttft_ms, 3),
                )
        elapsed = time.perf_counter() - started

        full_text = tokenizer.decode(resume_tokens + generated)
        if stops and stopped_at == -1:
            stopped_at = first_stop(full_text)
        if stopped_at != -1:
            full_text = full_text[:stopped_at]
        if len(full_text) > emitted:
            yield TextDelta(full_text[emitted:])  # flush the tail
        yield terminal(full_text, len(generated))
